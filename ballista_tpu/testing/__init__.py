"""Test-support machinery that ships with the engine.

``testing.faults`` is imported from production code paths (the fault
points are compiled in, inert by default), so this package is part of
the library proper — not of tests/.
"""
