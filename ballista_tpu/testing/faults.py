"""Deterministic fault injection: named, registered fault points.

The reference has "no fault injection" at all (SURVEY.md:336-343);
every recovery behavior in this engine was pinned only by hand-crafted
setups (killing work_dirs, fake statuses). This module turns each
failure-handling boundary into a NAMED fault point that tests — and
the chaos sweep — can arm from the environment:

    BALLISTA_FAULTS="shuffle.fetch=fail-every:3;client.rpc=delay:50"

Grammar: ``;``- or ``,``-separated ``point=trigger`` pairs, where
``trigger`` is one of

- ``fail-once[:K]``  — raise :class:`FaultInjected` on the Kth hit
  only (1-based, default 1);
- ``fail-every:N``   — raise on every Nth hit (N >= 1);
- ``delay:MS``       — sleep MS milliseconds on every hit;
- ``delay-once:MS``  — sleep MS milliseconds on the first hit only;
- ``drop[-once[:K]|-every:N]`` — for points with drop semantics (the
  data plane closes the connection without a response); points that
  cannot drop treat a triggered drop as a no-op.

Triggers are DETERMINISTIC: per-point hit counters, no randomness —
the same program under the same spec fails identically every run. A
chaos sweep gets its variety by sweeping SPECS (seeds index a config
table), not by sampling.

Registered points (``dev/check_fault_points.py`` lints call sites
against this table):

==================== =======================================================
point                boundary
==================== =======================================================
scheduler.poll_work  top of the scheduler's PollWork handler (RPC fails,
                     executors exercise their backoff + report re-delivery)
executor.task.start  executor task runner, before execution (task fails
                     transiently; recovery re-queues within budget)
shuffle.fetch        consumer-side shuffle fetch, per attempt (tagged
                     ShuffleFetchError path: producer re-queue)
dataplane.serve      data-plane request handler (drop = close without a
                     response; fail = error response)
state.save           scheduler state task-status persistence
state.load           scheduler state rehydration read at construction
                     (fail = a restarted scheduler's recovery scan
                     degrades — serves with whatever loaded)
client.rpc           every SchedulerClient RPC, client side
scheduler.progress_report  executor-side TaskProgress piggyback assembly
                     (drop = skip this round's samples, delay = stall
                     them, fail = swallowed — progress is best-effort
                     and results must stay byte-identical)
shuffle.spill.write  spill-pool segment append (fail = IoError-shaped
                     disk fault; drop = TORN write — half the payload
                     reaches disk, the re-read detects SpillCorrupt)
shuffle.stream.chunk consumer-side chunk receive, per chunk (fail =
                     mid-transfer transport fault; delay = slow
                     consumer exercising flow control)
dataplane.flow       server-side chunk-stream writer, per chunk (drop =
                     close mid-stream like a crashed peer; fail =
                     tagged error frame to the reader)
scheduler.admit      admission gate on ExecuteQuery (fail = the
                     submission is shed with a structured retryable
                     error; clients honoring retry-after resubmit)
scheduler.admission_queue  admission queue pump (fail = this pump round
                     is skipped and the next retries — a queue fault
                     may delay dispatch, never lose a submission)
autoscaler.spawn     autoscaler scale-up hook, before the spawn (fail =
                     this tick is skipped; the demand signal persists
                     so the next tick retries)
==================== =======================================================

Disabled cost: one module-global ``is None`` check per hit — the
<5% warm-q1 overhead gate covers the armed-but-idle case too.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

from ..errors import FaultInjected

log = logging.getLogger("ballista.faults")

# point -> description (the lint's registry; keep in step with the
# table in the module docstring and docs/robustness.md)
FAULT_POINTS: Dict[str, str] = {
    "scheduler.poll_work": "scheduler PollWork handler entry",
    "executor.task.start": "executor task runner, before execution",
    "shuffle.fetch": "consumer-side shuffle fetch attempt",
    "dataplane.serve": "data-plane request handler",
    "state.save": "scheduler task-status persistence",
    "state.load": "scheduler state rehydration read at construction",
    "client.rpc": "SchedulerClient RPC, client side",
    "scheduler.progress_report": "executor TaskProgress piggyback "
                                 "assembly (live progress plane)",
    "shuffle.spill.write": "spill-pool segment append (drop = torn "
                           "write)",
    "shuffle.stream.chunk": "consumer-side chunk receive on the "
                            "streaming shuffle fetch",
    "dataplane.flow": "server-side chunk-stream writer (drop = close "
                      "mid-stream)",
    "scheduler.admit": "admission gate on ExecuteQuery (fail = the "
                       "submission is shed with a retryable error)",
    "scheduler.admission_queue": "admission queue pump (fail = skip "
                                 "this round, the next pump retries; "
                                 "delay = stalled dispatch)",
    "autoscaler.spawn": "autoscaler scale-up hook, before the spawn "
                        "(fail = skip this tick, the next retries)",
}


class _Rule:
    """One parsed trigger for one point, with its deterministic hit
    counter."""

    __slots__ = ("point", "action", "nth", "every", "delay_ms", "hits",
                 "lock")

    def __init__(self, point: str, action: str, nth: int = 0,
                 every: int = 0, delay_ms: float = 0.0):
        self.point = point
        self.action = action  # "fail" | "delay" | "drop"
        self.nth = nth        # fire on exactly this hit (1-based)
        self.every = every    # fire on every Nth hit
        self.delay_ms = delay_ms
        self.hits = 0
        self.lock = threading.Lock()

    def fire(self) -> Optional[str]:
        """Count one hit; return the action when this hit triggers."""
        with self.lock:
            self.hits += 1
            n = self.hits
        if self.every:
            triggered = n % self.every == 0
        else:
            triggered = n == (self.nth or 1)
        if not triggered:
            return None
        if self.action == "delay":
            time.sleep(self.delay_ms / 1000.0)
            return "delay"
        return self.action


class FaultConfigError(ValueError):
    """BALLISTA_FAULTS could not be parsed — raised eagerly at load so
    a typo'd spec fails the test arming it, not silently no-ops."""


def _parse_trigger(point: str, trig: str) -> _Rule:
    head, _, arg = trig.partition(":")
    head = head.strip().lower()
    arg = arg.strip()
    try:
        if head == "fail-once":
            return _Rule(point, "fail", nth=int(arg) if arg else 1)
        if head == "fail-every":
            return _Rule(point, "fail", every=max(int(arg), 1))
        if head == "delay":
            return _Rule(point, "delay", every=1,
                         delay_ms=float(arg))
        if head == "delay-once":
            return _Rule(point, "delay", nth=1, delay_ms=float(arg))
        if head in ("drop", "drop-once"):
            return _Rule(point, "drop", nth=int(arg) if arg else 1)
        if head == "drop-every":
            return _Rule(point, "drop", every=max(int(arg), 1))
    except ValueError as e:
        raise FaultConfigError(
            f"bad argument in BALLISTA_FAULTS trigger {trig!r} "
            f"for point {point!r}: {e}") from None
    raise FaultConfigError(
        f"unknown BALLISTA_FAULTS trigger {trig!r} for point {point!r} "
        "(expected fail-once[:K] | fail-every:N | delay:MS | "
        "delay-once:MS | drop[-once[:K]|-every:N])")


def parse_spec(spec: str) -> Dict[str, _Rule]:
    """Parse a BALLISTA_FAULTS value into {point: rule}. Unknown point
    names fail loudly — an armed fault that can never fire is a test
    bug."""
    rules: Dict[str, _Rule] = {}
    for part in spec.replace(",", ";").split(";"):
        part = part.strip()
        if not part:
            continue
        point, sep, trig = part.partition("=")
        point = point.strip()
        if not sep or not trig.strip():
            raise FaultConfigError(
                f"malformed BALLISTA_FAULTS entry {part!r} "
                "(expected point=trigger)")
        if point not in FAULT_POINTS:
            raise FaultConfigError(
                f"unknown fault point {point!r} in BALLISTA_FAULTS "
                f"(registered: {', '.join(sorted(FAULT_POINTS))})")
        rules[point] = _parse_trigger(point, trig.strip())
    return rules


# None = faults disabled (the common case: ONE is-None check per hit).
# Armed once at first use from BALLISTA_FAULTS; tests re-arm explicitly
# via reload_faults() after changing the env.
_rules: Optional[Dict[str, _Rule]] = None
_loaded = False
_load_lock = threading.Lock()


def _load() -> Optional[Dict[str, _Rule]]:
    global _rules, _loaded
    with _load_lock:
        if not _loaded:
            spec = os.environ.get("BALLISTA_FAULTS", "").strip()
            _rules = parse_spec(spec) or None if spec else None
            _loaded = True
            if _rules:
                log.warning("fault injection ARMED: %s",
                            {p: vars_str(r) for p, r in _rules.items()})
        return _rules


def vars_str(rule: _Rule) -> str:
    if rule.every:
        sched = f"every:{rule.every}"
    else:
        sched = f"once:{rule.nth or 1}"
    extra = f" {rule.delay_ms}ms" if rule.action == "delay" else ""
    return f"{rule.action}-{sched}{extra}"


def reload_faults() -> None:
    """Re-read BALLISTA_FAULTS and reset every hit counter (tests call
    this after mutating the env; deterministic sweeps call it between
    seeds)."""
    global _loaded
    with _load_lock:
        _loaded = False
    _load()


def faults_armed() -> bool:
    return _load() is not None


def fault_point(name: str, **ctx) -> Optional[str]:
    """Declare a fault point. No-op (returns None) unless
    ``BALLISTA_FAULTS`` arms ``name``; a triggered ``fail`` raises
    :class:`FaultInjected`, ``delay`` sleeps then returns "delay", and
    ``drop`` returns "drop" for the caller to act on (callers without
    drop semantics ignore the return value). ``ctx`` is logged with
    the injection for debuggability."""
    rules = _rules if _loaded else _load()
    if rules is None:
        return None
    rule = rules.get(name)
    if rule is None:
        return None
    action = rule.fire()
    if action is None:
        return None
    log.warning("fault injected at %s (%s, hit %d) %s", name,
                vars_str(rule), rule.hits, ctx or "")
    if action == "fail":
        raise FaultInjected(
            f"injected fault at {name} (hit {rule.hits})")
    return action
