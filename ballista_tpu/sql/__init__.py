"""SQL frontend: lexer -> parser -> logical planner.

The reference delegates SQL to DataFusion's parser/planner (reference:
rust/client/src/context.rs:131-144 ``BallistaContext::sql``); this package
is the from-scratch equivalent sized for the TPC-H dialect plus general
analytics SQL: SELECT/DISTINCT, expressions, joins (explicit + comma/WHERE
style), GROUP BY/HAVING, ORDER BY, LIMIT, CASE, BETWEEN/IN/LIKE/EXTRACT,
date and interval literals.
"""

from .parser import parse_sql  # noqa: F401
from .planner import SqlPlanner  # noqa: F401
