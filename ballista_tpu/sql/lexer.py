"""SQL tokenizer."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..errors import SqlError

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "like", "between", "is", "null",
    "case", "when", "then", "else", "end", "cast", "distinct", "asc",
    "desc", "join", "inner", "left", "right", "full", "outer", "semi",
    "anti", "on", "date", "interval", "extract", "union", "all", "exists",
    "create", "external", "table", "stored", "location", "with", "header",
    "row", "nulls", "first", "last", "true", "false", "offset", "using",
}

# Soft (contextual) keywords: only special at statement position, so
# schemas with columns named e.g. ``verbose`` keep parsing (they lex as
# plain identifiers; the parser matches them by value where relevant).
SOFT_KEYWORDS = {"explain", "verbose", "analyze"}

TWO_CHAR_OPS = ("<=", ">=", "<>", "!=", "||")
ONE_CHAR_OPS = "+-*/%(),.;=<>"


@dataclass
class Token:
    kind: str  # kw | ident | number | string | op | eof
    value: str
    pos: int

    def is_kw(self, *names: str) -> bool:
        return self.kind == "kw" and self.value in names

    def __repr__(self):  # pragma: no cover
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":  # line comment
            while i < n and sql[i] != "\n":
                i += 1
            continue
        if c == "'":  # string literal (with '' escape)
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise SqlError(f"unterminated string at {i}")
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':  # quoted identifier
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlError(f"unterminated quoted identifier at {i}")
            out.append(Token("ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    nxt = sql[j + 1] if j + 1 < n else ""
                    if nxt.isdigit() or nxt in "+-":
                        seen_exp = True
                        j += 2 if nxt in "+-" else 1
                    else:
                        break
                else:
                    break
            out.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            low = word.lower()
            if low in KEYWORDS:
                out.append(Token("kw", low, i))
            else:
                out.append(Token("ident", word, i))
            i = j
            continue
        if sql[i : i + 2] in TWO_CHAR_OPS:
            out.append(Token("op", sql[i : i + 2], i))
            i += 2
            continue
        if c in ONE_CHAR_OPS:
            out.append(Token("op", c, i))
            i += 1
            continue
        raise SqlError(f"unexpected character {c!r} at position {i}")
    out.append(Token("eof", "", n))
    return out
