"""Recursive-descent / Pratt SQL parser producing statement ASTs whose
expressions are ``ballista_tpu.expr`` nodes (with unresolved column refs).
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field as dc_field
from typing import List, Optional, Tuple

from ..datatypes import Date32, dtype_from_name
from ..errors import SqlError
from .. import expr as ex
from .lexer import Token, tokenize


# ---------------------------------------------------------------------------
# Statement ASTs
# ---------------------------------------------------------------------------


@dataclass
class SelectItem:
    expr: Optional[ex.Expr]  # None => '*'
    alias: Optional[str] = None
    star: bool = False


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None
    subquery: Optional["Query"] = None  # derived table: FROM (SELECT ...) a


@dataclass
class JoinClause:
    how: str  # inner|left|right|semi|anti|cross
    table: TableRef
    on: Optional[ex.Expr] = None


@dataclass
class OrderItem:
    expr: ex.Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class Query:
    items: List[SelectItem]
    from_table: Optional[TableRef]
    joins: List[JoinClause]
    where: Optional[ex.Expr]
    group_by: List[ex.Expr]
    having: Optional[ex.Expr]
    order_by: List[OrderItem]
    limit: Optional[int]
    distinct: bool = False


@dataclass
class ExplainStmt:
    """EXPLAIN [ANALYZE] [VERBOSE] <select> (reference: rust/core/proto/
    ballista.proto:232 ExplainNode; DataFusion's SQL EXPLAIN surface).
    ``analyze`` executes the query and annotates the rendered plan with
    live operator metrics."""
    query: "Query"
    verbose: bool = False
    analyze: bool = False


@dataclass
class CreateExternalTable:
    name: str
    columns: List[Tuple[str, str]]  # (name, type string)
    stored_as: str  # CSV | TBL | PARQUET
    location: str
    has_header: bool = False


Statement = object  # Query | ExplainStmt | CreateExternalTable


def parse_sql(sql: str) -> Statement:
    return Parser(tokenize(sql)).parse_statement()


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    # -- token helpers ------------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def accept_kw(self, *names: str) -> Optional[Token]:
        if self.peek().is_kw(*names):
            return self.next()
        return None

    def expect_kw(self, *names: str) -> Token:
        t = self.next()
        if not t.is_kw(*names):
            raise SqlError(f"expected {'/'.join(names).upper()}, got {t.value!r}")
        return t

    def accept_op(self, *ops: str) -> Optional[Token]:
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            return self.next()
        return None

    def expect_op(self, op: str) -> Token:
        t = self.next()
        if t.kind != "op" or t.value != op:
            raise SqlError(f"expected {op!r}, got {t.value!r}")
        return t

    def expect_ident(self) -> str:
        t = self.next()
        if t.kind == "ident":
            return t.value
        # allow non-reserved keywords as identifiers in limited spots
        if t.kind == "kw":
            return t.value
        raise SqlError(f"expected identifier, got {t.value!r}")

    # -- statements ---------------------------------------------------------

    def _peek_soft(self, name: str) -> bool:
        """Contextual keyword: an identifier matched by value, so the same
        word stays usable as a column name elsewhere in the query."""
        from .lexer import SOFT_KEYWORDS

        assert name in SOFT_KEYWORDS, f"{name} not registered as soft kw"
        t = self.peek()
        return t.kind == "ident" and t.value.lower() == name

    def parse_statement(self) -> Statement:
        if self.peek().is_kw("create"):
            return self.parse_create_external_table()
        if self._peek_soft("explain"):
            self.next()
            verbose = analyze = False
            # EXPLAIN [ANALYZE] [VERBOSE] — flags accepted in either order
            while True:
                if not verbose and self._peek_soft("verbose"):
                    self.next()
                    verbose = True
                elif not analyze and self._peek_soft("analyze"):
                    self.next()
                    analyze = True
                else:
                    break
            if not self.peek().is_kw("select"):
                raise SqlError(
                    f"EXPLAIN expects SELECT, got {self.peek().value!r}")
            q = self.parse_query()
            self.accept_op(";")
            if self.peek().kind != "eof":
                raise SqlError(f"trailing tokens at {self.peek().pos}")
            return ExplainStmt(q, verbose, analyze)
        if self.peek().is_kw("select"):
            q = self.parse_query()
            self.accept_op(";")
            if self.peek().kind != "eof":
                raise SqlError(f"trailing tokens at {self.peek().pos}")
            return q
        raise SqlError(
            f"expected SELECT, EXPLAIN or CREATE, got {self.peek().value!r}")

    def parse_create_external_table(self) -> CreateExternalTable:
        self.expect_kw("create")
        self.expect_kw("external")
        self.expect_kw("table")
        name = self.expect_ident()
        self.expect_op("(")
        cols: List[Tuple[str, str]] = []
        while True:
            cname = self.expect_ident()
            tparts = [self.expect_ident()]
            if self.accept_op("("):
                inner = []
                while not self.accept_op(")"):
                    inner.append(self.next().value)
                tparts.append("(" + ",".join(inner) + ")")
            cols.append((cname, " ".join(tparts)))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        has_header = False
        if self.accept_kw("with"):
            self.expect_kw("header")
            self.expect_kw("row")
            has_header = True
        self.expect_kw("stored")
        self.expect_kw("as")
        stored = self.expect_ident().upper()
        self.expect_kw("location")
        t = self.next()
        if t.kind != "string":
            raise SqlError("LOCATION requires a string literal")
        self.accept_op(";")
        return CreateExternalTable(name, cols, stored, t.value, has_header)

    # -- queries ------------------------------------------------------------

    def parse_query(self) -> Query:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        self.accept_kw("all")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())

        from_table: Optional[TableRef] = None
        joins: List[JoinClause] = []
        if self.accept_kw("from"):
            from_table = self.parse_table_ref()
            while True:
                if self.accept_op(","):
                    joins.append(JoinClause("cross", self.parse_table_ref()))
                    continue
                how = self.parse_join_kind()
                if how is None:
                    break
                tref = self.parse_table_ref()
                on = None
                if self.accept_kw("on"):
                    on = self.parse_expr()
                joins.append(JoinClause(how, tref, on))

        where = self.parse_expr() if self.accept_kw("where") else None

        group_by: List[ex.Expr] = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.accept_op(","):
                group_by.append(self.parse_expr())

        having = self.parse_expr() if self.accept_kw("having") else None

        order_by: List[OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())

        limit = None
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "number":
                raise SqlError("LIMIT requires a number")
            limit = int(t.value)

        return Query(items, from_table, joins, where, group_by, having,
                     order_by, limit, distinct)

    def parse_join_kind(self) -> Optional[str]:
        if self.accept_kw("join"):
            return "inner"
        if self.accept_kw("inner"):
            self.expect_kw("join")
            return "inner"
        for kw in ("left", "right", "full"):
            if self.peek().is_kw(kw):
                self.next()
                self.accept_kw("outer")
                self.expect_kw("join")
                return kw
        for kw in ("semi", "anti"):
            if self.peek().is_kw(kw):
                self.next()
                self.expect_kw("join")
                return kw
        return None

    def parse_table_ref(self) -> TableRef:
        if self.accept_op("("):
            sub = self.parse_query()
            self.expect_op(")")
            alias = None
            if self.accept_kw("as"):
                alias = self.expect_ident()
            elif self.peek().kind == "ident":
                alias = self.next().value
            if alias is None:
                raise SqlError("derived table requires an alias")
            return TableRef(f"__subquery_{alias}", alias, subquery=sub)
        name = self.expect_ident()
        # dotted table names (one schema level, e.g. ``system.queries``):
        # consumed here so the catalog can key on the qualified name
        if self.peek().kind == "op" and self.peek().value == "." and \
                self.peek(1).kind in ("ident", "kw"):
            self.next()
            name = f"{name}.{self.next().value}"
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return TableRef(name, alias)

    def parse_select_item(self) -> SelectItem:
        if self.accept_op("*"):
            return SelectItem(None, None, star=True)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return SelectItem(e, alias)

    def parse_order_item(self) -> OrderItem:
        e = self.parse_expr()
        asc = True
        if self.accept_kw("asc"):
            asc = True
        elif self.accept_kw("desc"):
            asc = False
        nulls_first = None
        if self.accept_kw("nulls"):
            t = self.expect_kw("first", "last")
            nulls_first = t.value == "first"
        return OrderItem(e, asc, nulls_first)

    # -- expressions (Pratt) -------------------------------------------------

    def parse_expr(self) -> ex.Expr:
        return self.parse_or()

    def parse_or(self) -> ex.Expr:
        e = self.parse_and()
        while self.accept_kw("or"):
            e = ex.BinaryExpr(e, "or", self.parse_and())
        return e

    def parse_and(self) -> ex.Expr:
        e = self.parse_not()
        while self.accept_kw("and"):
            e = ex.BinaryExpr(e, "and", self.parse_not())
        return e

    def parse_not(self) -> ex.Expr:
        if self.accept_kw("not"):
            return ex.Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ex.Expr:
        e = self.parse_additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("=", "<", ">", "<=", ">=", "<>", "!="):
                self.next()
                op = "!=" if t.value in ("<>", "!=") else t.value
                e = ex.BinaryExpr(e, op, self.parse_additive())
                continue
            negated = False
            if t.is_kw("not"):
                nxt = self.peek(1)
                if nxt.is_kw("between", "in", "like"):
                    self.next()
                    negated = True
                    t = self.peek()
                else:
                    break
            if t.is_kw("between"):
                self.next()
                lo = self.parse_additive()
                self.expect_kw("and")
                hi = self.parse_additive()
                rng = ex.BinaryExpr(
                    ex.BinaryExpr(e, ">=", lo), "and", ex.BinaryExpr(e, "<=", hi)
                )
                e = ex.Not(rng) if negated else rng
                continue
            if t.is_kw("in"):
                self.next()
                self.expect_op("(")
                if self.peek().is_kw("select"):
                    sub = self.parse_query()
                    self.expect_op(")")
                    e = ex.InSubquery(e, sub, negated)
                    continue
                vals = [self.parse_expr()]
                while self.accept_op(","):
                    vals.append(self.parse_expr())
                self.expect_op(")")
                e = ex.InList(e, vals, negated)
                continue
            if t.is_kw("like"):
                self.next()
                pat = self.next()
                if pat.kind != "string":
                    raise SqlError("LIKE requires a string pattern")
                e = ex.Like(e, pat.value, negated)
                continue
            if t.is_kw("is"):
                self.next()
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                e = ex.IsNotNull(e) if neg else ex.IsNull(e)
                continue
            break
        return e

    def parse_additive(self) -> ex.Expr:
        e = self.parse_multiplicative()
        while True:
            t = self.accept_op("+", "-")
            if not t:
                return e
            rhs = self.parse_multiplicative()
            e = self._fold_date_arith(e, t.value, rhs)

    def _fold_date_arith(self, l: ex.Expr, op: str, r: ex.Expr) -> ex.Expr:
        # interval plumbing: intervals parse as Literal(days, Int32) tagged
        # via _IntervalDays, or month-intervals that only fold on date
        # literals
        if isinstance(r, _IntervalMonths):
            if isinstance(l, ex.Literal) and l.dtype == Date32:
                base = _dt.date(1970, 1, 1) + _dt.timedelta(days=int(l.value))
                months = r.months if op == "+" else -r.months
                y = base.year + (base.month - 1 + months) // 12
                m = (base.month - 1 + months) % 12 + 1
                d = min(base.day, _days_in_month(y, m))
                return ex.Literal((_dt.date(y, m, d) - _dt.date(1970, 1, 1)).days,
                                  Date32)
            raise SqlError("month/year intervals supported only on date literals")
        if isinstance(r, _IntervalDays):
            r = ex.Literal(r.days, _I32)  # plain int day count
        e = ex.BinaryExpr(l, op, r)
        # constant-fold date literal +/- int literal
        if (
            isinstance(l, ex.Literal) and l.dtype == Date32
            and isinstance(r, ex.Literal) and r.dtype.is_integer
        ):
            days = int(l.value) + (int(r.value) if op == "+" else -int(r.value))
            return ex.Literal(days, Date32)
        return e

    def parse_multiplicative(self) -> ex.Expr:
        e = self.parse_unary()
        while True:
            t = self.accept_op("*", "/", "%")
            if not t:
                return e
            e = ex.BinaryExpr(e, t.value, self.parse_unary())

    def parse_unary(self) -> ex.Expr:
        if self.accept_op("-"):
            inner = self.parse_unary()
            if isinstance(inner, ex.Literal) and inner.dtype.is_numeric:
                return ex.Literal(-inner.value, inner.dtype)
            return ex.BinaryExpr(ex.Literal(0, _I64), "-", inner)
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> ex.Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                return ex.Literal(float(t.value), _F64)
            return ex.Literal(int(t.value), _I64)
        if t.kind == "string":
            self.next()
            return ex.Literal(t.value, _UTF8)
        if t.is_kw("true"):
            self.next()
            return ex.Literal(True, _BOOL)
        if t.is_kw("false"):
            self.next()
            return ex.Literal(False, _BOOL)
        if t.is_kw("null"):
            self.next()
            return ex.Literal(None, _I64)
        if t.is_kw("date"):
            self.next()
            s = self.next()
            if s.kind != "string":
                raise SqlError("DATE requires a string literal")
            return ex.Literal(ex.parse_date_literal(s.value), Date32)
        if t.is_kw("interval"):
            self.next()
            s = self.next()
            if s.kind not in ("string", "number"):
                raise SqlError("INTERVAL requires a quantity")
            qty = s.value
            unit = self.expect_ident().lower().rstrip("s")
            # also supports "interval '3 month'" style
            if " " in qty.strip():
                parts = qty.split()
                qty, unit = parts[0], parts[1].lower().rstrip("s")
            n = int(float(qty))
            if unit == "day":
                return _IntervalDays(n)
            if unit == "week":
                return _IntervalDays(7 * n)
            if unit == "month":
                return _IntervalMonths(n)
            if unit == "year":
                return _IntervalMonths(12 * n)
            raise SqlError(f"unsupported interval unit {unit}")
        if t.is_kw("exists"):
            self.next()
            self.expect_op("(")
            sub = self.parse_query()
            self.expect_op(")")
            return ex.Exists(sub)
        if t.is_kw("case"):
            return self.parse_case()
        if t.is_kw("cast"):
            self.next()
            self.expect_op("(")
            inner = self.parse_expr()
            self.expect_kw("as")
            tname = [self.expect_ident()]
            if self.accept_op("("):
                args = []
                while not self.accept_op(")"):
                    args.append(self.next().value)
                tname.append("(" + ",".join(args) + ")")
            self.expect_op(")")
            return ex.Cast(inner, dtype_from_name(" ".join(tname)))
        if t.is_kw("extract"):
            self.next()
            self.expect_op("(")
            part = self.expect_ident().lower()
            self.expect_kw("from")
            inner = self.parse_expr()
            self.expect_op(")")
            if part not in ("year", "month", "day"):
                raise SqlError(f"EXTRACT({part}) unsupported")
            return ex.ScalarFunction(f"extract_{part}", [inner])
        if self.accept_op("("):
            if self.peek().is_kw("select"):
                sub = self.parse_query()
                self.expect_op(")")
                return ex.ScalarSubquery(None, sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "ident" or t.is_kw("left", "right"):  # fn names may clash
            name = self.next().value
            if self.accept_op("("):
                return self.parse_function(name.lower())
            if self.accept_op("."):
                colname = self.expect_ident()
                return ex.ColumnRef(colname, name)
            return ex.ColumnRef(name)
        raise SqlError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_function(self, name: str) -> ex.Expr:
        args: List[ex.Expr] = []
        distinct = False
        if self.accept_op("*"):
            self.expect_op(")")
            if name != "count":
                raise SqlError(f"{name}(*) not supported")
            return ex.count()
        if self.accept_kw("distinct"):
            distinct = True
        if not self.accept_op(")"):
            args.append(self.parse_expr())
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
        if name in ("sum", "avg", "min", "max", "count"):
            if len(args) != 1:
                raise SqlError(f"{name} takes one argument")
            if distinct:
                if name != "count":
                    raise SqlError(f"{name}(DISTINCT) not supported")
                return ex.count_distinct(args[0])
            return ex.AggregateExpr(name, args[0])
        if name in ("substring", "substr"):
            return ex.ScalarFunction("substr", args)
        if name == "char_length":
            return ex.ScalarFunction("length", args)
        return ex.ScalarFunction(name, args)

    def parse_case(self) -> ex.Expr:
        self.expect_kw("case")
        base = None
        if not self.peek().is_kw("when"):
            base = self.parse_expr()
        branches = []
        while self.accept_kw("when"):
            w = self.parse_expr()
            self.expect_kw("then")
            th = self.parse_expr()
            branches.append((w, th))
        otherwise = None
        if self.accept_kw("else"):
            otherwise = self.parse_expr()
        self.expect_kw("end")
        return ex.Case(base, branches, otherwise)


# -- helper literal dtypes (avoid importing the heavy module paths inline) ---

from ..datatypes import (  # noqa: E402
    Boolean as _BOOL,
    Float64 as _F64,
    Int32 as _I32,
    Int64 as _I64,
    Utf8 as _UTF8,
)


@dataclass(repr=False, eq=False)
class _IntervalDays(ex.Expr):
    days: int

    def name(self) -> str:
        return f"INTERVAL {self.days} DAY"


@dataclass(repr=False, eq=False)
class _IntervalMonths(ex.Expr):
    months: int

    def name(self) -> str:
        return f"INTERVAL {self.months} MONTH"


def _days_in_month(y: int, m: int) -> int:
    if m == 12:
        return 31
    return ((_dt.date(y, m + 1, 1)) - _dt.date(y, m, 1)).days
