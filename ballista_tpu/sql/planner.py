"""SQL AST -> LogicalPlan.

Replaces the DataFusion SQL planner the reference leans on (reference:
rust/client/src/context.rs:131-144; scheduler-side planning at
rust/scheduler/src/lib.rs:224-407). Key responsibilities:

- name resolution against a catalog of registered tables, with table
  aliases and qualified column refs;
- join graph extraction: explicit JOIN ... ON plus TPC-H-style comma FROM +
  WHERE equality conjuncts become a greedy join chain whose build sides are
  chosen by primary-key heuristics (build side must be the unique-key side
  for the FK fast path — see physical/join.py);
- aggregate extraction: SELECT/HAVING/ORDER BY expressions over aggregates
  are rewritten to reference generated aggregate output columns;
- DISTINCT -> group-by-all; ordinal GROUP BY/ORDER BY references.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..datatypes import Schema
from ..errors import PlanError, SqlError
from .. import expr as ex
from ..logical import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Sort,
    TableScan,
    TableSource,
)
from .parser import JoinClause, OrderItem, Query, SelectItem, TableRef


@dataclass
class CatalogTable:
    name: str
    source: TableSource
    primary_key: Optional[str] = None  # unique column, for join-side choice


class SqlPlanner:
    def __init__(self, catalog: Dict[str, CatalogTable]):
        self.catalog = catalog

    # ------------------------------------------------------------------ API

    def plan(self, q: Query) -> LogicalPlan:
        if q.from_table is None:
            raise SqlError("SELECT without FROM not supported yet")

        tables = self._resolve_tables(q)
        where_conjuncts = (
            self._qualify_conjuncts(q.where, tables) if q.where is not None else []
        )
        plan, remaining = self._plan_joins(q, tables, where_conjuncts)
        if remaining:
            from ..optimizer import conjoin

            plan = Filter(conjoin(remaining), plan)

        plan = self._plan_select(q, plan)
        return plan

    # -------------------------------------------------------- FROM resolution

    def _resolve_tables(self, q: Query) -> List[Tuple[str, CatalogTable]]:
        """[(alias, table)] in FROM order."""
        out = []
        refs = [q.from_table] + [j.table for j in q.joins]
        for r in refs:
            if r.name not in self.catalog:
                raise SqlError(f"unknown table {r.name!r}")
            out.append((r.alias or r.name, self.catalog[r.name]))
        return out

    def _owner_of(self, colname: str, tables) -> Optional[str]:
        """alias of the table owning an unqualified column name."""
        owner = None
        for alias, t in tables:
            if t.source.table_schema().has_field(colname):
                if owner is not None:
                    raise SqlError(f"ambiguous column {colname!r}")
                owner = alias
        return owner

    def _qualify(self, e: ex.Expr, tables) -> ex.Expr:
        """Resolve qualified refs (alias.col -> col) after checking owners."""
        if isinstance(e, ex.ColumnRef):
            if e.relation is not None:
                aliases = {a for a, _ in tables}
                if e.relation not in aliases:
                    raise SqlError(f"unknown table alias {e.relation!r}")
                return ex.ColumnRef(e.column)
            if self._owner_of(e.column, tables) is None:
                raise SqlError(f"unknown column {e.column!r}")
            return e
        for attr in ("expr", "left", "right", "base", "otherwise"):
            if hasattr(e, attr) and isinstance(getattr(e, attr), ex.Expr):
                setattr(e, attr, self._qualify(getattr(e, attr), tables))
        if hasattr(e, "args"):
            e.args = [self._qualify(a, tables) for a in e.args]
        if hasattr(e, "list"):
            e.list = [self._qualify(a, tables) for a in e.list]
        if hasattr(e, "branches"):
            e.branches = [
                (self._qualify(w, tables), self._qualify(t, tables))
                for w, t in e.branches
            ]
        return e

    def _qualify_conjuncts(self, where: ex.Expr, tables) -> List[ex.Expr]:
        from ..optimizer import split_conjuncts

        return [self._qualify(c, tables) for c in split_conjuncts(where)]

    # ------------------------------------------------------------ join graph

    def _plan_joins(self, q: Query, tables, conjuncts):
        """Greedy join chain; returns (plan, leftover conjuncts).

        Build-side choice: when adding table T to the accumulated plan via
        edge (acc_col = t_col), use Join(left=T, right=acc) iff t_col is T's
        primary key (fast FK probe into acc), else Join(left=acc, right=T)
        iff acc_col is unique in acc; else default to build=T (runtime
        expanding join handles duplicates).
        """
        alias_schema = {a: t.source.table_schema() for a, t in tables}
        col_owner: Dict[str, str] = {}
        for a, t in tables:
            for n in t.source.table_schema().names():
                # later duplicates are ambiguous; _owner_of catches misuse
                col_owner.setdefault(n, a)

        # single-table fast path
        if len(tables) == 1:
            alias, t = tables[0]
            return TableScan(t.name, t.source), conjuncts

        # classify conjuncts
        def owners(e: ex.Expr) -> Set[str]:
            return {col_owner[c] for c in ex.referenced_columns(e) if c in col_owner}

        join_edges: List[Tuple[str, str, str, str]] = []  # (a1, c1, a2, c2)
        table_filters: Dict[str, List[ex.Expr]] = {a: [] for a, _ in tables}
        post: List[ex.Expr] = []
        for c in conjuncts:
            if (
                isinstance(c, ex.BinaryExpr) and c.op == "="
                and isinstance(c.left, ex.ColumnRef)
                and isinstance(c.right, ex.ColumnRef)
            ):
                o1 = col_owner.get(c.left.column)
                o2 = col_owner.get(c.right.column)
                if o1 and o2 and o1 != o2:
                    join_edges.append((o1, c.left.column, o2, c.right.column))
                    continue
            os = owners(c)
            if len(os) == 1:
                table_filters[next(iter(os))].append(c)
            else:
                post.append(c)

        # explicit JOIN ... ON clauses contribute edges / filters too
        explicit_how: Dict[str, str] = {}
        for j in q.joins:
            alias = j.table.alias or j.table.name
            if j.how != "cross":
                explicit_how[alias] = j.how
            if j.on is not None:
                for c in self._qualify_conjuncts(j.on, tables):
                    if (
                        isinstance(c, ex.BinaryExpr) and c.op == "="
                        and isinstance(c.left, ex.ColumnRef)
                        and isinstance(c.right, ex.ColumnRef)
                    ):
                        o1 = col_owner.get(c.left.column)
                        o2 = col_owner.get(c.right.column)
                        if o1 and o2 and o1 != o2:
                            join_edges.append((o1, c.left.column, o2, c.right.column))
                            continue
                    post.append(c)

        def scan_with_filters(alias: str) -> LogicalPlan:
            t = dict(tables)[alias]
            p: LogicalPlan = TableScan(t.name, t.source)
            from ..optimizer import conjoin

            if table_filters[alias]:
                p = Filter(conjoin(table_filters[alias]), p)
            return p

        # greedy chain in FROM order
        joined: Set[str] = {tables[0][0]}
        plan = scan_with_filters(tables[0][0])
        # unique cols currently valid for the accumulated plan's rows
        acc_unique: Set[str] = set()
        pk0 = dict(tables)[tables[0][0]].primary_key
        if pk0:
            acc_unique.add(pk0)
        pending = [a for a, _ in tables[1:]]
        edges = list(join_edges)

        while pending:
            progress = False
            for alias in list(pending):
                # find an edge connecting alias to the joined set
                edge = None
                used = None
                for e_ in edges:
                    a1, c1, a2, c2 = e_
                    if a1 == alias and a2 in joined:
                        edge, used = (alias, c1, a2, c2), e_
                        break
                    if a2 == alias and a1 in joined:
                        edge, used = (alias, c2, a1, c1), e_
                        break
                if edge is None:
                    continue
                t_alias, t_col, _, acc_col = edge
                t = dict(tables)[t_alias]
                t_plan = scan_with_filters(t_alias)
                how = explicit_how.get(t_alias, "inner")
                if t.primary_key == t_col:
                    # build the new (dimension) table, probe the acc
                    plan = Join(t_plan, plan, [(t_col, acc_col)], how)
                    # acc row granularity unchanged -> acc_unique survives
                elif acc_col in acc_unique:
                    plan = Join(plan, t_plan, [(acc_col, t_col)], how)
                    acc_unique = {t.primary_key} if t.primary_key else set()
                else:
                    plan = Join(t_plan, plan, [(t_col, acc_col)], how)
                joined.add(t_alias)
                pending.remove(t_alias)
                edges.remove(used)
                # leftover edges between already-joined tables become
                # post-join equality filters (e.g. q5's c_nationkey =
                # s_nationkey once both sides are in the chain)
                resolved = [
                    e_ for e_ in edges if e_[0] in joined and e_[2] in joined
                ]
                for a1, c1, a2, c2 in resolved:
                    post.append(ex.BinaryExpr(ex.col(c1), "=", ex.col(c2)))
                edges = [e_ for e_ in edges if e_ not in resolved]
                progress = True
            if not progress:
                raise SqlError(
                    f"no join condition connects tables {pending} to the rest"
                )
        return plan, post

    # -------------------------------------------------- SELECT/agg/order/limit

    def _plan_select(self, q: Query, plan: LogicalPlan) -> LogicalPlan:
        in_schema = plan.schema()

        # expand stars
        items: List[SelectItem] = []
        for it in q.items:
            if it.star:
                for n in in_schema.names():
                    items.append(SelectItem(ex.ColumnRef(n), None))
            else:
                items.append(it)

        select_exprs = [
            it.expr.alias(it.alias) if it.alias else it.expr for it in items
        ]

        # resolve GROUP BY entries (ordinals / aliases / exprs)
        group_exprs: List[ex.Expr] = []
        for g in q.group_by:
            group_exprs.append(self._resolve_ref(g, items, in_schema))

        has_aggs = any(self._contains_agg(e) for e in select_exprs) or (
            q.having is not None and self._contains_agg(q.having)
        )
        distinct = q.distinct

        if group_exprs or has_aggs:
            plan = self._plan_aggregate(q, plan, select_exprs, group_exprs)
        else:
            if distinct:
                # DISTINCT == group by all output columns
                proj = Projection(select_exprs, plan)
                names = proj.schema().names()
                plan = Aggregate([ex.ColumnRef(n) for n in names], [], proj)
                distinct = False
            else:
                plan = Projection(select_exprs, plan)

        out_schema = plan.schema()

        # ORDER BY (may reference output aliases, ordinals, or input cols)
        if q.order_by:
            sort_exprs = []
            for oi in q.order_by:
                e = self._resolve_order_ref(oi.expr, items, out_schema)
                sort_exprs.append(ex.SortExpr(e, oi.ascending,
                                              bool(oi.nulls_first)))
            plan = Sort(sort_exprs, plan)

        if q.limit is not None:
            plan = Limit(q.limit, plan)
        return plan

    def _plan_aggregate(self, q: Query, plan, select_exprs, group_exprs):
        # collect aggregate subexpressions across SELECT + HAVING + ORDER BY
        aggs: List[ex.AggregateExpr] = []

        def collect(e: ex.Expr):
            for node in ex.walk(e):
                if isinstance(node, ex.AggregateExpr):
                    if not any(node is a or a.name() == node.name() for a in aggs):
                        aggs.append(node)

        for e in select_exprs:
            collect(e)
        if q.having is not None:
            collect(q.having)
        for oi in q.order_by:
            collect(oi.expr)

        agg_plan = Aggregate(group_exprs, list(aggs), plan)
        agg_schema = agg_plan.schema()

        group_names = {g.name() for g in group_exprs}

        def rewrite(e: ex.Expr) -> ex.Expr:
            """Replace aggregate subtrees / group exprs with output col refs."""
            if isinstance(e, ex.Alias):
                return ex.Alias(rewrite(e.expr), e.alias_name)
            if isinstance(e, ex.AggregateExpr):
                return ex.ColumnRef(e.name())
            if e.name() in group_names:
                return ex.ColumnRef(e.name())
            for attr in ("expr", "left", "right", "base", "otherwise"):
                if hasattr(e, attr) and isinstance(getattr(e, attr), ex.Expr):
                    setattr(e, attr, rewrite(getattr(e, attr)))
            if hasattr(e, "args"):
                e.args = [rewrite(a) for a in e.args]
            if hasattr(e, "list"):
                e.list = [rewrite(a) for a in e.list]
            if hasattr(e, "branches"):
                e.branches = [(rewrite(w), rewrite(t)) for w, t in e.branches]
            return e

        out: LogicalPlan = agg_plan
        if q.having is not None:
            out = Filter(rewrite(self._resolve_ref(q.having, [], agg_schema)), out)
        projected = [rewrite(e) for e in select_exprs]
        # validate non-aggregate select exprs reference group cols only
        for e in projected:
            for node in ex.walk(e):
                if isinstance(node, ex.ColumnRef) and not agg_schema.has_field(
                    node.column
                ):
                    raise SqlError(
                        f"column {node.column!r} is neither grouped nor aggregated"
                    )
        return Projection(projected, out)

    # ------------------------------------------------------------- reference
    # resolution helpers

    def _resolve_ref(self, e: ex.Expr, items: List[SelectItem], schema: Schema):
        # ordinal (1-based)
        if isinstance(e, ex.Literal) and e.dtype.is_integer and items:
            idx = int(e.value) - 1
            if 0 <= idx < len(items):
                return items[idx].expr
            raise SqlError(f"ordinal {e.value} out of range")
        # output alias
        if isinstance(e, ex.ColumnRef) and not schema.has_field(e.column):
            for it in items:
                if it.alias == e.column:
                    return it.expr
        return e

    def _resolve_order_ref(self, e: ex.Expr, items, out_schema: Schema):
        if isinstance(e, ex.Literal) and e.dtype.is_integer:
            idx = int(e.value) - 1
            names = out_schema.names()
            if 0 <= idx < len(names):
                return ex.ColumnRef(names[idx])
            raise SqlError(f"ordinal {e.value} out of range")
        if isinstance(e, ex.AggregateExpr):
            if out_schema.has_field(e.name()):
                return ex.ColumnRef(e.name())
            raise SqlError(f"ORDER BY aggregate {e.name()} not in output")
        if isinstance(e, ex.ColumnRef):
            if out_schema.has_field(e.column):
                return e
            for it in items:
                if it.alias == e.column:
                    return it.expr
            raise SqlError(f"unknown ORDER BY column {e.column!r}")
        return e

    def _contains_agg(self, e: ex.Expr) -> bool:
        return any(isinstance(n, ex.AggregateExpr) for n in ex.walk(e))
