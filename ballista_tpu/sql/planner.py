"""SQL AST -> LogicalPlan.

Replaces the DataFusion SQL planner the reference leans on (reference:
rust/client/src/context.rs:131-144; scheduler-side planning at
rust/scheduler/src/lib.rs:224-407). Key responsibilities:

- name resolution against a catalog of registered tables AND derived
  tables (FROM-subqueries), with table aliases; self-joins disambiguate by
  renaming the duplicated relations' columns to ``alias__column`` and
  resolving qualified refs through a per-alias rename map;
- join graph extraction: explicit JOIN ... ON plus TPC-H-style comma FROM +
  WHERE equality conjuncts become a greedy join chain whose build sides are
  chosen by primary-key heuristics (build side must be the unique-key side
  for the FK fast path — see physical/join.py);
- subqueries: [NOT] IN (SELECT ...) and [NOT] EXISTS (SELECT ...) are
  decorrelated into semi/anti joins (equality correlation); scalar
  subqueries are planned and inlined as literals at execution time
  (execution.resolve_subqueries);
- aggregate extraction: SELECT/HAVING/ORDER BY expressions over aggregates
  are rewritten to reference generated aggregate output columns;
  COUNT(DISTINCT x) rewrites to a two-level aggregate;
- DISTINCT -> group-by-all; ordinal GROUP BY/ORDER BY references.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..datatypes import Schema
from ..errors import PlanError, SqlError
from .. import expr as ex
from ..logical import (
    Aggregate,
    Explain,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Sort,
    TableScan,
    TableSource,
)
from .parser import (
    ExplainStmt, JoinClause, OrderItem, Query, SelectItem, TableRef,
)


@dataclass
class CatalogTable:
    name: str
    source: Optional[TableSource]
    primary_key: Optional[str] = None  # unique column, for join-side choice
    # view semantics: a registered DataFrame's logical plan, inlined
    # wherever SQL references the name (the reference wraps registered
    # frames the same way: DFTableAdapter, rust/core/src/datasource.rs:28-66)
    plan: Optional["LogicalPlan"] = None


@dataclass
class Relation:
    """One FROM item after resolution (base table or derived subquery)."""

    alias: str
    plan: LogicalPlan  # scan / derived plan, post-rename
    schema: Schema  # exposed schema (post-rename)
    primary_key: Optional[str]  # exposed pk name or None
    rename: Dict[str, str]  # original column -> exposed name


class SqlPlanner:
    def __init__(self, catalog: Dict[str, CatalogTable],
                 system_provider=None):
        self.catalog = catalog
        # system_provider(name) -> TableSource for a ``system.*`` table
        # (observability/systables.py). None falls back to the current
        # process's snapshot, so SQL over system tables works anywhere a
        # planner does; contexts pass a provider that routes remote
        # scans to the scheduler.
        self._system_provider = system_provider
        # resolved system CatalogTables, cached per planner so one
        # query's several references share a source instance
        self._system_tables: Dict[str, CatalogTable] = {}

    def _table(self, name: str) -> Optional[CatalogTable]:
        """Catalog lookup with ``system.*`` fallthrough: registered
        tables always win (a user may shadow a system name)."""
        t = self.catalog.get(name)
        if t is not None:
            return t
        from ..observability.systables import (SystemTableSource,
                                               is_system_table)

        if not is_system_table(name):
            return None
        t = self._system_tables.get(name)
        if t is None:
            src = (self._system_provider(name)
                   if self._system_provider is not None
                   else SystemTableSource(name))
            t = self._system_tables[name] = CatalogTable(name, src)
        return t

    # ------------------------------------------------------------------ API

    def plan(self, q) -> LogicalPlan:
        if isinstance(q, ExplainStmt):
            # EXPLAIN [ANALYZE] [VERBOSE] <select>: wrap the planned query
            # (reference surface: rust/core/proto/ballista.proto:232
            # ExplainNode)
            return Explain(self.plan(q.query), q.verbose, q.analyze)
        if q.from_table is None:
            raise SqlError("SELECT without FROM not supported yet")

        relations = self._resolve_relations(q)
        col_owner = self._column_owners(relations)

        conjuncts: List[ex.Expr] = []
        if q.where is not None:
            from ..optimizer import factor_or, split_conjuncts

            for c in split_conjuncts(q.where):
                # expose join conditions hidden inside OR-of-ANDs (q19)
                for f in factor_or(c):
                    conjuncts.append(self._qualify(f, relations, col_owner))

        # pull subquery predicates out of the WHERE conjuncts
        semi_specs, conjuncts = self._extract_subquery_predicates(
            conjuncts, relations, col_owner
        )

        plan, remaining = self._plan_joins(
            q, relations, col_owner, conjuncts, semi_specs
        )
        if remaining:
            from ..optimizer import conjoin

            plan = Filter(conjoin(remaining), plan)

        plan = self._plan_select(q, plan, relations, col_owner)
        return plan

    # ------------------------------------------------------- FROM resolution

    def _resolve_relations(self, q: Query) -> List[Relation]:
        refs = [q.from_table] + [j.table for j in q.joins]
        # duplicate-table detection: column names colliding across relations
        raw: List[Tuple[str, TableRef, Schema, Optional[str], Optional[LogicalPlan]]] = []
        for r in refs:
            alias = r.alias or r.name
            if r.subquery is not None:
                sub_plan = self.plan(r.subquery)
                raw.append((alias, r, sub_plan.schema(), None, sub_plan))
            else:
                t = self._table(r.name)
                if t is None:
                    raise SqlError(f"unknown table {r.name!r}")
                if t.plan is not None:  # registered DataFrame: a view
                    # inline a COPY: execution mutates plans in place
                    # (resolve_scalar_subqueries bakes literals into expr
                    # nodes), and the catalog's plan must stay pristine
                    # across queries and re-registrations
                    import copy

                    vplan = copy.deepcopy(t.plan)
                    raw.append(
                        (alias, r, vplan.schema(), t.primary_key, vplan)
                    )
                else:
                    raw.append(
                        (alias, r, t.source.table_schema(), t.primary_key,
                         None)
                    )
        seen: Dict[str, int] = {}
        for _, _, sch, _, _ in raw:
            for n in sch.names():
                seen[n] = seen.get(n, 0) + 1
        dup_cols = {n for n, c in seen.items() if c > 1}

        relations: List[Relation] = []
        for alias, r, sch, pk, sub_plan in raw:
            needs_rename = any(n in dup_cols for n in sch.names())
            if sub_plan is not None:
                base: LogicalPlan = sub_plan
            else:
                t = self._table(r.name)
                base = TableScan(t.name, t.source)
            if needs_rename:
                rename = {
                    n: (f"{alias}__{n}" if n in dup_cols else n)
                    for n in sch.names()
                }
                base = Projection(
                    [ex.ColumnRef(n).alias(rename[n]) for n in sch.names()],
                    base,
                )
                new_schema = base.schema()
                new_pk = rename.get(pk) if pk else None
            else:
                rename = {n: n for n in sch.names()}
                new_schema = sch
                new_pk = pk
            relations.append(Relation(alias, base, new_schema, new_pk, rename))
        return relations

    def _column_owners(self, relations: List[Relation]) -> Dict[str, str]:
        out: Dict[str, str] = {}
        counts: Dict[str, int] = {}
        for rel in relations:
            for n in rel.schema.names():
                out.setdefault(n, rel.alias)
                counts[n] = counts.get(n, 0) + 1
        # exposed names are unique post-rename; a residual dup is an error
        for n, c in counts.items():
            if c > 1:
                raise SqlError(f"ambiguous column {n!r} after aliasing")
        return out

    # ------------------------------------------------------- qualification

    def _qualify(self, e: ex.Expr, relations: List[Relation],
                 col_owner: Dict[str, str], lenient: bool = False) -> ex.Expr:
        by_alias = {r.alias: r for r in relations}
        if isinstance(e, ex.ColumnRef):
            if e.relation is not None:
                rel = by_alias.get(e.relation)
                if rel is None:
                    raise SqlError(f"unknown table alias {e.relation!r}")
                if e.column not in rel.rename:
                    raise SqlError(
                        f"column {e.column!r} not in {e.relation!r}"
                    )
                return ex.ColumnRef(rel.rename[e.column])
            if e.column in col_owner:
                return e
            # maybe the bare name was renamed by a self-join: unique match?
            hits = [
                r.rename[e.column] for r in relations if e.column in r.rename
            ]
            if len(hits) == 1:
                return ex.ColumnRef(hits[0])
            if len(hits) > 1:
                raise SqlError(f"ambiguous column {e.column!r}")
            if lenient:
                # may be a SELECT alias / ordinal; resolved later against
                # the output schema
                return e
            raise SqlError(f"unknown column {e.column!r}")
        if isinstance(e, (ex.ScalarSubquery, ex.Exists, ex.InSubquery)):
            return self._qualify_subquery_expr(e, relations, col_owner)
        for attr in ("expr", "left", "right", "base", "otherwise"):
            if hasattr(e, attr) and isinstance(getattr(e, attr), ex.Expr):
                setattr(e, attr, self._qualify(getattr(e, attr), relations,
                                               col_owner, lenient))
        if hasattr(e, "args"):
            e.args = [self._qualify(a, relations, col_owner, lenient)
                      for a in e.args]
        if hasattr(e, "list"):
            e.list = [self._qualify(a, relations, col_owner, lenient)
                      for a in e.list]
        if hasattr(e, "branches"):
            e.branches = [
                (self._qualify(w, relations, col_owner, lenient),
                 self._qualify(t, relations, col_owner, lenient))
                for w, t in e.branches
            ]
        return e

    def _qualify_subquery_expr(self, e, relations, col_owner):
        if isinstance(e, ex.InSubquery):
            e.expr = self._qualify(e.expr, relations, col_owner)
        if isinstance(e, ex.ScalarSubquery) and e.plan is None:
            try:
                e.plan = self.plan(e.query)  # uncorrelated
            except SqlError:
                # correlated: left for decorrelation at the WHERE level
                e.plan = None
        return e

    # --------------------------------------------- subquery predicate lowering

    def _extract_subquery_predicates(self, conjuncts, relations, col_owner):
        """IN/EXISTS conjuncts -> semi/anti join specs.

        Returns (specs, remaining_conjuncts). A spec is
        (sub_plan, outer_col, sub_col, how).
        """
        specs = []  # (sub_plan, on_pairs [(outer_col, sub_col)], how)
        remaining = []
        self._corr_counter = getattr(self, "_corr_counter", 0)
        for c in conjuncts:
            neg = False
            node = c
            if isinstance(node, ex.Not) and isinstance(node.expr,
                                                       (ex.Exists, ex.InSubquery)):
                neg = True
                node = node.expr
            if isinstance(node, ex.InSubquery):
                negated = neg or node.negated
                inner = ex.strip_alias(node.expr)
                if not isinstance(inner, ex.ColumnRef):
                    raise SqlError("IN-subquery requires a column on the left")
                sub_plan = self.plan(node.query)
                sub_cols = sub_plan.schema().names()
                if len(sub_cols) != 1:
                    raise SqlError("IN-subquery must produce one column")
                specs.append(
                    (sub_plan, [(inner.column, sub_cols[0])],
                     "anti" if negated else "semi", negated)
                )
                continue
            if isinstance(node, ex.Exists):
                negated = neg or node.negated
                plan_, on_pairs, how, pred = self._decorrelate_exists(
                    node.query, relations, col_owner, negated
                )
                specs.append((plan_, on_pairs, how, False))
                if pred is not None:
                    remaining.append(pred)
                continue
            # correlated scalar subquery comparison: expr OP (SELECT agg ...)
            handled = self._try_correlated_scalar(
                node, relations, col_owner, specs, remaining
            )
            if handled:
                continue
            remaining.append(c)
        return specs, remaining

    def _try_correlated_scalar(self, node, relations, col_owner, specs,
                               remaining) -> bool:
        """lhs OP (correlated scalar subquery) -> derived group-by aggregate
        joined on the correlation keys + plain comparison (classic
        decorrelation; covers TPC-H q2/q17/q20)."""
        if not (isinstance(node, ex.BinaryExpr) and node.op in ex.CMP_OPS):
            return False
        lhs, rhs = node.left, node.right
        flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=",
                "!=": "!="}
        op = node.op
        if isinstance(lhs, ex.ScalarSubquery) and lhs.plan is None:
            lhs, rhs, op = rhs, lhs, flip[op]
        if not (isinstance(rhs, ex.ScalarSubquery) and rhs.plan is None):
            return False
        sub_q: Query = rhs.query
        if len(sub_q.items) != 1 or sub_q.items[0].star:
            raise SqlError("correlated scalar subquery must select one expr")

        from ..optimizer import conjoin, split_conjuncts

        inner_rels = self._resolve_relations(sub_q)
        inner_owner = self._column_owners(inner_rels)
        corr_edges: List[Tuple[str, str]] = []
        residual: List[ex.Expr] = []
        if sub_q.where is not None:
            for c in split_conjuncts(sub_q.where):
                edge = self._correlation_edge(c, inner_rels, inner_owner,
                                              relations, col_owner)
                if edge is not None:
                    corr_edges.append(edge)
                else:
                    residual.append(c)
        if not corr_edges:
            raise SqlError(
                "correlated scalar subquery without equality correlation"
            )
        if len(corr_edges) > 2:
            raise SqlError(">2 correlation columns (round 2)")

        self._corr_counter += 1
        n = self._corr_counter
        key_aliases = [f"__corr_key{n}_{i}" for i in range(len(corr_edges))]
        val_alias = f"__corr_val{n}"
        derived_q = Query(
            items=[
                SelectItem(ex.ColumnRef(ic), ka)
                for (_, ic), ka in zip(corr_edges, key_aliases)
            ] + [SelectItem(sub_q.items[0].expr, val_alias)],
            from_table=sub_q.from_table,
            joins=sub_q.joins,
            where=conjoin(residual) if residual else None,
            group_by=[ex.ColumnRef(ic) for _, ic in corr_edges],
            having=None, order_by=[], limit=None,
        )
        derived = self.plan(derived_q)
        on_pairs = [
            (oc, ka) for (oc, _), ka in zip(corr_edges, key_aliases)
        ]
        specs.append((derived, on_pairs, "inner", False))
        remaining.append(
            ex.BinaryExpr(lhs, op, ex.ColumnRef(val_alias))
        )
        return True

    def _decorrelate_exists(self, sub_q: Query, outer_relations, outer_owner,
                            negated: bool):
        """EXISTS decorrelation.

        Returns (plan, on_pairs, how, residual_pred_or_None).

        Equality-only correlation -> plain semi/anti join (pred None).

        One extra ``inner_col <> outer_col`` correlated conjunct (the q21
        shape) -> group the inner rows by the equality key computing
        count(val)/min(val)/max(val) of the <>-column (count of NON-NULL
        values, so all-NULL groups behave like SQL's unknown comparisons),
        LEFT JOIN that derived table, and test via min/max:
          EXISTS     <=> __c > 0 AND (__mn <> x OR __mx <> x)
          NOT EXISTS <=> __c IS NULL OR __c = 0 OR (__mn = x AND __mx = x)
        """
        from ..optimizer import conjoin, split_conjuncts

        inner_rels = self._resolve_relations(sub_q)
        inner_owner = self._column_owners(inner_rels)
        corr_edges: List[Tuple[str, str]] = []  # (outer_col, inner_col)
        neq_edges: List[Tuple[str, str]] = []  # (outer_col, inner_col)
        inner_conjs: List[ex.Expr] = []
        if sub_q.where is not None:
            for c in split_conjuncts(sub_q.where):
                edge = self._correlation_edge(c, inner_rels, inner_owner,
                                              outer_relations, outer_owner)
                if edge is not None:
                    corr_edges.append(edge)
                    continue
                nedge = self._correlation_edge(
                    c, inner_rels, inner_owner, outer_relations, outer_owner,
                    op="!=",
                )
                if nedge is not None:
                    neq_edges.append(nedge)
                    continue
                inner_conjs.append(self._qualify(c, inner_rels, inner_owner))
        if not corr_edges:
            raise SqlError(
                "EXISTS subquery without equality correlation unsupported"
            )
        if len(corr_edges) > 1:
            raise SqlError("multi-column EXISTS correlation (round 2)")
        if len(neq_edges) > 1:
            raise SqlError("multiple <> correlations in EXISTS (round 2)")
        outer_col, inner_col = corr_edges[0]

        if not neq_edges:
            # plain semi/anti join
            inner_q = Query(
                items=[SelectItem(ex.ColumnRef(inner_col), None)],
                from_table=sub_q.from_table, joins=sub_q.joins, where=None,
                group_by=[], having=None, order_by=[], limit=None,
            )
            plan, remaining = self._plan_joins(
                inner_q, inner_rels, inner_owner, inner_conjs, []
            )
            if remaining:
                plan = Filter(conjoin(remaining), plan)
            plan = Projection([ex.ColumnRef(inner_col)], plan)
            return (plan, [(outer_col, inner_col)],
                    "anti" if negated else "semi", None)

        # generalized (q21): derived per-key count/min/max of the <> column
        neq_outer, neq_inner = neq_edges[0]
        self._corr_counter = getattr(self, "_corr_counter", 0) + 1
        n = self._corr_counter
        ck, cc, mn, mx = (f"__ex_key{n}", f"__ex_cnt{n}", f"__ex_min{n}",
                          f"__ex_max{n}")
        body, remaining = self._plan_joins(
            Query(items=[], from_table=sub_q.from_table, joins=sub_q.joins,
                  where=None, group_by=[], having=None, order_by=[],
                  limit=None),
            inner_rels, inner_owner, inner_conjs, [],
        )
        if remaining:
            body = Filter(conjoin(remaining), body)
        derived = Aggregate(
            [ex.ColumnRef(inner_col).alias(ck)],
            [
                # count of NON-NULL <>-values: all-NULL groups compare
                # unknown in SQL, matching cc = 0 here
                ex.count(ex.ColumnRef(neq_inner)).alias(cc),
                ex.min_(ex.ColumnRef(neq_inner)).alias(mn),
                ex.max_(ex.ColumnRef(neq_inner)).alias(mx),
            ],
            body,
        )
        x = ex.ColumnRef(neq_outer)
        zero = ex.Literal(0, ex.Int64)
        if negated:
            pred = ex.BinaryExpr(
                ex.BinaryExpr(
                    ex.IsNull(ex.ColumnRef(cc)), "or",
                    ex.BinaryExpr(ex.ColumnRef(cc), "=", zero),
                ),
                "or",
                ex.BinaryExpr(
                    ex.BinaryExpr(ex.ColumnRef(mn), "=", x), "and",
                    ex.BinaryExpr(ex.ColumnRef(mx), "=", x),
                ),
            )
        else:
            pred = ex.BinaryExpr(
                ex.BinaryExpr(ex.ColumnRef(cc), ">", zero), "and",
                ex.BinaryExpr(
                    ex.BinaryExpr(ex.ColumnRef(mn), "!=", x), "or",
                    ex.BinaryExpr(ex.ColumnRef(mx), "!=", x),
                ),
            )
        return (derived, [(outer_col, ck)], "left", pred)

    def _correlation_edge(self, c, inner_rels, inner_owner, outer_rels,
                          outer_owner, op: str = "="):
        """outer_col OP inner_col cross-scope conjunct, else None."""
        if not (isinstance(c, ex.BinaryExpr) and c.op == op):
            return None
        sides = [c.left, c.right]
        if not all(isinstance(s, ex.ColumnRef) for s in sides):
            return None

        def resolve(ref, rels, owner):
            try:
                q = self._qualify(
                    ex.ColumnRef(ref.column, ref.relation), rels, owner
                )
                return q.column
            except SqlError:
                return None

        for a, b in ((0, 1), (1, 0)):
            # SQL scoping: a column resolvable in the INNER scope binds
            # there; the correlated side is the one that only resolves in
            # the outer scope
            inner_c = resolve(sides[a], inner_rels, inner_owner)
            inner_of_b = resolve(sides[b], inner_rels, inner_owner)
            outer_c = resolve(sides[b], outer_rels, outer_owner)
            if inner_c and outer_c and inner_of_b is None:
                return (outer_c, inner_c)
        return None

    # ------------------------------------------------------------ join graph

    def _plan_joins(self, q: Query, relations: List[Relation],
                    col_owner: Dict[str, str], conjuncts, semi_specs):
        """Greedy join chain; returns (plan, leftover conjuncts)."""

        def owners(e: ex.Expr) -> Set[str]:
            return {col_owner[c] for c in ex.referenced_columns(e)
                    if c in col_owner}

        join_edges: List[Tuple[str, str, str, str]] = []
        table_filters: Dict[str, List[ex.Expr]] = {r.alias: [] for r in relations}
        post: List[ex.Expr] = []
        # WHERE predicates must run post-join for any null-extended side:
        # the right table of a LEFT JOIN, or everything else under a RIGHT
        # JOIN (conservative)
        explicit_joins = {
            (j.table.alias or j.table.name): j.how for j in q.joins
            if j.how != "cross"
        }
        no_push = {a for a, h in explicit_joins.items() if h == "left"}
        any_right = any(h == "right" for h in explicit_joins.values())

        def classify(c: ex.Expr, from_where: bool = True):
            if (
                isinstance(c, ex.BinaryExpr) and c.op == "="
                and isinstance(c.left, ex.ColumnRef)
                and isinstance(c.right, ex.ColumnRef)
            ):
                o1 = col_owner.get(c.left.column)
                o2 = col_owner.get(c.right.column)
                if o1 and o2 and o1 != o2:
                    join_edges.append((o1, c.left.column, o2, c.right.column))
                    return
            refs = ex.referenced_columns(c)
            if any(r not in col_owner for r in refs):
                # references a subquery-derived column (__corr_val...):
                # must run after those joins are applied
                post.append(c)
                return
            os_ = owners(c)
            if len(os_) == 1:
                owner = next(iter(os_))
                if from_where and (owner in no_push or any_right):
                    post.append(c)
                else:
                    table_filters[owner].append(c)
            else:
                post.append(c)

        for c in conjuncts:
            classify(c, from_where=True)

        explicit_how: Dict[str, str] = {}
        for j in q.joins:
            alias = j.table.alias or j.table.name
            if j.how != "cross":
                explicit_how[alias] = j.how
            if j.on is not None:
                from ..optimizer import split_conjuncts

                for c in split_conjuncts(j.on):
                    # ON-clause filters DO apply pre-join on the new table
                    classify(self._qualify(c, relations, col_owner),
                             from_where=False)

        def filtered_plan(rel: Relation) -> LogicalPlan:
            from ..optimizer import conjoin

            p = rel.plan
            if table_filters[rel.alias]:
                p = Filter(conjoin(table_filters[rel.alias]), p)
            return p

        if len(relations) == 1:
            plan: LogicalPlan = relations[0].plan
            leftover = table_filters[relations[0].alias] + post
        else:
            plan, leftover = self._join_chain(
                relations, join_edges, explicit_how, filtered_plan, post
            )

        # apply subquery-derived joins (semi/anti/correlated-scalar) on top
        for sub_plan, on_pairs, how, null_aware in semi_specs:
            if how == "inner":
                # derived aggregates have unique group keys: put them on
                # the build (left) side for the FK fast path
                plan = Join(sub_plan, plan,
                            [(s_, o) for o, s_ in on_pairs], how)
            else:
                plan = Join(plan, sub_plan, list(on_pairs), how,
                            null_aware=null_aware)
        return plan, leftover

    def _join_chain(self, relations, join_edges, explicit_how, filtered_plan,
                    post):
        by_alias = {r.alias: r for r in relations}
        joined: Set[str] = {relations[0].alias}
        plan = filtered_plan(relations[0])
        acc_unique: Set[str] = set()
        if relations[0].primary_key:
            acc_unique.add(relations[0].primary_key)
        pending = [r.alias for r in relations[1:]]
        edges = list(join_edges)

        while pending:
            progress = False
            for alias in list(pending):
                # collect ALL edges connecting alias to the joined set;
                # every equality edge becomes a composite join key (the
                # join kernels rank arbitrary key tuples against the
                # build side, so there is no column-count cap — and outer
                # joins MUST put every condition in the ON clause, a
                # post filter would drop preserved rows)
                mine: List[Tuple[Tuple[str, str], tuple]] = []
                for e_ in edges:
                    a1, c1, a2, c2 = e_
                    if a1 == alias and a2 in joined:
                        mine.append(((c1, c2), e_))
                    elif a2 == alias and a1 in joined:
                        mine.append(((c2, c1), e_))
                if not mine:
                    continue
                key_pairs = [p for p, _ in mine]  # (t_col, acc_col)
                t_alias = alias
                rel = by_alias[t_alias]
                t_plan = filtered_plan(rel)
                how = explicit_how.get(t_alias, "inner")
                t_col = key_pairs[0][0]
                acc_col = key_pairs[0][1]
                if len(key_pairs) >= 2 and how == "inner":
                    # composite join: build the new table (runtime
                    # uniqueness detection picks the fast path when the
                    # composite key is unique, e.g. partsupp)
                    on = [(t, a) for t, a in key_pairs]
                    plan = Join(t_plan, plan, on, how)
                elif len(key_pairs) >= 2:
                    # outer joins preserve the accumulated side
                    on = [(a, t) for t, a in key_pairs]
                    plan = Join(plan, t_plan, on, how)
                    acc_unique = set()
                elif rel.primary_key == t_col and how == "inner":
                    plan = Join(t_plan, plan, [(t_col, acc_col)], how)
                elif acc_col in acc_unique and how == "inner":
                    plan = Join(plan, t_plan, [(acc_col, t_col)], how)
                    acc_unique = (
                        {rel.primary_key} if rel.primary_key else set()
                    )
                elif how in ("left", "right", "full"):
                    # outer joins: the accumulated side is the logical left
                    plan = Join(plan, t_plan, [(acc_col, t_col)], how)
                    acc_unique = set()
                else:
                    plan = Join(t_plan, plan, [(t_col, acc_col)], how)
                joined.add(t_alias)
                pending.remove(t_alias)
                for _, e_ in mine:
                    edges.remove(e_)
                resolved = [
                    e_ for e_ in edges if e_[0] in joined and e_[2] in joined
                ]
                for a1, c1, a2, c2 in resolved:
                    post.append(
                        ex.BinaryExpr(ex.ColumnRef(c1), "=", ex.ColumnRef(c2))
                    )
                edges = [e_ for e_ in edges if e_ not in resolved]
                progress = True
            if not progress:
                raise SqlError(
                    f"no join condition connects tables {pending} to the rest"
                )
        return plan, post

    # -------------------------------------------------- SELECT/agg/order/limit

    def _plan_select(self, q: Query, plan: LogicalPlan,
                     relations, col_owner) -> LogicalPlan:
        in_schema = plan.schema()

        items: List[SelectItem] = []
        for it in q.items:
            if it.star:
                for n in in_schema.names():
                    items.append(SelectItem(ex.ColumnRef(n), None))
            else:
                e = self._qualify(it.expr, relations, col_owner)
                items.append(SelectItem(e, it.alias))

        select_exprs = [
            it.expr.alias(it.alias) if it.alias else it.expr for it in items
        ]

        group_exprs: List[ex.Expr] = []
        for g in q.group_by:
            g = self._resolve_ref(
                self._qualify(g, relations, col_owner, lenient=True),
                items, in_schema,
            )
            group_exprs.append(g)

        having = (
            self._qualify(q.having, relations, col_owner, lenient=True)
            if q.having is not None else None
        )
        order_items = [
            OrderItem(self._qualify(oi.expr, relations, col_owner,
                                    lenient=True),
                      oi.ascending, oi.nulls_first)
            for oi in q.order_by
        ]

        has_aggs = any(self._contains_agg(e) for e in select_exprs) or (
            having is not None and self._contains_agg(having)
        )
        distinct = q.distinct

        if group_exprs or has_aggs:
            plan = self._plan_aggregate(q, plan, select_exprs, group_exprs,
                                        having, order_items)
        else:
            if distinct:
                proj = Projection(select_exprs, plan)
                names = proj.schema().names()
                plan = Aggregate([ex.ColumnRef(n) for n in names], [], proj)
                distinct = False
            else:
                plan = Projection(select_exprs, plan)

        out_schema = plan.schema()

        if order_items:
            sort_exprs = []
            for oi in order_items:
                e = self._resolve_order_ref(oi.expr, items, out_schema)
                sort_exprs.append(ex.SortExpr(e, oi.ascending,
                                              bool(oi.nulls_first)))
            plan = Sort(sort_exprs, plan)

        if q.limit is not None:
            plan = Limit(q.limit, plan)
        return plan

    def _plan_aggregate(self, q: Query, plan, select_exprs, group_exprs,
                        having, order_items):
        aggs: List[ex.AggregateExpr] = []

        def collect(e: ex.Expr):
            for node in ex.walk(e):
                if isinstance(node, ex.AggregateExpr):
                    if not any(node is a or a.name() == node.name() for a in aggs):
                        aggs.append(node)

        for e in select_exprs:
            collect(e)
        if having is not None:
            collect(having)
        for oi in order_items:
            collect(oi.expr)

        # COUNT(DISTINCT x) -> two-level aggregate rewrite
        distinct_aggs = [a for a in aggs if a.fn == "count_distinct"]
        if distinct_aggs:
            if len(distinct_aggs) != len(aggs):
                raise SqlError(
                    "mixing COUNT(DISTINCT) with other aggregates (round 2)"
                )
            if len(distinct_aggs) > 1:
                raise SqlError("multiple COUNT(DISTINCT) aggregates (round 2)")
            da = distinct_aggs[0]
            inner = Aggregate(group_exprs + [da.expr], [], plan)
            inner_names = inner.schema().names()
            outer_groups = [ex.ColumnRef(n) for n in inner_names[:-1]]
            counted = ex.AggregateExpr(
                "count", ex.ColumnRef(inner_names[-1])
            ).alias(da.name())
            agg_plan = Aggregate(outer_groups, [counted], inner)
        else:
            agg_plan = Aggregate(group_exprs, list(aggs), plan)
        agg_schema = agg_plan.schema()

        group_names = {g.name() for g in group_exprs}

        def rewrite(e: ex.Expr) -> ex.Expr:
            if isinstance(e, ex.Alias):
                return ex.Alias(rewrite(e.expr), e.alias_name)
            if isinstance(e, ex.AggregateExpr):
                return ex.ColumnRef(e.name())
            if e.name() in group_names:
                return ex.ColumnRef(e.name())
            for attr in ("expr", "left", "right", "base", "otherwise"):
                if hasattr(e, attr) and isinstance(getattr(e, attr), ex.Expr):
                    setattr(e, attr, rewrite(getattr(e, attr)))
            if hasattr(e, "args"):
                e.args = [rewrite(a) for a in e.args]
            if hasattr(e, "list"):
                e.list = [rewrite(a) for a in e.list]
            if hasattr(e, "branches"):
                e.branches = [(rewrite(w), rewrite(t)) for w, t in e.branches]
            return e

        out: LogicalPlan = agg_plan
        if having is not None:
            out = Filter(rewrite(having), out)
        projected = [rewrite(e) for e in select_exprs]
        for e in projected:
            for node in ex.walk(e):
                if isinstance(node, ex.ColumnRef) and not agg_schema.has_field(
                    node.column
                ):
                    raise SqlError(
                        f"column {node.column!r} is neither grouped nor aggregated"
                    )
        return Projection(projected, out)

    # ---------------------------------------------------- reference helpers

    def _resolve_ref(self, e: ex.Expr, items: List[SelectItem], schema: Schema):
        if isinstance(e, ex.Literal) and e.dtype.is_integer and items:
            idx = int(e.value) - 1
            if 0 <= idx < len(items):
                return items[idx].expr
            raise SqlError(f"ordinal {e.value} out of range")
        if isinstance(e, ex.ColumnRef) and not schema.has_field(e.column):
            for it in items:
                if it.alias == e.column:
                    return it.expr
        return e

    def _resolve_order_ref(self, e: ex.Expr, items, out_schema: Schema):
        if isinstance(e, ex.Literal) and e.dtype.is_integer:
            idx = int(e.value) - 1
            names = out_schema.names()
            if 0 <= idx < len(names):
                return ex.ColumnRef(names[idx])
            raise SqlError(f"ordinal {e.value} out of range")
        if isinstance(e, ex.AggregateExpr):
            if out_schema.has_field(e.name()):
                return ex.ColumnRef(e.name())
            raise SqlError(f"ORDER BY aggregate {e.name()} not in output")
        if isinstance(e, ex.ColumnRef):
            if out_schema.has_field(e.column):
                return e
            for it in items:
                if it.alias == e.column:
                    return it.expr
            raise SqlError(f"unknown ORDER BY column {e.column!r}")
        return e

    def _contains_agg(self, e: ex.Expr) -> bool:
        return any(isinstance(n, ex.AggregateExpr) for n in ex.walk(e))
