"""Plan visualization + misc utilities.

(reference: rust/core/src/utils.rs:96-290 — format_plan pretty-printers and
``produce_diagram``, a GraphViz dot rendering of the stage DAG.)
"""

from __future__ import annotations

from typing import List

from .physical.base import PhysicalPlan
from .physical.shuffle import QueryStageExec, UnresolvedShuffleExec


def produce_diagram(stages: List[QueryStageExec]) -> str:
    """GraphViz dot of a job's stage DAG: one cluster per stage, edges from
    producing stages into the UnresolvedShuffle readers that consume them."""
    out = ["digraph G {", '  rankdir="BT";']
    node_ids = {}
    counter = [0]

    def emit(plan: PhysicalPlan, stage_idx: int) -> str:
        nid = f"s{stage_idx}_n{counter[0]}"
        counter[0] += 1
        label = plan.display().replace('"', "'")
        out.append(f'    {nid} [shape=box, label="{label}"];')
        for child in plan.children():
            cid = emit(child, stage_idx)
            out.append(f"    {cid} -> {nid};")
        if isinstance(plan, UnresolvedShuffleExec):
            for sid in plan.query_stage_ids:
                node_ids.setdefault(("shuffle_in", sid), []).append(nid)
        return nid

    for stage in stages:
        out.append(f"  subgraph cluster_{stage.stage_id} {{")
        out.append(f'    label = "Stage {stage.stage_id}";')
        root = emit(stage.child, stage.stage_id)
        node_ids[("stage_root", stage.stage_id)] = root
        out.append("  }")

    # cross-stage edges: producing stage root -> consuming shuffle node
    for (kind, sid), nids in list(node_ids.items()):
        if kind != "shuffle_in":
            continue
        root = node_ids.get(("stage_root", sid))
        if root:
            for nid in nids:
                out.append(f"  {root} -> {nid} [style=dashed];")
    out.append("}")
    return "\n".join(out)
