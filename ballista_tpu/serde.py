"""Plan/expression <-> protobuf serde.

Equivalent of the reference's serde layer (reference:
rust/core/src/serde/logical_plan/{to_proto.rs,from_proto.rs} and
serde/physical_plan/*; its roundtrip tests at serde/logical_plan/mod.rs:
20-920 are the model for tests/test_serde.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .datatypes import (
    Boolean,
    DataType,
    Date32,
    Decimal,
    Field,
    Float32,
    Float64,
    Int32,
    Int64,
    Schema,
    Utf8,
)
from .errors import SerdeError
from . import expr as ex
from . import logical as lp
from .proto import ballista_pb2 as pb

# ---------------------------------------------------------------------------
# Types
# ---------------------------------------------------------------------------


def dtype_to_proto(dt: DataType) -> pb.DataType:
    p = pb.DataType(kind=dt.kind, scale=dt.scale)
    if dt.kind == "list":
        p.element_kind = dt.element.kind
        p.element_scale = dt.element.scale
        p.length = dt.length
    return p


def dtype_from_proto(p: pb.DataType) -> DataType:
    if p.kind == "decimal":
        return Decimal(p.scale)
    if p.kind == "list":
        from .datatypes import FixedSizeList

        elem = (Decimal(p.element_scale) if p.element_kind == "decimal"
                else DataType(p.element_kind))
        return FixedSizeList(elem, p.length)
    return DataType(p.kind)


def schema_to_proto(s: Schema) -> pb.Schema:
    return pb.Schema(
        fields=[
            pb.Field(name=f.name, dtype=dtype_to_proto(f.dtype), nullable=f.nullable)
            for f in s.fields
        ]
    )


def schema_from_proto(p: pb.Schema) -> Schema:
    return Schema(
        [Field(f.name, dtype_from_proto(f.dtype), f.nullable) for f in p.fields]
    )


# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------


def expr_to_proto(e: ex.Expr) -> pb.LogicalExprNode:
    n = pb.LogicalExprNode()
    if isinstance(e, ex.ColumnRef):
        n.column.column = e.column
        n.column.relation = e.relation or ""
    elif isinstance(e, ex.Literal):
        sv = n.literal
        sv.dtype.CopyFrom(dtype_to_proto(e.dtype))
        if e.value is None:
            sv.null_value = True
        elif e.dtype == Boolean:
            sv.bool_value = bool(e.value)
        elif e.dtype == Date32:
            sv.date_value = int(e.value)
        elif e.dtype.kind == "utf8":
            sv.string_value = str(e.value)
        elif e.dtype.is_integer or e.dtype.kind == "decimal":
            sv.int_value = int(e.value)
        else:
            sv.float_value = float(e.value)
    elif isinstance(e, ex.BinaryExpr):
        n.binary.left.CopyFrom(expr_to_proto(e.left))
        n.binary.op = e.op
        n.binary.right.CopyFrom(expr_to_proto(e.right))
    elif isinstance(e, ex.Not):
        n.not_expr.CopyFrom(expr_to_proto(e.expr))
    elif isinstance(e, ex.IsNull):
        n.is_null.CopyFrom(expr_to_proto(e.expr))
    elif isinstance(e, ex.IsNotNull):
        n.is_not_null.CopyFrom(expr_to_proto(e.expr))
    elif isinstance(e, ex.Alias):
        n.alias.expr.CopyFrom(expr_to_proto(e.expr))
        n.alias.alias = e.alias_name
    elif isinstance(e, ex.Cast):
        n.cast.expr.CopyFrom(expr_to_proto(e.expr))
        n.cast.dtype.CopyFrom(dtype_to_proto(e.dtype))
    elif isinstance(e, ex.InList):
        n.in_list.expr.CopyFrom(expr_to_proto(e.expr))
        for item in e.list:
            n.in_list.list.append(expr_to_proto(item))
        n.in_list.negated = e.negated
    elif isinstance(e, ex.Like):
        n.like.expr.CopyFrom(expr_to_proto(e.expr))
        n.like.pattern = e.pattern
        n.like.negated = e.negated
    elif isinstance(e, ex.Case):
        if e.base is not None:
            n.case_expr.base.CopyFrom(expr_to_proto(e.base))
        for w, t in e.branches:
            b = n.case_expr.branches.add()
            b.when.CopyFrom(expr_to_proto(w))
            b.then.CopyFrom(expr_to_proto(t))
        if e.otherwise is not None:
            n.case_expr.otherwise.CopyFrom(expr_to_proto(e.otherwise))
    elif isinstance(e, ex.ScalarFunction):
        n.scalar_fn.fn = e.fn
        for a in e.args:
            n.scalar_fn.args.append(expr_to_proto(a))
    elif isinstance(e, ex.AggregateExpr):
        n.aggregate.fn = e.fn
        n.aggregate.expr.CopyFrom(expr_to_proto(e.expr))
        n.aggregate.is_star = e.is_star
    elif isinstance(e, ex.SortExpr):
        n.sort.expr.CopyFrom(expr_to_proto(e.expr))
        n.sort.ascending = e.ascending
        n.sort.nulls_first = e.nulls_first
    else:
        raise SerdeError(f"cannot serialize expr {type(e).__name__}")
    return n


def expr_from_proto(n: pb.LogicalExprNode) -> ex.Expr:
    kind = n.WhichOneof("expr_type")
    if kind == "column":
        return ex.ColumnRef(n.column.column, n.column.relation or None)
    if kind == "literal":
        sv = n.literal
        dt = dtype_from_proto(sv.dtype)
        which = sv.WhichOneof("value")
        if which == "null_value":
            return ex.Literal(None, dt)
        if which == "bool_value":
            return ex.Literal(sv.bool_value, dt)
        if which == "date_value":
            return ex.Literal(sv.date_value, dt)
        if which == "string_value":
            return ex.Literal(sv.string_value, dt)
        if which == "int_value":
            return ex.Literal(sv.int_value, dt)
        if which == "float_value":
            return ex.Literal(sv.float_value, dt)
        raise SerdeError("literal without value")
    if kind == "binary":
        return ex.BinaryExpr(
            expr_from_proto(n.binary.left), n.binary.op,
            expr_from_proto(n.binary.right),
        )
    if kind == "not_expr":
        return ex.Not(expr_from_proto(n.not_expr))
    if kind == "is_null":
        return ex.IsNull(expr_from_proto(n.is_null))
    if kind == "is_not_null":
        return ex.IsNotNull(expr_from_proto(n.is_not_null))
    if kind == "alias":
        return ex.Alias(expr_from_proto(n.alias.expr), n.alias.alias)
    if kind == "cast":
        return ex.Cast(expr_from_proto(n.cast.expr), dtype_from_proto(n.cast.dtype))
    if kind == "in_list":
        return ex.InList(
            expr_from_proto(n.in_list.expr),
            [expr_from_proto(i) for i in n.in_list.list],
            n.in_list.negated,
        )
    if kind == "like":
        return ex.Like(expr_from_proto(n.like.expr), n.like.pattern, n.like.negated)
    if kind == "case_expr":
        base = (
            expr_from_proto(n.case_expr.base)
            if n.case_expr.HasField("base") else None
        )
        otherwise = (
            expr_from_proto(n.case_expr.otherwise)
            if n.case_expr.HasField("otherwise") else None
        )
        return ex.Case(
            base,
            [(expr_from_proto(b.when), expr_from_proto(b.then))
             for b in n.case_expr.branches],
            otherwise,
        )
    if kind == "scalar_fn":
        return ex.ScalarFunction(
            n.scalar_fn.fn, [expr_from_proto(a) for a in n.scalar_fn.args]
        )
    if kind == "aggregate":
        return ex.AggregateExpr(
            n.aggregate.fn, expr_from_proto(n.aggregate.expr), n.aggregate.is_star
        )
    if kind == "sort":
        return ex.SortExpr(
            expr_from_proto(n.sort.expr), n.sort.ascending, n.sort.nulls_first
        )
    raise SerdeError(f"unknown expr node {kind}")


# ---------------------------------------------------------------------------
# Table sources
# ---------------------------------------------------------------------------


def source_to_proto(src: lp.TableSource, primary_key: Optional[str] = None
                    ) -> pb.TableSourceDesc:
    d = src.source_descriptor()
    return pb.TableSourceDesc(
        kind=d.get("kind", ""),
        path=d.get("path", ""),
        delimiter=d.get("delimiter", ""),
        has_header=bool(d.get("has_header", False)),
        schema=schema_to_proto(src.table_schema()),
        primary_key=primary_key or "",
        num_partitions=d.get("num_partitions", 0),
        # system sources: the snapshot rows, materialized at
        # serialization time (observability/systables.py)
        payload=d.get("rows_json", "").encode(),
    )


def source_from_proto(p: pb.TableSourceDesc) -> lp.TableSource:
    from .io import CsvSource, ParquetSource, TblSource

    schema = schema_from_proto(p.schema)
    if p.kind == "tbl":
        return TblSource(p.path, schema)
    if p.kind == "csv":
        return CsvSource(p.path, schema, has_header=p.has_header,
                         delimiter=p.delimiter or ",")
    if p.kind == "parquet":
        return ParquetSource(p.path, schema)
    if p.kind == "system":
        import json

        from .observability.systables import SystemTableSource

        return SystemTableSource(p.path,
                                 rows=json.loads(p.payload.decode()))
    raise SerdeError(f"source kind {p.kind!r} is not remotable")


# ---------------------------------------------------------------------------
# Logical plans
# ---------------------------------------------------------------------------


def plan_to_proto(plan: lp.LogicalPlan) -> pb.LogicalPlanNode:
    n = pb.LogicalPlanNode()
    if isinstance(plan, lp.TableScan):
        n.scan.table_name = plan.table_name
        n.scan.source.CopyFrom(source_to_proto(plan.source))
        if plan.projection is not None:
            n.scan.has_projection = True
            n.scan.projection.extend(plan.projection)
    elif isinstance(plan, lp.Projection):
        n.projection.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.exprs:
            n.projection.exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.Filter):
        n.filter.input.CopyFrom(plan_to_proto(plan.input))
        n.filter.predicate.CopyFrom(expr_to_proto(plan.predicate))
    elif isinstance(plan, lp.Aggregate):
        n.aggregate.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.group_exprs:
            n.aggregate.group_exprs.append(expr_to_proto(e))
        for e in plan.agg_exprs:
            n.aggregate.agg_exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.Join):
        n.join.left.CopyFrom(plan_to_proto(plan.left))
        n.join.right.CopyFrom(plan_to_proto(plan.right))
        for l, r in plan.on:
            o = n.join.on.add()
            o.left_col = l
            o.right_col = r
        n.join.how = plan.how
        n.join.null_aware = plan.null_aware
    elif isinstance(plan, lp.Sort):
        n.sort.input.CopyFrom(plan_to_proto(plan.input))
        for e in plan.sort_exprs:
            n.sort.sort_exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.Limit):
        n.limit.input.CopyFrom(plan_to_proto(plan.input))
        n.limit.n = plan.n
    elif isinstance(plan, lp.Repartition):
        n.repartition.input.CopyFrom(plan_to_proto(plan.input))
        n.repartition.num_partitions = plan.num_partitions
        for e in plan.hash_exprs or []:
            n.repartition.hash_exprs.append(expr_to_proto(e))
    elif isinstance(plan, lp.EmptyRelation):
        n.empty.produce_one_row = plan.produce_one_row
    elif isinstance(plan, lp.Explain):
        n.explain.input.CopyFrom(plan_to_proto(plan.input))
        n.explain.verbose = plan.verbose
        n.explain.analyze = plan.analyze
    else:
        raise SerdeError(f"cannot serialize plan {type(plan).__name__}")
    return n


def plan_from_proto(n: pb.LogicalPlanNode) -> lp.LogicalPlan:
    kind = n.WhichOneof("plan_type")
    if kind == "scan":
        src = source_from_proto(n.scan.source)
        proj = tuple(n.scan.projection) if n.scan.has_projection else None
        return lp.TableScan(n.scan.table_name, src, proj)
    if kind == "projection":
        return lp.Projection(
            [expr_from_proto(e) for e in n.projection.exprs],
            plan_from_proto(n.projection.input),
        )
    if kind == "filter":
        return lp.Filter(
            expr_from_proto(n.filter.predicate), plan_from_proto(n.filter.input)
        )
    if kind == "aggregate":
        return lp.Aggregate(
            [expr_from_proto(e) for e in n.aggregate.group_exprs],
            [expr_from_proto(e) for e in n.aggregate.agg_exprs],
            plan_from_proto(n.aggregate.input),
        )
    if kind == "join":
        return lp.Join(
            plan_from_proto(n.join.left),
            plan_from_proto(n.join.right),
            [(o.left_col, o.right_col) for o in n.join.on],
            n.join.how,
            n.join.null_aware,
        )
    if kind == "sort":
        return lp.Sort(
            [expr_from_proto(e) for e in n.sort.sort_exprs],
            plan_from_proto(n.sort.input),
        )
    if kind == "limit":
        return lp.Limit(n.limit.n, plan_from_proto(n.limit.input))
    if kind == "repartition":
        hx = [expr_from_proto(e) for e in n.repartition.hash_exprs]
        return lp.Repartition(
            plan_from_proto(n.repartition.input),
            n.repartition.num_partitions,
            hx or None,
        )
    if kind == "empty":
        return lp.EmptyRelation(n.empty.produce_one_row)
    if kind == "explain":
        return lp.Explain(plan_from_proto(n.explain.input), n.explain.verbose,
                          n.explain.analyze)
    raise SerdeError(f"unknown plan node {kind}")


# ---------------------------------------------------------------------------
# Physical plans
# ---------------------------------------------------------------------------


def physical_to_proto(plan) -> pb.PhysicalPlanNode:
    from .physical.aggregate import HashAggregateExec
    from .physical.explain import ExplainAnalyzeExec, ExplainExec
    from .physical.join import JoinExec
    from .physical.mesh_agg import MeshAggExec, MeshJoinExec
    from .physical import operators as ops
    from .physical.shuffle import ShuffleReaderExec, UnresolvedShuffleExec

    n = pb.PhysicalPlanNode()
    if isinstance(plan, ops.ScanExec):
        n.scan.table_name = plan.table_name
        n.scan.source.CopyFrom(source_to_proto(plan.source))
        if plan.projection is not None:
            n.scan.has_projection = True
            n.scan.projection.extend(plan.projection)
    elif isinstance(plan, ops.ProjectionExec):
        n.projection.input.CopyFrom(physical_to_proto(plan.child))
        for e in plan.exprs:
            n.projection.exprs.append(expr_to_proto(e))
    elif isinstance(plan, ops.FilterExec):
        n.filter.input.CopyFrom(physical_to_proto(plan.child))
        n.filter.predicate.CopyFrom(expr_to_proto(plan.predicate))
    elif isinstance(plan, HashAggregateExec):
        n.hash_aggregate.input.CopyFrom(physical_to_proto(plan.child))
        n.hash_aggregate.mode = plan.mode
        for e in plan.group_exprs:
            n.hash_aggregate.group_exprs.append(expr_to_proto(e))
        for e in plan.agg_exprs:
            n.hash_aggregate.agg_exprs.append(expr_to_proto(e))
        n.hash_aggregate.group_capacity = plan.group_capacity
    elif isinstance(plan, JoinExec):
        n.join.build.CopyFrom(physical_to_proto(plan.build))
        n.join.probe.CopyFrom(physical_to_proto(plan.probe))
        for l, r in plan.on:
            o = n.join.on.add()
            o.left_col = l
            o.right_col = r
        n.join.how = plan.how
        n.join.null_aware = plan.null_aware
        n.join.partitioned = plan.partitioned
        n.join.adaptive_note = plan.adaptive_note or ""
    elif isinstance(plan, MeshJoinExec):
        n.mesh_join.build_producer.CopyFrom(
            physical_to_proto(plan.build_producer))
        n.mesh_join.probe_producer.CopyFrom(
            physical_to_proto(plan.probe_producer))
        for l, r in plan.on:
            o = n.mesh_join.on.add()
            o.left_col = l
            o.right_col = r
        n.mesh_join.how = plan.how
        n.mesh_join.n_devices = plan.n_devices
        n.mesh_join.null_aware = plan.null_aware
    elif isinstance(plan, MeshAggExec):
        n.mesh_agg.producer.CopyFrom(physical_to_proto(plan.producer))
        for e in plan.group_exprs:
            n.mesh_agg.group_exprs.append(expr_to_proto(e))
        for e in plan.agg_exprs:
            n.mesh_agg.agg_exprs.append(expr_to_proto(e))
        for e in plan.hash_exprs:
            n.mesh_agg.hash_exprs.append(expr_to_proto(e))
        n.mesh_agg.n_devices = plan.n_devices
        n.mesh_agg.group_capacity = plan.group_capacity
    elif isinstance(plan, ops.SortExec):
        n.sort.input.CopyFrom(physical_to_proto(plan.child))
        for e in plan.sort_exprs:
            n.sort.sort_exprs.append(expr_to_proto(e))
    elif isinstance(plan, ops.LimitExec):
        n.limit.input.CopyFrom(physical_to_proto(plan.child))
        n.limit.n = plan.n
    elif isinstance(plan, ops.MergeExec):
        n.merge.input.CopyFrom(physical_to_proto(plan.child))
    elif isinstance(plan, ops.CoalesceBatchesExec):
        n.coalesce_batches.input.CopyFrom(physical_to_proto(plan.child))
    elif isinstance(plan, ops.RepartitionExec):
        n.repartition.input.CopyFrom(physical_to_proto(plan.child))
        n.repartition.num_partitions = plan.num_partitions
        for e in plan.hash_exprs or []:
            n.repartition.hash_exprs.append(expr_to_proto(e))
    elif isinstance(plan, ShuffleReaderExec):
        for loc in plan.partition_locations:
            n.shuffle_reader.partition_location.append(location_to_proto(loc))
        n.shuffle_reader.schema.CopyFrom(schema_to_proto(plan.output_schema()))
        for ranges in plan.read_partitions or []:
            rp = n.shuffle_reader.read_partitions.add()
            for olo, ohi, plo, phi in ranges:
                rp.ranges.add(output_lo=olo, output_hi=ohi,
                              producer_lo=plo, producer_hi=phi)
        n.shuffle_reader.hash_columns.extend(plan.hash_columns)
        n.shuffle_reader.original_partitions = plan.original_partitions
    elif isinstance(plan, UnresolvedShuffleExec):
        n.unresolved_shuffle.query_stage_ids.extend(plan.query_stage_ids)
        n.unresolved_shuffle.schema.CopyFrom(schema_to_proto(plan.output_schema()))
        n.unresolved_shuffle.partition_count = plan.partition_count
    elif isinstance(plan, ops.EmptyExec):
        n.empty.produce_one_row = plan.produce_one_row
    elif isinstance(plan, ExplainExec):
        n.explain.plan_type.extend(t for t, _ in plan.rows)
        n.explain.plan.extend(p for _, p in plan.rows)
    elif isinstance(plan, ExplainAnalyzeExec):
        n.explain_analyze.input.CopyFrom(physical_to_proto(plan.inner))
        n.explain_analyze.verbose = plan.verbose
        n.explain_analyze.logical_text = plan.logical_text or ""
    else:
        raise SerdeError(f"cannot serialize physical plan {type(plan).__name__}")
    return n


def physical_from_proto(n: pb.PhysicalPlanNode):
    from .physical.aggregate import HashAggregateExec
    from .physical.join import JoinExec
    from .physical import operators as ops
    from .physical.shuffle import ShuffleReaderExec, UnresolvedShuffleExec

    kind = n.WhichOneof("plan_type")
    if kind == "scan":
        src = source_from_proto(n.scan.source)
        proj = list(n.scan.projection) if n.scan.has_projection else None
        return ops.ScanExec(n.scan.table_name, src, proj)
    if kind == "projection":
        return ops.ProjectionExec(
            [expr_from_proto(e) for e in n.projection.exprs],
            physical_from_proto(n.projection.input),
        )
    if kind == "filter":
        return ops.FilterExec(
            expr_from_proto(n.filter.predicate),
            physical_from_proto(n.filter.input),
        )
    if kind == "hash_aggregate":
        return HashAggregateExec(
            n.hash_aggregate.mode,
            [expr_from_proto(e) for e in n.hash_aggregate.group_exprs],
            [expr_from_proto(e) for e in n.hash_aggregate.agg_exprs],
            physical_from_proto(n.hash_aggregate.input),
            n.hash_aggregate.group_capacity or 4096,
        )
    if kind == "join":
        return JoinExec(
            physical_from_proto(n.join.build),
            physical_from_proto(n.join.probe),
            [(o.left_col, o.right_col) for o in n.join.on],
            n.join.how,
            null_aware=n.join.null_aware,
            partitioned=n.join.partitioned,
            adaptive_note=n.join.adaptive_note or None,
        )
    if kind == "mesh_join":
        from .physical.mesh_agg import MeshJoinExec as _MeshJoinExec

        return _MeshJoinExec(
            physical_from_proto(n.mesh_join.build_producer),
            physical_from_proto(n.mesh_join.probe_producer),
            [(o.left_col, o.right_col) for o in n.mesh_join.on],
            n.mesh_join.how,
            n.mesh_join.n_devices,
            null_aware=n.mesh_join.null_aware,
        )
    if kind == "mesh_agg":
        from .physical.aggregate import DEFAULT_GROUP_CAPACITY
        from .physical.mesh_agg import MeshAggExec as _MeshAggExec

        return _MeshAggExec(
            physical_from_proto(n.mesh_agg.producer),
            [expr_from_proto(e) for e in n.mesh_agg.group_exprs],
            [expr_from_proto(e) for e in n.mesh_agg.agg_exprs],
            [expr_from_proto(e) for e in n.mesh_agg.hash_exprs],
            n.mesh_agg.n_devices,
            n.mesh_agg.group_capacity or DEFAULT_GROUP_CAPACITY,
        )
    if kind == "sort":
        return ops.SortExec(
            [expr_from_proto(e) for e in n.sort.sort_exprs],
            physical_from_proto(n.sort.input),
        )
    if kind == "limit":
        return ops.LimitExec(n.limit.n, physical_from_proto(n.limit.input))
    if kind == "merge":
        return ops.MergeExec(physical_from_proto(n.merge.input))
    if kind == "coalesce_batches":
        return ops.CoalesceBatchesExec(
            physical_from_proto(n.coalesce_batches.input)
        )
    if kind == "repartition":
        hx = [expr_from_proto(e) for e in n.repartition.hash_exprs]
        return ops.RepartitionExec(
            physical_from_proto(n.repartition.input),
            n.repartition.num_partitions,
            hx or None,
        )
    if kind == "shuffle_reader":
        return ShuffleReaderExec(
            [location_from_proto(l) for l in n.shuffle_reader.partition_location],
            schema_from_proto(n.shuffle_reader.schema),
            read_partitions=[
                [(r.output_lo, r.output_hi, r.producer_lo, r.producer_hi)
                 for r in rp.ranges]
                for rp in n.shuffle_reader.read_partitions
            ] or None,
            hash_columns=tuple(n.shuffle_reader.hash_columns),
            original_partitions=n.shuffle_reader.original_partitions,
        )
    if kind == "unresolved_shuffle":
        return UnresolvedShuffleExec(
            list(n.unresolved_shuffle.query_stage_ids),
            schema_from_proto(n.unresolved_shuffle.schema),
            n.unresolved_shuffle.partition_count,
        )
    if kind == "empty":
        return ops.EmptyExec(n.empty.produce_one_row)
    if kind == "explain":
        from .physical.explain import ExplainExec

        return ExplainExec(list(zip(n.explain.plan_type, n.explain.plan)))
    if kind == "explain_analyze":
        from .physical.explain import ExplainAnalyzeExec

        return ExplainAnalyzeExec(
            physical_from_proto(n.explain_analyze.input),
            n.explain_analyze.verbose,
            logical_text=n.explain_analyze.logical_text or None,
        )
    raise SerdeError(f"unknown physical node {kind}")


# ---------------------------------------------------------------------------
# Scheduling metadata helpers
# ---------------------------------------------------------------------------


def location_to_proto(loc) -> pb.PartitionLocation:
    """loc: distributed.types.PartitionLocation."""
    p = pb.PartitionLocation()
    p.partition_id.job_id = loc.job_id
    p.partition_id.stage_id = loc.stage_id
    p.partition_id.partition_id = loc.partition_id
    p.executor_meta.id = loc.executor_id
    p.executor_meta.host = loc.host
    p.executor_meta.port = loc.port
    p.path = loc.path or ""
    if loc.shuffle_output is not None:
        p.is_shuffle = True
        p.shuffle_output = loc.shuffle_output
    if loc.stats is not None:
        stats_to_proto(loc.stats, p.partition_stats)
    return p


def stats_to_proto(stats: dict, msg: "pb.PartitionStats") -> None:
    """PartitionStats dict (incl. optional per-column selectivity
    stats) -> proto."""
    msg.num_rows = stats.get("num_rows", 0)
    msg.num_batches = stats.get("num_batches", 0)
    msg.num_bytes = stats.get("num_bytes", 0)
    msg.shuffle_partition_bytes.extend(
        int(b) for b in stats.get("shuffle_partition_bytes") or []
    )
    for c in stats.get("columns") or []:
        cs = msg.column_stats.add()
        cs.name = c.get("name", "")
        cs.null_count = int(c.get("null_count", 0))
        cs.distinct_count = int(c.get("distinct_count", -1))
        for key, int_f, dbl_f, str_f in (
            ("min", "min_int", "min_double", "min_str"),
            ("max", "max_int", "max_double", "max_str"),
        ):
            v = c.get(key)
            if v is None:
                continue
            if isinstance(v, bool):
                setattr(cs, int_f, int(v))
            elif isinstance(v, int):
                setattr(cs, int_f, v)
            elif isinstance(v, float):
                setattr(cs, dbl_f, v)
            else:
                setattr(cs, str_f, str(v))


def stats_from_proto(msg: "pb.PartitionStats") -> dict:
    out = {
        "num_rows": msg.num_rows,
        "num_batches": msg.num_batches,
        "num_bytes": msg.num_bytes,
    }
    if msg.shuffle_partition_bytes:
        out["shuffle_partition_bytes"] = list(msg.shuffle_partition_bytes)
    cols = []
    for cs in msg.column_stats:
        c = {"name": cs.name, "null_count": cs.null_count,
             "distinct_count": cs.distinct_count}
        w = cs.WhichOneof("min_value")
        if w is not None:
            c["min"] = getattr(cs, w)
        w = cs.WhichOneof("max_value")
        if w is not None:
            c["max"] = getattr(cs, w)
        cols.append(c)
    if cols:
        out["columns"] = cols
    return out


def location_from_proto(p: pb.PartitionLocation):
    from .distributed.types import PartitionLocation

    return PartitionLocation(
        job_id=p.partition_id.job_id,
        stage_id=p.partition_id.stage_id,
        partition_id=p.partition_id.partition_id,
        executor_id=p.executor_meta.id,
        host=p.executor_meta.host,
        port=p.executor_meta.port,
        path=p.path,
        shuffle_output=p.shuffle_output if p.is_shuffle else None,
        stats=stats_from_proto(p.partition_stats),
    )


# ---------------------------------------------------------------------------
# Task/stage metrics (observability subsystem)
# ---------------------------------------------------------------------------
# Python shape: {"operators": [{"operator", "depth", "metrics": {...}}],
# "elapsed_total": float}. Timer values keep their ``elapsed_`` name
# prefix; the proto oneof preserves the kind across the wire.


def task_metrics_to_proto(tm: dict, msg: "pb.TaskMetrics") -> None:
    msg.elapsed_total_secs = float(tm.get("elapsed_total", 0.0))
    for row in tm.get("operators") or []:
        om = msg.operators.add()
        om.operator = row.get("operator", "")
        om.depth = int(row.get("depth", 0))
        for name, v in (row.get("metrics") or {}).items():
            mv = om.metrics.add()
            mv.name = name
            if name.startswith("elapsed_"):
                mv.elapsed_secs = float(v)
            elif isinstance(v, float):
                # Python type IS the kind: MetricsSet stores gauges as
                # float and counters as int, so an integral-valued gauge
                # (e.g. selectivity=1.0) must stay a gauge on the wire —
                # encoded as counter it would get SUMMED across tasks on
                # stage aggregation instead of max-ed
                mv.gauge = float(v)
            else:
                mv.counter = int(v)


def task_metrics_from_proto(msg: "pb.TaskMetrics") -> Optional[dict]:
    if not msg.operators and not msg.elapsed_total_secs:
        return None
    ops = []
    for om in msg.operators:
        metrics = {}
        for mv in om.metrics:
            which = mv.WhichOneof("value")
            if which == "elapsed_secs":
                metrics[mv.name] = mv.elapsed_secs
            elif which == "gauge":
                metrics[mv.name] = mv.gauge
            else:
                metrics[mv.name] = mv.counter
        ops.append({"operator": om.operator, "depth": om.depth,
                    "metrics": metrics})
    return {"operators": ops, "elapsed_total": msg.elapsed_total_secs}


def stage_metrics_to_proto(stages: Dict[int, dict], out) -> None:
    """stages: stage_id -> {"num_tasks", "elapsed_total", "operators"};
    ``out`` is a repeated StageMetrics field."""
    for sid in sorted(stages):
        st = stages[sid]
        sm = out.add()
        sm.stage_id = sid
        sm.num_tasks = int(st.get("num_tasks", 1))
        task_metrics_to_proto(st, sm.metrics)


def stage_metrics_from_proto(msgs) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    for sm in msgs:
        tm = task_metrics_from_proto(sm.metrics) or {
            "operators": [], "elapsed_total": 0.0}
        out[sm.stage_id] = {"num_tasks": sm.num_tasks or 1, **tm}
    return out


# -- distributed profiler: per-task profile windows ---------------------------
# Python shape (observability/distributed.capture_task_profile):
# {"t0", "wall_seconds", "pid", "role", "executor_id",
#  "records": [span dict...], "phases": {...}, "compile": {...},
#  "memory": {...}}. Records/context dicts are free-form span attrs, so
# they cross the wire as JSON blobs.


def task_profile_to_proto(p: dict, msg: "pb.TaskProfile") -> None:
    import json

    msg.t0 = float(p.get("t0", 0.0))
    msg.wall_seconds = float(p.get("wall_seconds", 0.0))
    msg.pid = int(p.get("pid", 0))
    msg.role = str(p.get("role", "executor"))
    msg.executor_id = str(p.get("executor_id", ""))
    # capture_task_profile pre-encodes the record list while applying
    # its byte bound — reuse that instead of serializing twice
    pre = p.get("records_json")
    msg.records_json = pre.encode() if isinstance(pre, str) else \
        json.dumps(p.get("records") or [], default=str).encode()
    msg.phases_json = json.dumps(p.get("phases") or {},
                                 default=str).encode()
    msg.compile_json = json.dumps(p.get("compile") or {},
                                  default=str).encode()
    msg.memory_json = json.dumps(p.get("memory") or {},
                                 default=str).encode()


def task_profile_from_proto(msg: "pb.TaskProfile") -> Optional[dict]:
    import json

    if not msg.records_json and not msg.wall_seconds:
        return None

    def _load(raw, default):
        try:
            return json.loads(raw.decode()) if raw else default
        except (ValueError, UnicodeDecodeError):
            return default

    return {
        "t0": msg.t0,
        "wall_seconds": msg.wall_seconds,
        "pid": msg.pid,
        "role": msg.role or "executor",
        "executor_id": msg.executor_id,
        "records": _load(msg.records_json, []),
        "phases": _load(msg.phases_json, {}),
        "compile": _load(msg.compile_json, {}),
        "memory": _load(msg.memory_json, {}),
    }


# -- live progress plane: job/stage progress snapshots ------------------------
# Python shape (observability/progress.py snapshot contract — ONE shape
# on both paths): {"job_id", "status", "fraction", "eta_seconds"
# (None = unknown), "wall_seconds", "tasks_total", "tasks_running",
# "tasks_queued", "tasks_completed", "stages": [{"stage_id",
# "tasks_total", "tasks_running", "tasks_completed", "fraction",
# "eta_seconds", "rows_so_far", "bytes_so_far"}, ...]}.


def job_progress_to_proto(snap: dict, msg: "pb.JobProgress") -> None:
    def _eta(v):
        return -1.0 if v is None else float(v)

    msg.fraction = float(snap.get("fraction", 0.0))
    msg.eta_seconds = _eta(snap.get("eta_seconds"))
    msg.wall_seconds = float(snap.get("wall_seconds", 0.0))
    msg.tasks_total = int(snap.get("tasks_total", 0))
    msg.tasks_running = int(snap.get("tasks_running", 0))
    msg.tasks_queued = int(snap.get("tasks_queued", 0))
    msg.tasks_completed = int(snap.get("tasks_completed", 0))
    for st in snap.get("stages") or []:
        sp = msg.stages.add()
        sp.stage_id = int(st.get("stage_id", 0))
        sp.tasks_total = int(st.get("tasks_total", 0))
        sp.tasks_running = int(st.get("tasks_running", 0))
        sp.tasks_completed = int(st.get("tasks_completed", 0))
        sp.fraction = float(st.get("fraction", 0.0))
        sp.eta_seconds = _eta(st.get("eta_seconds"))
        sp.rows_so_far = int(st.get("rows_so_far") or 0)
        sp.bytes_so_far = int(st.get("bytes_so_far") or 0)


def job_progress_from_proto(msg: "pb.JobProgress", job_id: str = "",
                            status: str = "running") -> dict:
    def _eta(v):
        return None if v < 0 else float(v)

    return {
        "job_id": job_id,
        "status": status,
        "fraction": msg.fraction,
        "eta_seconds": _eta(msg.eta_seconds),
        "wall_seconds": msg.wall_seconds,
        "tasks_total": msg.tasks_total,
        "tasks_running": msg.tasks_running,
        "tasks_queued": msg.tasks_queued,
        "tasks_completed": msg.tasks_completed,
        "stages": [
            {
                "stage_id": sp.stage_id,
                "tasks_total": sp.tasks_total,
                "tasks_running": sp.tasks_running,
                "tasks_completed": sp.tasks_completed,
                "fraction": sp.fraction,
                "eta_seconds": _eta(sp.eta_seconds),
                "rows_so_far": sp.rows_so_far,
                "bytes_so_far": sp.bytes_so_far,
            }
            for sp in msg.stages
        ],
    }
