"""Plan-fingerprint result cache: repeated queries skip execution.

Tier (c) of the warm-path cache subsystem. A standalone collect whose
physical plan, input data and semantics-affecting settings all match a
previous run can return that run's result without executing anything —
the dashboard/multi-tenant repeat-query case.

The key is the ONE invalidation signal, composed:

- ``compile_signature()`` of the fused physical plan — the full
  operator tree, expressions, schemas and capacities;
- per-leaf ``content_signature()`` of every scan source, re-stat'd at
  lookup time (file sizes + mtimes, the registry's
  ``file_entry_key`` discipline) — a rewritten or appended file misses
  by construction;
- the context settings, minus identity-only keys (``session.id``) —
  conservatively EVERYTHING else is treated as semantics-affecting, so
  a knob flip can fragment the cache but never serve a wrong result.

A plan with any un-signable leaf (memtables, system tables, raw
sources without ``content_signature``) is uncacheable: ``plan_key``
returns None and the collect executes normally.

Results are stored as HOST pydicts (numpy columns), accounted under
the ``cache`` host-memory category, LRU-bounded by
``BALLISTA_RESULT_CACHE_BUDGET_MB``. Both fill and hit deep-copy the
columns — a caller mutating its DataFrame must never corrupt the
cache, and vice versa.

Opt-in: ``BALLISTA_RESULT_CACHE`` defaults OFF (docs decision — result
reuse changes observable execution side effects like metrics and
progress, so operators enable it deliberately). The
``result_cache.enabled`` context setting overrides the environment
per session.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..observability.memory import record_host_bytes, release_host_bytes

_OFF = ("off", "0", "false", "no")
_ON = ("on", "1", "true", "yes")


def result_cache_enabled(settings: Optional[dict] = None) -> bool:
    """``BALLISTA_RESULT_CACHE`` (default off; opt-in), overridable per
    context via the ``result_cache.enabled`` setting."""
    if settings is not None:
        v = str(settings.get("result_cache.enabled", "")).lower()
        if v in _ON:
            return True
        if v in _OFF and v:
            return False
    return os.environ.get("BALLISTA_RESULT_CACHE", "off").lower() in _ON


def result_cache_budget_bytes() -> int:
    """``BALLISTA_RESULT_CACHE_BUDGET_MB``: host-byte budget for cached
    result sets (default 64 MiB)."""
    try:
        mb = int(os.environ.get("BALLISTA_RESULT_CACHE_BUDGET_MB", "")
                 or 64)
    except ValueError:
        mb = 64
    return max(mb, 1) << 20


# identity-only settings that never affect results
_IDENTITY_SETTINGS = ("session.id",)


def plan_key(phys, settings: Optional[dict] = None) -> Optional[tuple]:
    """Cache key for a planned (post-fusion) physical tree, or None
    when any leaf cannot sign its content."""
    leaf_sigs: List[tuple] = []

    def walk(node) -> bool:
        kids = node.children()
        if kids:
            return all(walk(c) for c in kids)
        src = getattr(node, "source", None)
        sig_fn = getattr(src, "content_signature", None)
        if sig_fn is None:
            return False
        try:
            sig = sig_fn()
        except Exception:  # noqa: BLE001 - unsignable: uncacheable
            return False
        if sig is None:
            return False
        leaf_sigs.append(sig)
        return True

    try:
        if not walk(phys):
            return None
        plan_sig = phys.compile_signature()
    except Exception:  # noqa: BLE001 - exotic plans: just don't cache
        return None
    setting_items = tuple(sorted(
        (str(k), str(v)) for k, v in (settings or {}).items()
        if k not in _IDENTITY_SETTINGS))
    return (plan_sig, tuple(leaf_sigs), setting_items)


def _copy_pydict(data: dict) -> dict:
    out = {}
    for k, v in data.items():
        if isinstance(v, np.ndarray):
            out[k] = v.copy()
        else:
            out[k] = list(v)
    return out


def _pydict_nbytes(data: dict) -> int:
    total = 0
    for v in data.values():
        if isinstance(v, np.ndarray):
            total += int(v.nbytes)
        else:
            total += 64 * len(v)  # object rows: rough per-cell charge
    return total


class _Entry:
    __slots__ = ("data", "nbytes", "hits", "filled_at", "last_access")

    def __init__(self, data: dict, nbytes: int):
        self.data = data
        self.nbytes = nbytes
        self.hits = 0
        self.filled_at = time.time()
        self.last_access = self.filled_at


class ResultCache:
    """LRU plan-fingerprint -> host result store, byte-bounded."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0

    def lookup(self, key: Optional[tuple]) -> Optional[dict]:
        if key is None:
            return None
        from ..observability import trace_span

        # spanned so the latency ledger's cache_lookup phase (and the
        # flight recorder) sees every probe, hit or miss
        with trace_span("cache.lookup", tier="result"):
            with self._lock:
                e = self._entries.get(key)
                if e is None:
                    self.misses += 1
                    return None
                self._entries.move_to_end(key)
                e.hits += 1
                e.last_access = time.time()
                self.hits += 1
                data = e.data
            return _copy_pydict(data)

    def fill(self, key: Optional[tuple], data: dict) -> bool:
        if key is None:
            return False
        stored = _copy_pydict(data)
        n = _pydict_nbytes(stored)
        budget = result_cache_budget_bytes()
        if n > budget:
            return False  # one oversized result must not flush the LRU
        dropped: List[_Entry] = []
        with self._lock:
            if key in self._entries:
                return False  # concurrent identical query won the fill
            while self._bytes + n > budget and self._entries:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                self.evictions += 1
                dropped.append(old)
            self._entries[key] = _Entry(stored, n)
            self._bytes += n
            self.fills += 1
        for old in dropped:
            release_host_bytes("cache", old.nbytes)
        record_host_bytes("cache", n)
        return True

    def invalidate(self) -> None:
        with self._lock:
            dropped = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
        for e in dropped:
            release_host_bytes("cache", e.nbytes)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "fills": self.fills,
                "evictions": self.evictions,
                "budget_bytes": result_cache_budget_bytes(),
            }

    def reset_stats(self) -> None:
        with self._lock:
            self.hits = self.misses = self.fills = self.evictions = 0

    def entry_rows(self) -> List[dict]:
        """``system.cache`` rows for this tier."""
        now = time.time()
        with self._lock:
            return [
                {
                    "tier": "result",
                    "entry": f"plan:{abs(hash(k)) % 10**10:010d}",
                    "bytes": e.nbytes,
                    "hits": e.hits,
                    "age_seconds": round(now - e.filled_at, 3),
                    "idle_seconds": round(now - e.last_access, 3),
                }
                for k, e in self._entries.items()
            ]


_cache_lock = threading.Lock()
_cache: Optional[ResultCache] = None


def process_result_cache() -> ResultCache:
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = ResultCache()
        return _cache


def _reset_for_tests() -> None:
    global _cache
    with _cache_lock:
        c, _cache = _cache, None
    if c is not None:
        c.invalidate()
