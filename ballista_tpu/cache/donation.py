"""Buffer-donation eligibility tracking for fused execution.

Steady-state execution moves every batch through exactly one governed
XLA program (the fused pipeline chain, or the aggregation program fed
by ``concat_batches``). XLA can reuse a donated input buffer for the
output allocation (``donate_argnums``), turning the copy-in/copy-out
round trip into an in-place update — but ONLY when the engine can
prove the input has exactly one consumer and nothing else will ever
read it again. This module is that proof:

- A :class:`~ballista_tpu.columnar.ColumnBatch` carries a
  ``_transient`` flag, ``False`` by default. Only the sites that
  CREATE a single-owner batch mark it: scan emission when the batch is
  *not* being pinned by the device table cache, ``concat_batches`` for
  ``len > 1`` (fresh ``jnp.concatenate`` output), and the fused
  pipeline's per-batch output. Cached / pinned / materialized batches
  are never marked, so they are never donation-eligible by
  construction.
- :func:`consume_transient` claims the flag exactly once. A call site
  that donates MUST consume first — a second alias of the same batch
  then sees ``False`` and takes the copying path instead of touching
  deleted buffers.

The ``num_rows`` scalar is NEVER donated even on transient batches:
``MetricsSet.record_output_batch`` holds it in ``_pending_rows`` long
after the batch body is consumed (see ``governed_donating`` in
``physical/base.py`` for the split-call wiring).

``BALLISTA_DONATION=off`` disables the whole tier; marked flags are
simply never consumed.
"""

from __future__ import annotations

import os
import threading
import warnings

# Donation is best-effort by design: a program whose output shapes
# don't line up with an input buffer simply allocates (e.g. the 8-slot
# scalar-agg output vs a 2^20-row input). XLA's per-call warning for
# those is noise here, and the interesting number is tracked by
# record_donation instead.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

_OFF = ("off", "0", "false", "no")


def donation_enabled() -> bool:
    """``BALLISTA_DONATION``: donate single-consumer intermediate
    buffers through governed programs (default on)."""
    return os.environ.get("BALLISTA_DONATION", "on").lower() not in _OFF


def mark_transient(batch) -> None:
    """Mark ``batch`` single-owner: its creator guarantees no other
    reference will read the device buffers after the one consumer."""
    batch._transient = True


def is_transient(batch) -> bool:
    return bool(getattr(batch, "_transient", False))


def propagate_transient(src, dst) -> None:
    """Carry the mark through a pass-through transform (same buffers,
    new wrapper)."""
    if is_transient(src):
        dst._transient = True


def consume_transient(batch) -> bool:
    """Claim the donation right: True exactly once per marked batch.
    Clearing before the donating call means an aliasing second consumer
    can never double-donate the same buffers."""
    if getattr(batch, "_transient", False):
        batch._transient = False
        return True
    return False


_lock = threading.Lock()
_donated_calls = 0
_donated_bytes = 0


def record_donation(nbytes: int) -> None:
    global _donated_calls, _donated_bytes
    with _lock:
        _donated_calls += 1
        _donated_bytes += int(nbytes)


def donation_stats() -> dict:
    with _lock:
        return {
            "donated_buffers": _donated_calls,
            "donated_bytes": _donated_bytes,
            "enabled": donation_enabled(),
        }


def reset_donation_stats() -> None:
    """Re-baseline the cumulative counters (bench phases, tests)."""
    global _donated_calls, _donated_bytes
    with _lock:
        _donated_calls = 0
        _donated_bytes = 0
