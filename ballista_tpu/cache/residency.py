"""Device-resident table cache: pin hot scan outputs across queries.

Tier (a) of the warm-path cache subsystem. A scan's expensive work is
parse (file -> host arrays) and H2D (host -> device upload); its
OUTPUT — post-parse, post-upload, bucketed-capacity ``ColumnBatch``
lists — is immutable and keyed entirely by on-disk content. This
module keeps those outputs resident on the device so a warm repeat
scan is a dictionary lookup instead of a re-ingest.

Invalidation is by construction, the same signal the dictionary
registry uses (PR 11): every key embeds the partition file's
``(basename, size, mtime_ns)`` signature via
:func:`columnar_registry.file_entry_key`-style stats taken AT LOOKUP
TIME. A rewritten or appended file mints a different key; the stale
entry simply stops being reachable and ages out of the LRU.

Memory is governed by :class:`DeviceMemoryGovernor`, the device-side
sibling of the shuffle governor (``distributed/spill.py``): charge on
insert, refuse past the watermark, evict coldest first — NEVER block.
A refused fill degrades to the plain streaming scan (the batches are
yielded either way); eviction under pressure degrades a later query to
re-ingest, never fails it.

Fill protocol (:meth:`DeviceTableCache.begin_fill`): scan sources add
batches as they are emitted and ``commit()`` only after the partition
completed — a partial entry (abandoned generator, mid-scan cancel,
budget refusal) is aborted and released, because serving a truncated
partition would be a correctness bug, not a cache miss.

Knobs (read at call time): ``BALLISTA_TABLE_CACHE`` (default on),
``BALLISTA_TABLE_CACHE_BUDGET_MB`` (default 512),
``BALLISTA_TABLE_CACHE_WATERMARK`` (default 0.9).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Iterable, List, Optional

_OFF = ("off", "0", "false", "no")


def table_cache_enabled() -> bool:
    """``BALLISTA_TABLE_CACHE``: keep scan outputs device-resident
    across queries and sessions (default on)."""
    return os.environ.get("BALLISTA_TABLE_CACHE", "on").lower() not in _OFF


def table_cache_budget_bytes() -> int:
    """``BALLISTA_TABLE_CACHE_BUDGET_MB``: device-byte budget for
    pinned scan outputs (default 512 MiB)."""
    try:
        mb = int(os.environ.get("BALLISTA_TABLE_CACHE_BUDGET_MB", "")
                 or 512)
    except ValueError:
        mb = 512
    return max(mb, 1) << 20


def table_cache_watermark() -> float:
    """``BALLISTA_TABLE_CACHE_WATERMARK``: fraction of the budget past
    which inserts refuse/evict (default 0.9)."""
    try:
        v = float(os.environ.get("BALLISTA_TABLE_CACHE_WATERMARK", "")
                  or 0.9)
    except ValueError:
        return 0.9
    return min(max(v, 0.01), 1.0)


def file_signature(path: str) -> tuple:
    """(basename, size, mtime_ns) of one partition file, taken NOW —
    the invalidation signal. Unstatable paths get a per-call unique
    token so they can never alias a cached entry."""
    try:
        return (os.path.basename(path), os.path.getsize(path),
                os.stat(path).st_mtime_ns)
    except OSError:
        return (path, -1, time.monotonic_ns())


def scan_key(kind: str, path: str, partition: int,
             projection, extra: tuple = ()) -> tuple:
    """Cache key for one (source file, partition, projection, format)
    scan. The file signature is re-stat'd per call, so file changes
    invalidate by construction."""
    proj = tuple(projection) if projection is not None else None
    return (kind, os.path.abspath(path), file_signature(path),
            int(partition), proj) + tuple(extra)


def batch_device_bytes(batch) -> int:
    """Device bytes a batch pins (all pytree leaves)."""
    import jax

    return int(sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree_util.tree_leaves(batch)))


class DeviceMemoryGovernor:
    """Process-wide accountant for device bytes pinned by the table
    cache — the device-side sibling of the shuffle memory governor.
    Charge/release pairs are locked (a lost update leaks budget
    forever); budget/watermark read the environment at call time so
    one instance serves any knob configuration. ``try_charge`` NEVER
    blocks: a refusal means the caller skips pinning (or evicts and
    retries)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.resident_bytes = 0
        self.peak_resident_bytes = 0
        self.denials = 0

    def try_charge(self, nbytes: int) -> bool:
        n = int(nbytes)
        if n <= 0:
            return True
        limit = int(table_cache_budget_bytes() * table_cache_watermark())
        with self._lock:
            if self.resident_bytes + n > limit:
                self.denials += 1
                return False
            self.resident_bytes += n
            if self.resident_bytes > self.peak_resident_bytes:
                self.peak_resident_bytes = self.resident_bytes
        return True

    def release(self, nbytes: int) -> None:
        n = int(nbytes)
        if n <= 0:
            return
        with self._lock:
            self.resident_bytes = max(0, self.resident_bytes - n)

    def stats(self) -> dict:
        with self._lock:
            return {
                "resident_bytes": self.resident_bytes,
                "peak_resident_bytes": self.peak_resident_bytes,
                "denials": self.denials,
                "budget_bytes": table_cache_budget_bytes(),
            }

    def reset_stats(self) -> None:
        """Re-baseline the peak (bench phases, tests);
        ``resident_bytes`` is live accounting and is NOT reset."""
        with self._lock:
            self.peak_resident_bytes = self.resident_bytes
            self.denials = 0


class _Entry:
    __slots__ = ("batches", "nbytes", "hits", "filled_at", "last_access")

    def __init__(self, batches: List, nbytes: int):
        self.batches = batches
        self.nbytes = nbytes
        self.hits = 0
        self.filled_at = time.time()
        self.last_access = self.filled_at


class _Filler:
    """One in-progress fill: charges the governor per added batch and
    publishes the entry only on ``commit()`` after every batch landed.
    ``add`` returning False means the budget refused even after
    evicting everything colder — the fill is dead, remaining batches
    stay un-pinned (and donation-eligible)."""

    def __init__(self, cache: "DeviceTableCache", key: tuple):
        self._cache = cache
        self._key = key
        self._batches: List = []
        self._charged = 0
        self._dead = False
        self._done = False

    def add(self, batch) -> bool:
        if self._dead:
            return False
        n = batch_device_bytes(batch)
        if not self._cache._charge_evicting(n):
            self.abort()
            return False
        self._charged += n
        self._batches.append(batch)
        return True

    def commit(self) -> bool:
        """Publish the complete entry; False when the fill died or was
        already finalized."""
        if self._dead or self._done:
            return False
        self._done = True
        return self._cache._publish(self._key, self._batches, self._charged)

    def abort(self) -> None:
        """Release whatever was charged; the entry is never published.
        Idempotent — safe from a generator's ``finally``."""
        if self._done or self._dead:
            return
        self._dead = True
        self._cache._gov.release(self._charged)
        self._batches = []
        self._charged = 0


class DeviceTableCache:
    """LRU map of scan keys -> pinned batch lists, bounded by the
    device memory governor. Lookups are O(1) under one lock; entries
    are whole partitions (all batches or nothing)."""

    def __init__(self, governor: Optional[DeviceMemoryGovernor] = None):
        self._gov = governor or DeviceMemoryGovernor()
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.fills = 0
        self.evictions = 0
        self.refusals = 0

    @property
    def governor(self) -> DeviceMemoryGovernor:
        return self._gov

    def lookup(self, key: Optional[tuple]) -> Optional[List]:
        """The pinned batch list for ``key``, or None. A hit refreshes
        LRU order; the returned list is a copy (callers iterate and
        may drop it mid-stream)."""
        if key is None or not table_cache_enabled():
            return None
        from ..observability import trace_span

        # spanned so the latency ledger's cache_lookup phase (and the
        # flight recorder) sees every probe, hit or miss
        with trace_span("cache.lookup", tier="table"):
            with self._lock:
                e = self._entries.get(key)
                if e is None:
                    self.misses += 1
                    return None
                self._entries.move_to_end(key)
                e.hits += 1
                e.last_access = time.time()
                self.hits += 1
                return list(e.batches)

    def contains(self, key: Optional[tuple]) -> bool:
        """Membership probe WITHOUT touching hit/miss counters or LRU
        order (prefetch-routing decisions, tests)."""
        if key is None or not table_cache_enabled():
            return False
        with self._lock:
            return key in self._entries

    def begin_fill(self, key: Optional[tuple]) -> Optional[_Filler]:
        """A filler for ``key``, or None when the tier is off, the key
        is uncacheable, or the entry already exists."""
        if key is None or not table_cache_enabled():
            return None
        with self._lock:
            if key in self._entries:
                return None
        return _Filler(self, key)

    def _charge_evicting(self, nbytes: int) -> bool:
        """Charge, evicting coldest entries while the governor refuses.
        Returns False once nothing is left to evict. Never blocks."""
        while not self._gov.try_charge(nbytes):
            with self._lock:
                if not self._entries:
                    self.refusals += 1
                    return False
                _, e = self._entries.popitem(last=False)
                self.evictions += 1
            self._gov.release(e.nbytes)
        return True

    def _publish(self, key: tuple, batches: List, nbytes: int) -> bool:
        with self._lock:
            if key in self._entries:
                # a concurrent scan won the fill race: keep theirs
                dup = True
            else:
                self._entries[key] = _Entry(batches, nbytes)
                self.fills += 1
                dup = False
        if dup:
            self._gov.release(nbytes)
        return not dup

    def invalidate(self, key: Optional[tuple] = None) -> None:
        """Drop one entry (or everything) and release its budget.
        File-change invalidation needs no call here — changed files
        mint different keys — this is for explicit resets (tests,
        ``CacheSource.invalidate`` parity)."""
        with self._lock:
            if key is not None:
                dropped = [self._entries.pop(key)] \
                    if key in self._entries else []
            else:
                dropped = list(self._entries.values())
                self._entries.clear()
        for e in dropped:
            self._gov.release(e.nbytes)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "fills": self.fills,
                "evictions": self.evictions,
                "refusals": self.refusals,
            }
        out.update(self._gov.stats())
        out["enabled"] = table_cache_enabled()
        return out

    def reset_stats(self) -> None:
        with self._lock:
            self.hits = self.misses = self.fills = 0
            self.evictions = self.refusals = 0
        self._gov.reset_stats()

    def entry_rows(self) -> List[dict]:
        """``system.cache`` rows for this tier: one per pinned
        partition."""
        now = time.time()
        with self._lock:
            return [
                {
                    "tier": "table",
                    "entry": f"{k[0]}:{os.path.basename(str(k[1]))}"
                             f"[{k[3]}]",
                    "bytes": e.nbytes,
                    "hits": e.hits,
                    "age_seconds": round(now - e.filled_at, 3),
                    "idle_seconds": round(now - e.last_access, 3),
                }
                for k, e in self._entries.items()
            ]


_cache_lock = threading.Lock()
_cache: Optional[DeviceTableCache] = None


def process_table_cache() -> DeviceTableCache:
    """The process-wide device table cache (shared by every source,
    session and in-process executor)."""
    global _cache
    with _cache_lock:
        if _cache is None:
            _cache = DeviceTableCache()
        return _cache


def _reset_for_tests() -> None:
    global _cache
    with _cache_lock:
        c, _cache = _cache, None
    if c is not None:
        c.invalidate()


def serve_or_fill(key: Optional[tuple], produce, outcome_sink=None
                  ) -> Iterable:
    """The ONE scan-side integration point: yield cached batches on a
    hit, else stream ``produce()`` through a fill attempt. Batches that
    end up pinned are NOT donation-eligible; refused/unpinned ones are
    marked transient. ``outcome_sink(outcome)`` (optional) receives
    ``"hit" | "filled" | "miss"`` for EXPLAIN ANALYZE annotation."""
    from .donation import mark_transient

    cache = process_table_cache()
    cached = cache.lookup(key)
    if cached is not None:
        if outcome_sink is not None:
            outcome_sink("hit")
        for batch in cached:
            yield batch
        return
    filler = cache.begin_fill(key)
    committed = False
    try:
        for batch in produce():
            if filler is not None and filler.add(batch):
                pass  # pinned: never donation-eligible
            else:
                mark_transient(batch)
            yield batch
        if filler is not None:
            committed = filler.commit()
    finally:
        if filler is not None and not committed:
            # abandoned mid-stream (limit, cancel) or budget-refused:
            # a partial entry must never be served
            filler.abort()
    if outcome_sink is not None:
        outcome_sink("filled" if committed else "miss")
