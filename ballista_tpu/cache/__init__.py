"""Warm-path cache subsystem: three tiers, ONE invalidation signal.

- :mod:`.residency` — tier (a): device-resident table cache pinning
  hot scan outputs (post-parse, post-H2D) across queries and sessions,
  LRU-bounded by a device-memory governor (charge on insert, refuse
  past watermark, evict coldest, never block).
- :mod:`.donation` — tier (b): buffer donation through fused stages.
  Single-consumer intermediate batches are marked transient at
  creation and their device buffers donated (``donate_argnums``) to
  the governed program that consumes them; cached/pinned batches are
  never eligible.
- :mod:`.results` — tier (c): plan-fingerprint -> result cache keyed
  on ``compile_signature`` + input table content signatures +
  semantics-affecting settings. Opt-in
  (``BALLISTA_RESULT_CACHE=on``).

The shared invalidation signal is the registry/content-epoch + file
signature discipline from the dictionary registry: every key embeds
``(basename, size, mtime_ns)`` file stats taken at lookup time plus
plan fingerprints, so changed data or changed plans miss by
construction — there is no explicit invalidation bus to keep coherent.

``cache_counters()`` is the one-stop snapshot bench/serving loops and
the health plane export from.
"""

from __future__ import annotations

from .donation import (  # noqa: F401
    consume_transient,
    donation_enabled,
    donation_stats,
    is_transient,
    mark_transient,
    record_donation,
    reset_donation_stats,
)
from .residency import (  # noqa: F401
    DeviceMemoryGovernor,
    DeviceTableCache,
    batch_device_bytes,
    process_table_cache,
    scan_key,
    serve_or_fill,
    table_cache_budget_bytes,
    table_cache_enabled,
    table_cache_watermark,
)
from .results import (  # noqa: F401
    ResultCache,
    plan_key,
    process_result_cache,
    result_cache_budget_bytes,
    result_cache_enabled,
)


def cache_counters() -> dict:
    """Flat counter snapshot across all three tiers — the per-JSON-line
    fields bench.py / bench_serving.py emit and the regression lint
    tracks."""
    t = process_table_cache().stats()
    r = process_result_cache().stats()
    d = donation_stats()
    return {
        "table_cache_hits": t["hits"],
        "table_cache_misses": t["misses"],
        "table_cache_fills": t["fills"],
        "table_cache_evictions": t["evictions"],
        "table_cache_resident_bytes": t["resident_bytes"],
        "table_cache_peak_resident_bytes": t["peak_resident_bytes"],
        "result_cache_hits": r["hits"],
        "result_cache_misses": r["misses"],
        "result_cache_bytes": r["bytes"],
        "donated_buffers": d["donated_buffers"],
        "donated_bytes": d["donated_bytes"],
    }


def reset_cache_stats() -> None:
    """Re-baseline every tier's cumulative counters (bench phases,
    tests). Resident entries and their accounting stay."""
    process_table_cache().reset_stats()
    process_result_cache().reset_stats()
    reset_donation_stats()
