"""The analysis engine: parse the package once, run every pass over it.

The repo grew seven independent regex lints in ``dev/`` (jit sites,
dict sites, metric names, fault points, knob docs, ...) with no shared
machinery — each re-walked the tree, re-invented docstring skipping and
per-line opt-out markers, and none had a suppression or baseline story
for the semantic rules review kept enforcing by hand (PRs 4/5/12 each
shipped review-round fixes for missing cancel checks, unspanned device
syncs and double-checked-locking races). This module is the shared
core those passes now run on:

- :class:`SourceFile` / :class:`Package` — every ``.py`` file under the
  package parsed ONCE (source text, AST, suppression comments); rules
  never re-read or re-parse.
- :class:`Finding` — structured result: rule id, repo-relative file,
  line, message, plus the stripped source line as a line-drift-stable
  ``anchor`` for baseline matching.
- suppressions — ``# ballista: ignore[rule-id]`` on the finding line
  (or alone on the line above) silences that rule there; legacy
  per-rule markers (``# jit-ok:``...) stay honored by their ports.
- :class:`Baseline` — a committed JSON file of triaged pre-existing
  findings (``dev/analysis_baseline.json``); matched by
  ``(rule, file, anchor)`` so line churn doesn't invalidate entries,
  and entries that no longer match anything are reported as stale.

The package is import-light on purpose: stdlib only, intra-package
relative imports only, so ``dev/analyze.py`` (and staged lint
self-tests) can load it standalone without executing
``ballista_tpu/__init__`` — rules that need live registries import them
lazily inside ``run``.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(r"#\s*ballista:\s*ignore\[([^\]]*)\]")

# directories never worth parsing
_SKIP_DIRS = {"__pycache__"}


class SourceFile:
    """One parsed module: text, lines, AST, and suppression map."""

    __slots__ = ("rel", "path", "text", "lines", "tree", "suppressions",
                 "parse_error")

    def __init__(self, rel: str, path: str, text: str):
        self.rel = rel
        self.path = path
        self.text = text
        self.lines: List[str] = text.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:  # surfaced as a finding by the runner
            self.tree = ast.Module(body=[], type_ignores=[])
            self.parse_error = str(e)
        self.suppressions: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {t.strip() for t in m.group(1).split(",") if t.strip()}
            self.suppressions.setdefault(i, set()).update(rules or {"*"})
            # a comment-only line suppresses the line below it (long
            # statements have no room for a trailing marker)
            if line.lstrip().startswith("#"):
                self.suppressions.setdefault(i + 1, set()).update(
                    rules or {"*"})

    def line(self, n: int) -> str:
        """1-indexed raw source line ('' when out of range)."""
        if 1 <= n <= len(self.lines):
            return self.lines[n - 1]
        return ""

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and ("*" in rules or rule in rules)


class Package:
    """Every module under one package root, parsed once and shared by
    all passes (plus lazily-built cross-module indexes, see
    :mod:`callgraph`)."""

    def __init__(self, root: str, files: List[SourceFile]):
        self.root = root  # repo root (rel paths resolve against it)
        self.files = files
        self.by_rel: Dict[str, SourceFile] = {f.rel: f for f in files}
        self._index = None  # callgraph.ProjectIndex, built on demand

    @classmethod
    def load(cls, root: str, package_rel: str = "ballista_tpu"
             ) -> "Package":
        root = os.path.abspath(root)
        pkg_dir = os.path.join(root, package_rel)
        files: List[SourceFile] = []
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                try:
                    text = open(path, encoding="utf-8").read()
                except OSError:
                    continue
                files.append(SourceFile(rel, path, text))
        return cls(root, files)

    def index(self):
        """The shared import-resolving project index (built once)."""
        if self._index is None:
            from .callgraph import ProjectIndex

            self._index = ProjectIndex(self)
        return self._index


class Finding:
    """One rule violation at one site."""

    __slots__ = ("rule", "file", "line", "message", "anchor")

    def __init__(self, rule: str, file: str, line: int, message: str,
                 anchor: Optional[str] = None):
        self.rule = rule
        self.file = file
        self.line = line
        self.message = message
        self.anchor = anchor if anchor is not None else ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.file, self.anchor)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "anchor": self.anchor}

    def render(self) -> str:
        return f"{self.rule}: {self.file}:{self.line}: {self.message}"

    def __repr__(self) -> str:  # debugging/pytest output
        return f"<Finding {self.render()}>"


def make_finding(rule: str, sf: SourceFile, line: int, message: str
                 ) -> Finding:
    """Finding anchored to the stripped source line (the baseline's
    line-drift-stable identity)."""
    return Finding(rule, sf.rel, line, message, sf.line(line).strip())


class Rule:
    """Base class for passes. Subclasses set ``id``/``description`` and
    implement ``run(package) -> list[Finding]``. Construction takes no
    required arguments so the registry can instantiate defaults; rules
    with tunable scope (module lists, allowlists) accept overrides as
    keyword arguments for fixture tests."""

    id: str = ""
    description: str = ""

    def run(self, package: Package) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


class Baseline:
    """Triaged pre-existing findings, committed as JSON.

    Entry shape: ``{"rule", "file", "anchor", "note"}`` — ``note`` is
    the triage justification (required by convention, not schema).
    Matching is by (rule, file, anchor): one entry absorbs every
    finding with that identity, so a moved line stays baselined and a
    FIXED site turns the entry stale (reported, prunable with
    ``dev/analyze.py --write-baseline``)."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries: List[dict] = list(entries or [])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        return cls(data.get("findings", []))

    def save(self, path: str) -> None:
        data = {"version": 1, "findings": self.entries}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=True)
            fh.write("\n")

    def _keys(self) -> Set[Tuple[str, str, str]]:
        return {(e.get("rule", ""), e.get("file", ""), e.get("anchor", ""))
                for e in self.entries}

    def partition(self, findings: Sequence[Finding]
                  ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """(new, baselined, stale_entries)."""
        keys = self._keys()
        new = [f for f in findings if f.key() not in keys]
        old = [f for f in findings if f.key() in keys]
        live = {f.key() for f in old}
        stale = [
            e for e in self.entries
            if (e.get("rule", ""), e.get("file", ""),
                e.get("anchor", "")) not in live
        ]
        return new, old, stale

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      previous: Optional["Baseline"] = None) -> "Baseline":
        """Baseline covering ``findings``. Entries already present in
        ``previous`` keep their triage notes; only genuinely new sites
        get the TRIAGE ME placeholder (a rewrite must never destroy
        recorded justifications)."""
        prev_notes = {}
        if previous is not None:
            prev_notes = {
                (e.get("rule", ""), e.get("file", ""), e.get("anchor", "")):
                e.get("note", "")
                for e in previous.entries
            }
        seen: Set[Tuple[str, str, str]] = set()
        entries = []
        for f in sorted(findings, key=lambda f: (f.rule, f.file, f.line)):
            if f.key() in seen:
                continue
            seen.add(f.key())
            entries.append({"rule": f.rule, "file": f.file,
                            "anchor": f.anchor,
                            "note": prev_notes.get(f.key(), "TRIAGE ME")})
        return cls(entries)


class AnalysisResult:
    __slots__ = ("findings", "baselined", "stale", "suppressed",
                 "parse_errors")

    def __init__(self, findings: List[Finding], baselined: List[Finding],
                 stale: List[dict], suppressed: int,
                 parse_errors: List[Finding]):
        self.findings = findings      # NEW (non-baselined) findings
        self.baselined = baselined
        self.stale = stale
        self.suppressed = suppressed
        self.parse_errors = parse_errors

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors


def analyze(package: Package, rules: Iterable[Rule],
            baseline: Optional[Baseline] = None,
            only_files: Optional[Set[str]] = None) -> AnalysisResult:
    """Run ``rules`` over ``package``; drop suppressed findings, split
    the rest against ``baseline``. ``only_files`` (repo-relative paths)
    filters file-scoped findings — package-scoped rules still see the
    whole tree, their findings are just not reported for other files
    (the ``--changed-only`` fast path)."""
    parse_errors = [
        Finding("parse-error", f.rel, 1, f.parse_error or "syntax error")
        for f in package.files if f.parse_error
    ]
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.run(package))
    kept: List[Finding] = []
    suppressed = 0
    for f in raw:
        sf = package.by_rel.get(f.file)
        if sf is not None and sf.suppressed(f.line, f.rule):
            suppressed += 1
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.file, f.line, f.rule))
    if baseline is None:
        new, old, stale = kept, [], []
    else:
        # partition against the FULL finding set — staleness must not
        # depend on the reporting scope (a --changed-only run would
        # otherwise call every unchanged file's entries stale)
        new, old, stale = baseline.partition(kept)
    if only_files is not None:
        new = [f for f in new if f.file in only_files]
        old = [f for f in old if f.file in only_files]
    return AnalysisResult(new, old, stale, suppressed, parse_errors)
