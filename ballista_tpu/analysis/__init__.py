"""Unified AST static analysis for the engine's hand-enforced
invariants.

One framework (``engine.py``: parse-once package model, structured
findings, ``# ballista: ignore[rule]`` suppressions, committed
baseline; ``callgraph.py``: shared scope/import-resolving index), eight
passes (``passes/``): three semantic rules for the bug classes review
kept catching by hand — cancel-coverage, sync-span, lock-discipline —
plus the five code-shape lints that previously lived as independent
regex scripts under ``dev/``.

Driven by ``dev/analyze.py`` (tier-1 runs it with
``--baseline dev/analysis_baseline.json``); rule catalogue and
workflows in docs/static_analysis.md.

Import discipline: this package is stdlib-only at import time and uses
only intra-package relative imports, so ``dev/analyze.py`` can load it
WITHOUT executing ``ballista_tpu/__init__`` (which imports jax) — the
pure-AST rules then run in milliseconds; only the registry-backed
rules (metric-names, fault-points, knob-docs) import live engine
modules, lazily, inside ``run``.
"""

from .engine import (  # noqa: F401
    AnalysisResult,
    Baseline,
    Finding,
    Package,
    Rule,
    analyze,
    make_finding,
)
from .passes import RULE_FACTORIES, all_rules, rules_for  # noqa: F401
