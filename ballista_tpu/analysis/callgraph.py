"""Shared scope / import-resolving index over a parsed :class:`Package`.

Every semantic pass needs the same three questions answered:

- what does local name ``X`` in module M refer to? (``import jax.numpy
  as jnp`` -> external ``jax.numpy``; ``from ..lifecycle import
  check_cancel`` -> symbol ``check_cancel`` of
  ``ballista_tpu/lifecycle.py``)
- what functions/methods does module M define? (qualified as ``f`` or
  ``Class.f``)
- which definition does a call ``f(...)`` / ``self.m(...)`` /
  ``mod.f(...)`` resolve to? (best-effort, *confident* resolutions
  only: an unknown receiver resolves to nothing rather than to every
  same-named method in the package — passes that follow calls must
  never be tricked into marking a loop covered by an unrelated method)

Imports are collected at ANY depth (this codebase imports lazily inside
functions as a matter of style), flattened into one per-module map —
an approximation that is exact in practice because local import aliases
here never shadow differently across functions.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from .engine import Package, SourceFile


class FunctionInfo:
    __slots__ = ("module", "qualname", "node", "cls")

    def __init__(self, module: str, qualname: str, node: ast.AST,
                 cls: Optional[str]):
        self.module = module      # repo-relative path
        self.qualname = qualname  # "f" or "Class.f"
        self.node = node
        self.cls = cls


class ModuleIndex:
    """Per-module name tables."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # local alias -> ("ext", dotted) | ("mod", rel) | ("sym", rel, name)
        self.imports: Dict[str, Tuple] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self._pkg_files: set = set()

    # -- imports -------------------------------------------------------------

    def _module_parts(self) -> List[str]:
        # "ballista_tpu/io/ipc.py" -> ["ballista_tpu", "io"]: dropping
        # the last segment yields the containing package for plain
        # modules AND for __init__.py (whose dir IS its package)
        return self.sf.rel[:-3].split("/")[:-1]

    def _resolve_module(self, dotted: str, prefix: str) -> Optional[str]:
        """Dotted package-absolute module -> repo-relative file, if the
        target exists in the scanned package."""
        rel = dotted.replace(".", "/")
        if not (rel == prefix or rel.startswith(prefix + "/")):
            return None
        for cand in (rel + ".py", rel + "/__init__.py"):
            if cand in self._pkg_files:
                return cand
        return None

    def collect(self, pkg_files, prefix: str) -> None:
        self._pkg_files = pkg_files
        pkg_parts = self._module_parts()
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    dotted = a.name if a.asname else a.name.split(".")[0]
                    target = self._resolve_module(dotted, prefix)
                    if target is not None:
                        self.imports[local] = ("mod", target)
                    else:
                        self.imports[local] = ("ext", dotted)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[:len(pkg_parts) - (node.level - 1)] \
                        if node.level > 1 else list(pkg_parts)
                    if node.level - 1 > len(pkg_parts):
                        continue
                    dotted_base = ".".join(base)
                    dotted = (dotted_base + "." + node.module
                              if node.module else dotted_base)
                else:
                    dotted = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    # "from X import Y": Y is a submodule or a symbol
                    sub = self._resolve_module(dotted + "." + a.name, prefix)
                    if sub is not None:
                        self.imports[local] = ("mod", sub)
                        continue
                    target = self._resolve_module(dotted, prefix)
                    if target is not None:
                        self.imports[local] = ("sym", target, a.name)
                    elif dotted:
                        self.imports[local] = ("ext", dotted + "." + a.name)
        # functions/methods (module level and one class level deep —
        # nested defs are walked for loops but not addressable targets)
        for node in self.sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = FunctionInfo(
                    self.sf.rel, node.name, node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        q = f"{node.name}.{sub.name}"
                        self.functions[q] = FunctionInfo(
                            self.sf.rel, q, sub, node.name)

    def external_root(self, local: str) -> Optional[str]:
        """The top-level external package a local name refers to
        ('numpy', 'jax', ...) or None."""
        entry = self.imports.get(local)
        if entry and entry[0] == "ext":
            return entry[1].split(".")[0]
        return None

    def external_dotted(self, local: str) -> Optional[str]:
        entry = self.imports.get(local)
        if entry and entry[0] == "ext":
            return entry[1]
        return None


class ProjectIndex:
    """All modules' indexes + confident cross-module call resolution."""

    def __init__(self, package: Package):
        self.package = package
        prefixes = {f.rel.split("/")[0] for f in package.files}
        # single-rooted packages in practice; pick the common root
        self.prefix = sorted(prefixes)[0] if prefixes else ""
        pkg_files = set(package.by_rel)
        self.modules: Dict[str, ModuleIndex] = {}
        for sf in package.files:
            mi = ModuleIndex(sf)
            mi.collect(pkg_files, self.prefix)
            self.modules[sf.rel] = mi

    def module(self, rel: str) -> Optional[ModuleIndex]:
        return self.modules.get(rel)

    def resolve_call(self, rel: str, call: ast.Call,
                     cls: Optional[str] = None) -> Optional[FunctionInfo]:
        """Resolve a call site in module ``rel`` (inside class ``cls``
        when given) to its definition, confident cases only:

        - ``f(...)``        -> module-level ``f`` here, or an imported
                               symbol's definition in its home module
        - ``self.m(...)``   -> method ``m`` of the enclosing class
        - ``mod.f(...)``    -> ``f`` in an imported package module
        """
        mi = self.modules.get(rel)
        if mi is None:
            return None
        func = call.func
        if isinstance(func, ast.Name):
            fi = mi.functions.get(func.id)
            if fi is not None:
                return fi
            entry = mi.imports.get(func.id)
            if entry and entry[0] == "sym":
                target = self.modules.get(entry[1])
                if target is not None:
                    return target.functions.get(entry[2])
            return None
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and cls:
                    return mi.functions.get(f"{cls}.{func.attr}")
                entry = mi.imports.get(base.id)
                if entry and entry[0] == "mod":
                    target = self.modules.get(entry[1])
                    if target is not None:
                        return target.functions.get(func.attr)
        return None


# -- shared AST helpers -------------------------------------------------------


def walk_functions(sf: SourceFile
                   ) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """Yield every (function node, enclosing class name) in the file,
    including nested functions (class = the nearest enclosing class)."""

    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from visit(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            else:
                yield from visit(child, cls)

    yield from visit(sf.tree, None)


def identifiers(node: ast.AST) -> List[str]:
    """Every Name id and Attribute attr under ``node``."""
    out: List[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
        elif isinstance(n, ast.arg):
            out.append(n.arg)
    return out


def name_words(ident: str) -> List[str]:
    """'num_record_batches' -> ['num', 'record', 'batches'] (matching
    vocabulary is word-level so substrings never false-positive)."""
    return [w for w in ident.lower().split("_") if w]


def call_name(call: ast.Call) -> Optional[str]:
    """Terminal name of a call's function expression."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None
