"""sync-span: implicit host syncs on device values must sit inside a
``device.block`` tracing span.

The profiler's lane decomposition (docs/observability.md) attributes
query wall time to lanes; ``device_blocked`` is computed as the sum of
``device.block`` spans, so a blocking sync OUTSIDE such a span silently
shifts device time into whatever lane encloses it — exactly the class
of skew PR 5/7 review rounds kept fixing by hand. This pass makes the
attribution honest by construction.

Candidate sync sites:

- ``jax.device_get(...)`` — the explicit D2H fetch;
- ``<x>.item()`` — scalar host read (numpy's is host-only; suppress
  with a reason where the receiver provably never holds a jax array);
- ``np.asarray(X)`` where ``X`` is *device-provenance*: an attribute
  read of a ColumnBatch/Column device buffer (``.values`` /
  ``.validity`` / ``.selection``), or a local name assigned from a
  ``jax.*`` / ``jnp.*`` call or such an attribute. Host-side
  ``np.asarray`` over parsed python lists/numpy inputs is NOT flagged
  — provenance, not the call, is what makes it a sync.

A candidate is covered when it sits lexically inside a ``with
trace_span("device.block", ...)`` block (module-local containment —
the span need not be in the same function, a wrapper's span covers the
wrapped body). Everything else is a finding: wrap it with a span
carrying a ``site=`` attribute, or suppress with
``# ballista: ignore[sync-span]`` and a reason.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..callgraph import walk_functions
from ..engine import Finding, Package, Rule, SourceFile, make_finding

DEVICE_ATTRS = frozenset({"values", "validity", "selection"})

# jax host-side API: returns python objects, never device arrays — a
# name assigned from these carries NO device provenance
HOST_JAX_CALLS = frozenset({
    "devices", "local_devices", "device_count", "local_device_count",
    "process_index", "process_count", "tree_structure", "tree_flatten",
})

# modules whose whole business is recording/deciding, not executing —
# the span machinery itself must not be asked to span itself
SKIP_FILES = frozenset({
    "ballista_tpu/observability/tracing.py",
})


def _span_ranges(sf: SourceFile) -> List[Tuple[int, int]]:
    """(start, end) line ranges of every ``with trace_span("device.block"
    ...)`` body in the file."""
    ranges: List[Tuple[int, int]] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            fname = (call.func.id if isinstance(call.func, ast.Name)
                     else call.func.attr
                     if isinstance(call.func, ast.Attribute) else "")
            if fname != "trace_span" or not call.args:
                continue
            first = call.args[0]
            if isinstance(first, ast.Constant) and \
                    first.value == "device.block":
                ranges.append((node.lineno, node.end_lineno or node.lineno))
                break
    return ranges


def _covered(line: int, ranges: List[Tuple[int, int]]) -> bool:
    return any(lo <= line <= hi for lo, hi in ranges)


class _Provenance:
    """Per-function map of local names assigned from device values."""

    def __init__(self, fn: ast.AST, np_aliases: Set[str],
                 jax_aliases: Set[str]):
        self.np_aliases = np_aliases
        self.jax_aliases = jax_aliases
        self.device_names: Set[str] = set()
        # two passes so order of assignment vs use doesn't matter
        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if self.is_device(node.value):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self.device_names.add(t.id)

    def is_device(self, expr: ast.AST) -> bool:
        expr = self._unwrap(expr)
        if isinstance(expr, ast.Attribute):
            if expr.attr in DEVICE_ATTRS:
                return True
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.device_names
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_device(e) for e in expr.elts)
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr in HOST_JAX_CALLS:
                return False
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in self.jax_aliases:
                return True
        return False

    @staticmethod
    def _unwrap(expr: ast.AST) -> ast.AST:
        while isinstance(expr, (ast.Subscript, ast.Starred)):
            expr = expr.value
        return expr


class SyncSpanRule(Rule):
    id = "sync-span"
    description = ("implicit device->host syncs must run inside a "
                   "device.block span (profiler lane honesty)")

    def __init__(self, skip_files: Optional[Set[str]] = None):
        self.skip_files = (frozenset(skip_files) if skip_files is not None
                           else SKIP_FILES)

    def _aliases(self, package: Package, rel: str
                 ) -> Tuple[Set[str], Set[str]]:
        mi = package.index().module(rel)
        np_aliases: Set[str] = set()
        jax_aliases: Set[str] = set()
        if mi is None:
            return np_aliases, jax_aliases
        for local in mi.imports:
            root = mi.external_root(local)
            if root == "numpy":
                np_aliases.add(local)
            elif root == "jax":
                jax_aliases.add(local)
        return np_aliases, jax_aliases

    def run(self, package: Package) -> List[Finding]:
        findings: List[Finding] = []
        for sf in package.files:
            if sf.rel in self.skip_files:
                continue
            np_aliases, jax_aliases = self._aliases(package, sf.rel)
            spans = _span_ranges(sf)
            seen: Set[Tuple[int, int]] = set()  # nested defs walk twice
            for fn, _cls in walk_functions(sf):
                prov = _Provenance(fn, np_aliases, jax_aliases)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    pos = (node.lineno, node.col_offset)
                    if pos in seen:
                        continue
                    hit = self._classify(node, prov, np_aliases,
                                         jax_aliases)
                    if hit is None:
                        continue
                    seen.add(pos)
                    if _covered(node.lineno, spans):
                        continue
                    findings.append(make_finding(
                        self.id, sf, node.lineno,
                        f"{hit} outside a device.block span (wrap with "
                        "trace_span(\"device.block\", site=...) or "
                        "suppress with a reason)"))
        return findings

    def _classify(self, call: ast.Call, prov: _Provenance,
                  np_aliases: Set[str], jax_aliases: Set[str]
                  ) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if f.attr == "device_get" and isinstance(base, ast.Name) \
                    and base.id in jax_aliases:
                return "jax.device_get sync"
            if f.attr == "item" and not call.args and not call.keywords:
                return "scalar .item() sync"
            if f.attr == "asarray" and isinstance(base, ast.Name) \
                    and base.id in np_aliases and call.args:
                # dtype=object arrays are host-only by construction
                # (dictionary value tables, not device buffers)
                for kw in call.keywords:
                    if kw.arg == "dtype" and \
                            isinstance(kw.value, ast.Name) and \
                            kw.value.id == "object":
                        return None
                if prov.is_device(call.args[0]):
                    return "np.asarray on a device value"
        elif isinstance(f, ast.Name) and f.id == "device_get":
            return "device_get sync"
        return None
