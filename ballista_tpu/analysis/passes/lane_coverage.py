"""lane-coverage: every span name must be known to an attribution map.

The profiler's lane decomposition (``export.compute_lanes`` via
``export.LANE_SPANS``) and the latency ledger's span-derived phases
(``ledger.LEDGER_SPANS``) both attribute query wall time by SPAN NAME.
A new ``trace_span("foo.bar", ...)`` that neither map knows about
silently lands in whatever residual lane encloses it ("other" for the
profiler, ``device_execute``/``unattributed`` for the ledger) — the
attribution drifts without any test failing. This pass closes the
loop: every constant span/event name emitted anywhere in the package
must be either

- mapped by ``export.LANE_SPANS`` or ``ledger.LEDGER_SPANS``,
- covered by a mapped PREFIX (``ingest.*`` — compute_lanes folds the
  whole ingest family into the parse/h2d lanes by prefix), or
- on the explicit :data:`UNMAPPED_ALLOWLIST` with a justification.

Dynamic names (f-strings, concatenation — e.g. the admission plane's
``admission.{action}`` events) are structurally invisible to an AST
constant scan and are exercised by the runtime tests instead.

The registries import lazily inside ``run`` (live-package rule, same
as metric-names) so the pure-AST rules stay usable standalone.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, Package, Rule, make_finding

# span names that are DELIBERATELY unmapped: control-plane envelopes
# and markers that never represent attributable query wall time. Every
# entry carries its justification — an unexplained span name belongs in
# a map, not here.
UNMAPPED_ALLOWLIST = {
    # structural task envelope: compute_lanes keys per-process tracks
    # and flow arrows off it, and capture_task_profile bounds task
    # windows with it — its children are the attributed spans
    "executor.task",
    # scheduler-side planning envelope; planning wall time reaches the
    # ledger through the scheduler's explicit planning STAMP, and the
    # span exists for the merged artifact's scheduler track
    "scheduler.plan_job",
    # scheduler dispatch bookkeeping: control-plane time, not part of
    # any single query's attributable wall
    "scheduler.task_dispatch",
    # cancellation marker event (dur=0): lifecycle, not latency
    "lifecycle.cancel",
    # adaptive re-planning markers: they fire INSIDE windows that are
    # already attributed (standalone collect / executor task)
    "adaptive.standalone",
    "adaptive.replan",
    # whole-stage fusion runs inside the planning phase, which both
    # paths stamp wholesale (client ledger_phase / scheduler stamp)
    "compile.fuse",
    # control-plane events: restart recovery, degraded-mode entry,
    # cost-feedback persistence, autoscaler decisions — scheduler
    # lifetime, no per-query wall time to attribute
    "controlplane.recover",
    "controlplane.degraded",
    "controlplane.costs",
    "controlplane.autoscale",
}

# name prefixes an attribution surface handles wholesale:
# compute_lanes folds every ``ingest.*`` span into parse/h2d by prefix
MAPPED_PREFIXES = ("ingest.",)


class LaneCoverageRule(Rule):
    id = "lane-coverage"
    description = ("span names every attribution map ignores (lane/"
                   "ledger coverage drift)")

    def run(self, package: Package) -> List[Finding]:
        from ballista_tpu.observability.export import LANE_SPANS
        from ballista_tpu.observability.ledger import LEDGER_SPANS

        mapped = set(LANE_SPANS) | set(LEDGER_SPANS)
        findings: List[Finding] = []
        for sf in package.files:
            for node in ast.walk(sf.tree):
                name = _span_name(node)
                if name is None or name in mapped or \
                        name in UNMAPPED_ALLOWLIST or \
                        name.startswith(MAPPED_PREFIXES):
                    continue
                findings.append(make_finding(
                    self.id, sf, node.lineno,
                    f"span {name!r} is unknown to export.LANE_SPANS, "
                    "ledger.LEDGER_SPANS and the unmapped allowlist — "
                    "its wall time silently lands in a residual lane "
                    "(map it, or allowlist it with a justification)"))
        return findings


def _span_name(node: ast.AST):
    """The constant first argument of a trace_span/trace_event call,
    else None."""
    if not isinstance(node, ast.Call) or not node.args:
        return None
    f = node.func
    fname = (f.id if isinstance(f, ast.Name)
             else f.attr if isinstance(f, ast.Attribute) else "")
    if fname not in ("trace_span", "trace_event"):
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return first.value
    return None
