"""cancel-coverage: chunk/partition loops on the data path must check
their cancel token.

PR 9 made cancellation cooperative — work stops at batch/partition
boundaries via :func:`ballista_tpu.lifecycle.check_cancel` — and PR 12
extended the contract to every shuffle chunk boundary. The invariant
("every loop that does per-chunk work on a cancel-critical path checks
the token") was enforced only by review until now; this pass encodes
it:

- scope: the modules that make up the executor task-runner, shuffle
  read/write and ingest producer paths (``CANCEL_CRITICAL_MODULES`` —
  the ground truth set named in docs/robustness.md + docs/shuffle.md).
- a ``for``/``while`` statement there is a *chunk loop* when its
  header (for: target+iterable; while: test + names assigned in the
  body) mentions batch/chunk/partition-vocabulary identifiers
  (word-level match, so ``num_record_batches`` counts but
  ``partitioning`` does not), or its iterable calls a known producer
  (``execute``/``scan``/``fetch*``). Comprehensions are exempt
  (in-memory, no blocking work per element), as are loops whose body
  performs no calls at all (pure metadata walks).
- the loop is covered when its body (or a function it calls, ONE level
  of call-graph following through the import-resolving index) contains
  a cancel check: ``check_cancel()``, ``token.check()``,
  ``job_stream_cancelled(...)``, a read of ``.cancelled``, or an
  ``is_set()`` probe on a cancel/closed/stop flag.

Anything else is a finding — fix it with a ``check_cancel()`` at the
loop boundary, or suppress with ``# ballista: ignore[cancel-coverage]``
plus a reason when the loop is genuinely bounded elsewhere.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..callgraph import call_name, identifiers, name_words, walk_functions
from ..engine import Finding, Package, Rule, SourceFile, make_finding

# the executor task runner, shuffle read/write and ingest producer
# paths — the set PR 9/12 made cancel-safe and review has been guarding
CANCEL_CRITICAL_MODULES = frozenset({
    "ballista_tpu/distributed/executor.py",
    "ballista_tpu/distributed/dataplane.py",
    "ballista_tpu/distributed/spill.py",
    "ballista_tpu/distributed/flight.py",
    "ballista_tpu/physical/shuffle.py",
    "ballista_tpu/ingest/pipeline.py",
    "ballista_tpu/io/ipc.py",
    "ballista_tpu/io/parquet.py",
    "ballista_tpu/io/text.py",
    "ballista_tpu/io/native.py",
    "ballista_tpu/io/cache.py",
    "ballista_tpu/execution.py",
})

CHUNK_WORDS = frozenset({
    "batch", "batches", "chunk", "chunks", "part", "parts", "partition",
    "partitions", "piece", "pieces", "rb", "frame", "frames", "segment",
    "segments",
})

# a for-loop iterating a call to one of these is a chunk loop even when
# no vocabulary identifier appears (``for b in plan.execute(p)``)
PRODUCER_CALLS = frozenset({"execute", "scan", "fetch", "replay"})

# direct satisfiers: a call to one of these inside the loop body
CHECK_CALLS = frozenset({"check_cancel", "job_stream_cancelled"})
# receiver-gated satisfiers: <token-ish>.check() / <token-ish>.cancelled
# (an unrelated validator.check(b) or future.cancelled() must NOT
# satisfy the rule)
TOKEN_WORDS = ("token", "cancel")
# flag-probe satisfier: <something cancel/closed/stop-ish>.is_set()
FLAG_WORDS = ("cancel", "closed", "stop", "drain")


def _receiver_ident(func: ast.Attribute) -> str:
    base = func.value
    if isinstance(base, ast.Attribute):
        return base.attr
    if isinstance(base, ast.Name):
        return base.id
    return ""


def _chunky_words(idents) -> bool:
    for ident in idents:
        for w in name_words(ident):
            if w in CHUNK_WORDS:
                return True
    return False


def _is_chunk_loop(node: ast.AST) -> bool:
    if isinstance(node, ast.For):
        if _chunky_words(identifiers(node.target)
                         + identifiers(node.iter)):
            return True
        for call in ast.walk(node.iter):
            if isinstance(call, ast.Call):
                name = call_name(call) or ""
                words = set(name_words(name))
                if words & PRODUCER_CALLS or name in PRODUCER_CALLS:
                    return True
        return False
    if isinstance(node, ast.While):
        idents = identifiers(node.test)
        idents.extend(_assigned_names(node.body))
        return _chunky_words(idents)
    return False


def _assigned_names(stmts) -> List[str]:
    """Assignment-target identifiers anywhere in ``stmts`` (descending
    through try/with/if, NOT into nested defs — their loops report for
    themselves)."""
    out: List[str] = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    out.extend(identifiers(t))
            elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                out.extend(identifiers(child.target))
            visit(child)

    for stmt in stmts:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                out.extend(identifiers(t))
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            out.extend(identifiers(stmt.target))
        visit(stmt)
    return out


def _does_work(node: ast.AST) -> bool:
    """A loop with zero calls in its body is a pure metadata walk."""
    body = node.body + getattr(node, "orelse", [])
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                return True
    return False


def _has_direct_check(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = call_name(n)
            if name in CHECK_CALLS:
                return True
            if name == "check" and isinstance(n.func, ast.Attribute):
                ident = _receiver_ident(n.func).lower()
                if any(w in ident for w in TOKEN_WORDS):
                    return True
            if name == "is_set" and isinstance(n.func, ast.Attribute):
                ident = _receiver_ident(n.func).lower()
                if any(w in ident for w in FLAG_WORDS):
                    return True
        elif isinstance(n, ast.Attribute) and n.attr == "cancelled":
            ident = _receiver_ident(n).lower()
            if not ident or any(w in ident for w in TOKEN_WORDS):
                return True
    return False


def _own_loops(fn: ast.AST):
    """Loop statements belonging to ``fn`` itself (nested defs report
    their own loops when walk_functions yields them)."""

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, (ast.For, ast.While)):
                yield child
            yield from visit(child)

    yield from visit(fn)


class CancelCoverageRule(Rule):
    id = "cancel-coverage"
    description = ("chunk/partition loops on executor, shuffle and "
                   "ingest paths must check their cancel token")

    def __init__(self, critical_modules: Optional[Set[str]] = None):
        self.critical_modules = (frozenset(critical_modules)
                                 if critical_modules is not None
                                 else CANCEL_CRITICAL_MODULES)

    def _loop_covered(self, sf: SourceFile, loop: ast.AST,
                      cls: Optional[str], package: Package) -> bool:
        body = ast.Module(body=list(loop.body), type_ignores=[])
        if _has_direct_check(body):
            return True
        # one level of call-graph following: a body call whose resolved
        # definition contains a direct check covers the loop
        index = package.index()
        for n in ast.walk(body):
            if not isinstance(n, ast.Call):
                continue
            fi = index.resolve_call(sf.rel, n, cls)
            if fi is not None and _has_direct_check(fi.node):
                return True
        return False

    def run(self, package: Package) -> List[Finding]:
        findings: List[Finding] = []
        for sf in package.files:
            if sf.rel not in self.critical_modules:
                continue
            for fn, cls in walk_functions(sf):
                for node in _own_loops(fn):
                    if not _is_chunk_loop(node) or not _does_work(node):
                        continue
                    if self._loop_covered(sf, node, cls, package):
                        continue
                    findings.append(make_finding(
                        self.id, sf, node.lineno,
                        f"chunk loop in {cls + '.' if cls else ''}"
                        f"{fn.name} has no cancel check in its body "
                        "(add check_cancel() at the boundary)"))
        return findings
