"""h2d-discipline: scan-side host->device upload sites must route
through the device-residency layer.

The warm-path table cache (``cache/residency.py``, docs/caching.md)
pins hot scan outputs device-resident so repeat scans skip parse AND
the H2D copy entirely. That only works if every table-source upload
funnels through ONE integration point — ``serve_or_fill`` — with the
actual uploads living in the produce callback behind it. A scan source
that uploads directly from ``scan()`` (or never routes through the
residency layer at all) silently re-pays H2D on every query and its
bytes are invisible to the device-memory governor: exactly the drift
this pass prevents after the fact reviews would otherwise catch by
hand.

Scope: modules under an ``io/`` package directory that implement a
table source (define a class with a ``scan`` method). Upload sites:

- ``ColumnBatch.from_numpy(...)`` — the engine's canonical batch
  upload (``jnp.asarray`` inside);
- ``jnp.asarray(...)`` / ``jnp.array(...)`` — direct device placement
  (``import jax.numpy as jnp`` provenance, plain numpy is host-only);
- ``jax.device_put(...)`` / ``device_put(...)`` — the explicit H2D.

A site is covered when its module routes scans through
``serve_or_fill`` AND the site sits outside the ``scan`` method body
(i.e. behind the residency layer's produce callback, conventionally
``_scan_direct``). Anything else is a finding: route the source
through the residency layer, or suppress with
``# ballista: ignore[h2d-discipline]`` and a reason (e.g. memtables,
whose batches are uploaded once at registration and are already
permanently resident).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ..engine import Finding, Package, Rule, SourceFile, make_finding


def _scan_source_module(tree: ast.AST) -> bool:
    """True when the module defines a class with a ``scan`` method
    (a TableSource implementor — shuffle IPC codecs are out of
    scope)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name == "scan":
                    return True
    return False


def _routes_through_residency(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute) else "")
            if name == "serve_or_fill":
                return True
    return False


def _scan_ranges(tree: ast.AST) -> List[Tuple[int, int]]:
    """Line ranges of every ``scan`` method body — uploads there run
    in FRONT of the residency layer, which is the violation."""
    return [
        (node.lineno, node.end_lineno or node.lineno)
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name == "scan"
    ]


class H2dDisciplineRule(Rule):
    id = "h2d-discipline"
    description = ("scan-side H2D upload sites must route through the "
                   "device-residency layer (cache/residency.py)")

    def _jax_aliases(self, package: Package, rel: str) -> Set[str]:
        mi = package.index().module(rel)
        if mi is None:
            return set()
        return {local for local in mi.imports
                if mi.external_root(local) == "jax"}

    def run(self, package: Package) -> List[Finding]:
        findings: List[Finding] = []
        for sf in package.files:
            parts = sf.rel.split("/")
            if "io" not in parts[:-1]:
                continue
            if sf.tree is None or not _scan_source_module(sf.tree):
                continue
            jax_aliases = self._jax_aliases(package, sf.rel)
            routed = _routes_through_residency(sf.tree)
            scan_spans = _scan_ranges(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = self._upload_kind(node, jax_aliases)
                if kind is None:
                    continue
                in_scan = any(lo <= node.lineno <= hi
                              for lo, hi in scan_spans)
                if routed and not in_scan:
                    continue
                why = ("inside scan() in front of the residency layer"
                       if routed else
                       "in a module that never routes through "
                       "serve_or_fill")
                findings.append(make_finding(
                    self.id, sf, node.lineno,
                    f"{kind} {why} (move uploads behind "
                    "cache.residency.serve_or_fill's produce callback "
                    "or suppress with a reason)"))
        return findings

    @staticmethod
    def _upload_kind(call: ast.Call,
                     jax_aliases: Set[str]) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr == "from_numpy":
                return "ColumnBatch.from_numpy upload"
            base = f.value
            if isinstance(base, ast.Name) and base.id in jax_aliases:
                if f.attr in ("asarray", "array"):
                    return f"jnp.{f.attr} upload"
                if f.attr == "device_put":
                    return "jax.device_put upload"
        elif isinstance(f, ast.Name) and f.id == "device_put":
            return "device_put upload"
        return None
