"""lock-discipline: shared mutable state in threaded modules must be
written under a held lock; hand-rolled double-checked locking is
flagged.

The engine's concurrency contract (ingest pool, executor task threads,
dataplane server threads) routes every shared one-shot materialization
through :class:`ballista_tpu.ingest.KeyedLocks` and guards module-level
mutable containers with a module lock. PRs 4/5/12 each fixed a
review-caught violation of exactly this (double-checked-locking races
in tracing and the agg layout cache). Two sub-rules:

**unguarded-write** — in any module that uses threading (imports
``threading`` / ``concurrent.futures``), a write to a module-level
mutable container (dict/list/set/deque literal or constructor) from
inside a function must be lexically inside a ``with <lock>`` block
(any context manager whose expression mentions a lock/guard/mutex
name, including ``KeyedLocks.get``). Exception by convention: functions
named ``*_locked`` assert their callers hold the lock (the pattern
tracing.py documents).

**double-checked-locking** — ``if C: with lock: if C:`` re-check
shapes are flagged unless the lock comes from a ``KeyedLocks``-style
``.get(...)`` (receiver name containing "locks"): hand-rolled DCL is
where the PR 4/5 races lived, and KeyedLocks is the blessed carrier
for the pattern. Correct-but-manual instances get a baseline entry
with a justification instead of a rewrite.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..callgraph import walk_functions
from ..engine import Finding, Package, Rule, SourceFile, make_finding

MUTABLE_CALLS = frozenset({
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter",
})

MUTATORS = frozenset({
    "append", "appendleft", "add", "update", "setdefault", "pop",
    "popleft", "extend", "remove", "discard", "clear", "insert",
})

LOCK_WORDS = ("lock", "guard", "mutex")


def _is_mutable_ctor(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        f = expr.func
        name = (f.id if isinstance(f, ast.Name)
                else f.attr if isinstance(f, ast.Attribute) else "")
        return name in MUTABLE_CALLS
    return False


def _module_containers(sf: SourceFile) -> Dict[str, int]:
    """{name: def line} of module-level mutable containers."""
    out: Dict[str, int] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and _is_mutable_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.lineno
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and _is_mutable_ctor(node.value) \
                and isinstance(node.target, ast.Name):
            out[node.target.id] = node.lineno
    return out


def _uses_threading(package: Package, rel: str) -> bool:
    mi = package.index().module(rel)
    if mi is None:
        return False
    for local in mi.imports:
        dotted = mi.external_dotted(local) or ""
        if dotted.split(".")[0] in ("threading", "concurrent"):
            return True
    return False


def _mentions_lock(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        ident = (n.id if isinstance(n, ast.Name)
                 else n.attr if isinstance(n, ast.Attribute) else "")
        if ident and any(w in ident.lower() for w in LOCK_WORDS):
            return True
    return False


def _lock_ranges(fn: ast.AST) -> List[tuple]:
    ranges = []
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                if _mentions_lock(item.context_expr):
                    ranges.append((node.lineno,
                                   node.end_lineno or node.lineno))
                    break
    return ranges


def _writes(fn: ast.AST, containers: Set[str]):
    """Yield (line, name) for every mutation of a tracked container."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in containers:
            yield node.lineno, node.func.value.id
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in containers:
                    yield node.lineno, t.value.id
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id in containers:
                    yield node.lineno, t.value.id


def _keyed_locks_with(node: ast.With) -> bool:
    """True when any with-item acquires via ``<...locks...>.get(...)`` —
    the KeyedLocks carrier for per-key double-checked materialization."""
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
                and e.func.attr == "get":
            recv = e.func.value
            ident = (recv.attr if isinstance(recv, ast.Attribute)
                     else recv.id if isinstance(recv, ast.Name) else "")
            if "lock" in ident.lower():
                return True
    return False


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    description = ("shared mutable state written without a held lock / "
                   "hand-rolled double-checked locking")

    def run(self, package: Package) -> List[Finding]:
        findings: List[Finding] = []
        for sf in package.files:
            findings.extend(self._unguarded_writes(package, sf))
            findings.extend(self._dcl(sf))
        return findings

    def _unguarded_writes(self, package: Package, sf: SourceFile
                          ) -> List[Finding]:
        containers = _module_containers(sf)
        if not containers or not _uses_threading(package, sf.rel):
            return []
        tracked = set(containers)
        findings: List[Finding] = []
        seen: Set[tuple] = set()  # nested defs are walked by their parent too
        locked_fns = [fn for fn, _ in walk_functions(sf)
                      if fn.name.endswith("_locked")]
        for fn, cls in walk_functions(sf):
            if fn.name.endswith("_locked"):
                continue  # convention: caller holds the lock
            ranges = _lock_ranges(fn)
            # a *_locked helper nested in/next to this fn keeps its own
            # exemption even when the parent's walk reaches its writes
            ranges += [(f.lineno, f.end_lineno or f.lineno)
                       for f in locked_fns]
            for line, name in _writes(fn, tracked):
                if (line, name) in seen:
                    continue
                seen.add((line, name))
                if any(lo <= line <= hi for lo, hi in ranges):
                    continue
                findings.append(make_finding(
                    self.id, sf, line,
                    f"module-level mutable '{name}' written in "
                    f"{cls + '.' if cls else ''}{fn.name} without a "
                    "held lock (threaded module)"))
        return findings

    def _dcl(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.If):
                continue
            test_dump = ast.dump(node.test)
            for stmt in node.body:
                if not isinstance(stmt, ast.With):
                    continue
                if _keyed_locks_with(stmt):
                    continue
                for inner in stmt.body:
                    if isinstance(inner, ast.If) and \
                            ast.dump(inner.test) == test_dump:
                        findings.append(make_finding(
                            self.id, sf, node.lineno,
                            "hand-rolled double-checked locking (route "
                            "per-key materialization through "
                            "ingest.KeyedLocks, or triage with a "
                            "baseline note)"))
        return findings
