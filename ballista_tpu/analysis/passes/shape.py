"""The five code-shape lints, ported from their standalone ``dev/``
scripts onto the engine (the scripts remain as thin shims with their
original CLI/exit semantics).

Ports are AST-based where the originals were regex-based — docstring
skipping falls out for free (a docstring mentioning ``jax.jit`` is not
a Call node) — but keep the original allowlists and per-line opt-out
markers (``# jit-ok:``, ``# dict-ok:``, ``# metric-names: ...``,
``# fault-points: ...``) so existing annotated code keeps passing
byte-for-byte. The three registry-backed rules import their registries
lazily inside ``run`` so the pure-AST rules stay usable standalone
(staged lint self-tests, fixture trees).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from ..callgraph import call_name
from ..engine import Finding, Package, Rule, SourceFile, make_finding

# the analysis package contains rule patterns and marker strings that
# would confuse the shape lints scanning it — it is machinery, like
# observability/metrics.py is for metric recording
_ANALYSIS_DIR = "ballista_tpu/analysis/"
_PROTO_DIR = "ballista_tpu/proto/"


def _first_arg_literal(call: ast.Call) -> Optional[str]:
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


# ---------------------------------------------------------------------------
# jit-sites (dev/check_jit_sites.py)
# ---------------------------------------------------------------------------


class JitSitesRule(Rule):
    id = "jit-sites"
    description = ("raw jax.jit/pjit call sites outside the compile "
                   "governor")

    ALLOWLIST = frozenset({
        "ballista_tpu/compile/governor.py",  # THE jit site: the governor
        # fused-stage AOT export wraps a governed entry's own python
        # function for jax.export serialization — no uncounted cache
        "ballista_tpu/compile/aot.py",
    })
    MARKER = "jit-ok:"

    def __init__(self, allowlist: Optional[Set[str]] = None):
        self.allowlist = (frozenset(allowlist) if allowlist is not None
                          else self.ALLOWLIST)

    def _is_jit_ref(self, node: ast.AST, jax_aliases: Set[str]) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in ("jit", "pjit"):
            base = node.value
            return isinstance(base, ast.Name) and base.id in jax_aliases
        if isinstance(node, ast.Name) and node.id == "pjit":
            return True
        return False

    def run(self, package: Package) -> List[Finding]:
        findings: List[Finding] = []
        for sf in package.files:
            if sf.rel in self.allowlist or \
                    sf.rel.startswith(_ANALYSIS_DIR):
                continue
            mi = package.index().module(sf.rel)
            jax_aliases = {
                local for local in (mi.imports if mi else {})
                if mi.external_root(local) == "jax"
            } or {"jax"}
            for node in ast.walk(sf.tree):
                ref = None
                if isinstance(node, ast.Call) and \
                        self._is_jit_ref(node.func, jax_aliases):
                    ref = node.func
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        d = dec.func if isinstance(dec, ast.Call) else dec
                        if self._is_jit_ref(d, jax_aliases):
                            ref = d
                            break
                if ref is None:
                    continue
                if self.MARKER in sf.line(ref.lineno):
                    continue
                findings.append(make_finding(
                    self.id, sf, ref.lineno,
                    "raw jax.jit/pjit site outside ballista_tpu/compile/ "
                    "— route through ballista_tpu.compile.governed()"))
        return findings


# ---------------------------------------------------------------------------
# dict-sites (dev/check_dict_sites.py)
# ---------------------------------------------------------------------------


class DictSitesRule(Rule):
    id = "dict-sites"
    description = ("host np.unique/np.searchsorted outside the "
                   "dictionary registry")

    ALLOWLIST = frozenset({
        # THE unify/remap site: versioned unions, cached remap tables
        "ballista_tpu/columnar_registry.py",
        # the Dictionary's own encode/canonicalize/search primitives
        "ballista_tpu/columnar.py",
    })
    MARKER = "dict-ok:"

    def __init__(self, allowlist: Optional[Set[str]] = None):
        self.allowlist = (frozenset(allowlist) if allowlist is not None
                          else self.ALLOWLIST)

    def run(self, package: Package) -> List[Finding]:
        findings: List[Finding] = []
        for sf in package.files:
            if sf.rel in self.allowlist or \
                    sf.rel.startswith(_ANALYSIS_DIR):
                continue
            mi = package.index().module(sf.rel)
            np_aliases = {
                local for local in (mi.imports if mi else {})
                if mi.external_root(local) == "numpy"
            } or {"np"}
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("unique", "searchsorted")
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in np_aliases):
                    continue
                if self.MARKER in sf.line(node.lineno):
                    continue
                findings.append(make_finding(
                    self.id, sf, node.lineno,
                    "host dictionary unify/remap outside the registry — "
                    "route through ballista_tpu.columnar_registry (or "
                    "mark a non-dictionary use with '# dict-ok: reason')"))
        return findings


# ---------------------------------------------------------------------------
# metric-names (dev/check_metric_names.py)
# ---------------------------------------------------------------------------

_METRIC_ANNOTATION = re.compile(r"#\s*metric-names:\s*([\w\s,-]+)")
_PROM_NAME = re.compile(r"ballista_[A-Za-z0-9_]+\Z")
# the package's own name matches the family pattern but is not a metric
_NOT_FAMILIES = frozenset({"ballista_tpu"})


class MetricNamesRule(Rule):
    id = "metric-names"
    description = "metric names drifting out of the registry"

    SKIP_FILES = frozenset({
        # the recording machinery re-emits caller-supplied names
        "ballista_tpu/observability/metrics.py",
    })
    CALLS = frozenset({"add_counter", "add_time", "set_gauge"})

    def __init__(self):
        self._parents_cache: Dict[int, Dict[int, ast.AST]] = {}

    def run(self, package: Package) -> List[Finding]:
        from ballista_tpu.observability.registry import (
            OPERATOR_METRICS,
            PROCESS_METRICS,
        )

        findings: List[Finding] = []
        for sf in package.files:
            if sf.rel in self.SKIP_FILES or \
                    sf.rel.startswith((_PROTO_DIR, _ANALYSIS_DIR)):
                continue
            dyn_lines: Set[int] = set()
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call) and \
                        call_name(node) in self.CALLS:
                    lit = _first_arg_literal(node)
                    if lit is None:
                        dyn_lines.add(node.lineno)
                    elif lit not in OPERATOR_METRICS:
                        findings.append(make_finding(
                            self.id, sf, node.lineno,
                            f"literal metric name {lit!r} not in "
                            "OPERATOR_METRICS registry"))
                elif isinstance(node, ast.Constant) and \
                        isinstance(node.value, str) and \
                        node.value not in _NOT_FAMILIES and \
                        _PROM_NAME.match(node.value):
                    # prometheus family literals in sample tuples/calls
                    # (docstrings are Expr-statement constants: skipped)
                    if node.value not in PROCESS_METRICS and \
                            self._in_data_position(sf, node):
                        findings.append(make_finding(
                            self.id, sf, node.lineno,
                            f"prometheus family {node.value!r} not in "
                            "PROCESS_METRICS registry"))
            for line in sorted(dyn_lines):
                ann = _METRIC_ANNOTATION.search(sf.line(line))
                if ann is None:
                    findings.append(make_finding(
                        self.id, sf, line,
                        "dynamic metric name without a "
                        "'# metric-names: ...' annotation"))
                    continue
                for name in re.split(r"[\s,]+", ann.group(1).strip()):
                    if name and name not in OPERATOR_METRICS:
                        findings.append(make_finding(
                            self.id, sf, line,
                            f"annotated metric name {name!r} not in "
                            "OPERATOR_METRICS registry"))
        return findings

    def _in_data_position(self, sf: SourceFile, node: ast.Constant) -> bool:
        """Mirror the original regex's intent ("ballista_x", — a name in
        a sample tuple or argument list), excluding docstrings and bare
        expression statements."""
        parents = self._parents_for(sf)
        p = parents.get(id(node))
        return isinstance(p, (ast.Tuple, ast.List, ast.Call, ast.Dict,
                              ast.Set, ast.Compare, ast.keyword))

    def _parents_for(self, sf: SourceFile) -> Dict[int, ast.AST]:
        cached = self._parents_cache.get(id(sf))
        if cached is None:
            cached = {}
            for parent in ast.walk(sf.tree):
                for child in ast.iter_child_nodes(parent):
                    cached[id(child)] = parent
            self._parents_cache[id(sf)] = cached
        return cached


# ---------------------------------------------------------------------------
# fault-points (dev/check_fault_points.py)
# ---------------------------------------------------------------------------

_FAULT_ANNOTATION = re.compile(r"#\s*fault-points:\s*([\w\s.,-]+)")


class FaultPointsRule(Rule):
    id = "fault-points"
    description = ("fault_point call sites vs the FAULT_POINTS "
                   "registry (symmetric)")

    SKIP_FILES = frozenset({
        "ballista_tpu/testing/faults.py",  # the machinery itself
    })
    REGISTRY_FILE = "ballista_tpu/testing/faults.py"

    def run(self, package: Package) -> List[Finding]:
        from ballista_tpu.testing.faults import FAULT_POINTS

        findings: List[Finding] = []
        used: Dict[str, int] = {p: 0 for p in FAULT_POINTS}
        for sf in package.files:
            if sf.rel in self.SKIP_FILES or \
                    sf.rel.startswith((_PROTO_DIR, _ANALYSIS_DIR)):
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and call_name(node) == "fault_point"):
                    continue
                lit = _first_arg_literal(node)
                if lit is not None:
                    if lit in used:
                        used[lit] += 1
                    else:
                        findings.append(make_finding(
                            self.id, sf, node.lineno,
                            f"literal fault-point name {lit!r} not in "
                            "FAULT_POINTS registry"))
                    continue
                ann = _FAULT_ANNOTATION.search(sf.line(node.lineno))
                if ann is None:
                    findings.append(make_finding(
                        self.id, sf, node.lineno,
                        "dynamic fault-point name without a "
                        "'# fault-points: ...' annotation"))
                    continue
                for name in sorted({t for t in
                                    re.split(r"[\s,]+", ann.group(1))
                                    if t}):
                    if name in used:
                        used[name] += 1
                    else:
                        findings.append(make_finding(
                            self.id, sf, node.lineno,
                            f"annotated fault-point name {name!r} not "
                            "in FAULT_POINTS registry"))
        reg = package.by_rel.get(self.REGISTRY_FILE)
        for point in sorted(p for p, n in used.items() if n == 0):
            findings.append(Finding(
                self.id, self.REGISTRY_FILE,
                1 if reg is None else self._registry_line(reg, point),
                f"registered fault point {point!r} has no call site "
                "(an armable fault that can never fire)",
                anchor=f"fault-point:{point}"))
        return findings

    @staticmethod
    def _registry_line(sf: SourceFile, point: str) -> int:
        needle = f'"{point}"'
        for i, line in enumerate(sf.lines, 1):
            if needle in line:
                return i
        return 1


# ---------------------------------------------------------------------------
# knob-docs (dev/check_knob_docs.py)
# ---------------------------------------------------------------------------

_KNOB_EXACT = re.compile(r"^BALLISTA_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
_KNOB_PREFIX = re.compile(r"^BALLISTA_[A-Z0-9]+(?:_[A-Z0-9]+)*_$")
_README_TOKEN = re.compile(r"\bBALLISTA_[A-Z0-9_]+\b")

# "BALLISTA_" alone is the base of dynamically-composed env names
_IGNORED_LITERALS = frozenset({"BALLISTA" + "_"})


class KnobDocsRule(Rule):
    id = "knob-docs"
    description = ("BALLISTA_* knob drift between source, "
                   "system.settings registry and README")

    README = "README.md"

    def run(self, package: Package) -> List[Finding]:
        from ballista_tpu.observability.systables import (
            KNOB_PREFIXES,
            KNOBS,
        )

        prefixes = set(KNOB_PREFIXES)
        registry = set(KNOBS)
        literals: Dict[str, List] = {}
        for sf in package.files:
            if sf.rel.startswith(_ANALYSIS_DIR):
                continue
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    v = node.value
                    if v in _IGNORED_LITERALS:
                        continue
                    if _KNOB_EXACT.match(v) or _KNOB_PREFIX.match(v):
                        literals.setdefault(v, []).append((sf, node.lineno))

        findings: List[Finding] = []

        def global_finding(anchor: str, message: str,
                           sf: Optional[SourceFile] = None,
                           line: int = 1) -> None:
            findings.append(Finding(
                self.id, sf.rel if sf else self.README, line, message,
                anchor=anchor))

        def covered(name: str) -> bool:
            return any(name.startswith(p) for p in prefixes)

        exact = {n for n in literals if not n.endswith("_")}
        pfx = {n for n in literals if n.endswith("_")}

        for name in sorted(exact):
            if name not in registry and not covered(name):
                sf, line = literals[name][0]
                global_finding(
                    f"knob:{name}",
                    f"knob {name} is read in the source but missing "
                    "from the system.settings registry "
                    "(observability/systables.py KNOBS)", sf, line)
        for name in sorted(pfx):
            if name not in prefixes:
                sf, line = literals[name][0]
                global_finding(
                    f"knob:{name}",
                    f"dynamic knob prefix {name} is used in the source "
                    "but not declared in KNOB_PREFIXES", sf, line)

        try:
            readme = open(f"{package.root}/README.md",
                          encoding="utf-8").read()
        except OSError:
            readme = ""
        tokens = set(_README_TOKEN.findall(readme))

        for name in sorted(registry):
            if name not in exact:
                global_finding(
                    f"knob:{name}",
                    f"registry knob {name} is not read anywhere in the "
                    "package (stale KNOBS entry?)")
            if name not in tokens:
                global_finding(
                    f"knob-doc:{name}",
                    f"registry knob {name} is missing from the README "
                    "knob tables")
        for name in sorted(prefixes):
            if name not in pfx:
                global_finding(
                    f"knob:{name}",
                    f"declared prefix {name} is not used anywhere in "
                    "the package (stale KNOB_PREFIXES entry?)")
        for tok in sorted(tokens):
            if tok in registry or covered(tok):
                continue
            global_finding(
                f"knob-doc:{tok}",
                f"README mentions {tok}, which is neither a registered "
                "knob nor covered by a declared prefix")
        return findings
