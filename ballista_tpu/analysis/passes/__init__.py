"""Pass registry: every rule the engine runs, in catalogue order.

Adding a pass = subclass :class:`ballista_tpu.analysis.engine.Rule`,
implement ``run(package) -> list[Finding]``, append an instance factory
here and document the rule id in docs/static_analysis.md.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..engine import Rule
from .cancel_coverage import CancelCoverageRule
from .h2d_discipline import H2dDisciplineRule
from .lane_coverage import LaneCoverageRule
from .lock_discipline import LockDisciplineRule
from .shape import (
    DictSitesRule,
    FaultPointsRule,
    JitSitesRule,
    KnobDocsRule,
    MetricNamesRule,
)
from .sync_span import SyncSpanRule

# rule id -> zero-arg factory (instances are cheap; a fresh one per run
# keeps rules stateless across packages)
RULE_FACTORIES: Dict[str, Callable[[], Rule]] = {
    CancelCoverageRule.id: CancelCoverageRule,
    SyncSpanRule.id: SyncSpanRule,
    LaneCoverageRule.id: LaneCoverageRule,
    H2dDisciplineRule.id: H2dDisciplineRule,
    LockDisciplineRule.id: LockDisciplineRule,
    JitSitesRule.id: JitSitesRule,
    DictSitesRule.id: DictSitesRule,
    MetricNamesRule.id: MetricNamesRule,
    FaultPointsRule.id: FaultPointsRule,
    KnobDocsRule.id: KnobDocsRule,
}


def all_rules() -> List[Rule]:
    return [factory() for factory in RULE_FACTORIES.values()]


def rules_for(ids) -> List[Rule]:
    out = []
    for rid in ids:
        if rid not in RULE_FACTORIES:
            raise KeyError(
                f"unknown rule {rid!r} (known: "
                f"{', '.join(sorted(RULE_FACTORIES))})")
        out.append(RULE_FACTORIES[rid]())
    return out
