"""Generated protobuf wire contract (see ballista.proto).

Regenerate with:  protoc --python_out=. ballista.proto
"""

from . import ballista_pb2  # noqa: F401
