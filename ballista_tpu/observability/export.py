"""Profile artifact export: Chrome-trace/Perfetto JSON + lane summary.

One artifact file serves two readers:

- **chrome://tracing / Perfetto** load it directly: the top-level
  object carries a ``traceEvents`` array (Complete ``"X"`` / Instant
  ``"i"`` / Metadata ``"M"`` events, microsecond timestamps) and both
  tools ignore the extra keys.
- **Programs / humans** read the summary keys: ``wall_seconds``,
  ``lanes`` (the named wall-time decomposition), ``phases``,
  ``compile``, ``memory``, ``operators``.

Lane semantics (``lanes`` + ``lane_fractions``): measured categories
are THREAD seconds summed from their spans — under the ingest pipeline
they overlap, so their sum may legitimately exceed wall time —

- ``parse`` / ``h2d``: ingest phase totals (file parse, host->device);
- ``compile_trace_lower``: governed first-call time (jaxpr trace +
  lowering + backend compile or persistent-cache retrieval) from
  ``compile.jit`` records;
- ``device_blocked``: host time blocked on device results
  (``device.block`` spans: batched count syncs, result fetches, join
  builds);
- ``host_dictionary``: host-side numpy dictionary work
  (``host.dictionary`` spans: unify/remap/union builds);
- ``xla_execute_other``: the remainder of the wall clock after the
  measured categories (clamped at 0) — on this engine dominated by XLA
  execution and dispatch, hence the name.

``attributed_fraction`` is the fraction of wall time covered by the
MEASURED lanes (the remainder lane deliberately excluded — including a
lane defined as "whatever is left" would make the metric identically
1.0 and meaningless). 1.0 means every wall second was inside an
instrumented category; a low value means the ``xla_execute_other``
remainder carries most of the attribution and should be read as "XLA
execute + uninstrumented host work". Overlapped thread-seconds beyond
the wall clock don't raise it past 1.0.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

LANE_NAMES = ("parse", "h2d", "compile_trace_lower", "device_blocked",
              "host_dictionary", "xla_execute_other")


def compute_lanes(session: dict) -> dict:
    """The named wall-time decomposition (see module docstring)."""
    wall = float(session.get("wall_seconds", 0.0))
    phases = session.get("phases") or {}
    records = session.get("records") or []

    def span_sum(name: str, field: str = "dur") -> float:
        return float(sum(float(r.get(field, 0.0)) for r in records
                         if r.get("name") == name))

    lanes = {
        "parse": round(float(phases.get("parse", 0.0)), 6),
        "h2d": round(float(phases.get("h2d", 0.0)), 6),
        "device_blocked": round(span_sum("device.block"), 6),
        "host_dictionary": round(span_sum("host.dictionary"), 6),
    }
    compile_lane = sum(float(r.get("call_seconds", 0.0)) for r in records
                       if r.get("name") == "compile.jit")
    # AOT-loaded programs (compile/aot.py) never trace or lower; only
    # their measured backend compile/disk-retrieval seconds belong in
    # this lane — first-call execution is execution
    compile_lane += sum(float(r.get("compile_seconds", 0.0))
                        for r in records
                        if r.get("name") == "compile.aot")
    if compile_lane == 0.0:
        # no compile.jit records (tracing came up late): fall back to
        # the governor's process-stat delta
        comp = session.get("compile") or {}
        compile_lane = (float(comp.get("compile_seconds", 0.0))
                        + float(comp.get("trace_seconds", 0.0)))
    lanes["compile_trace_lower"] = round(compile_lane, 6)
    measured = sum(lanes.values())
    lanes["xla_execute_other"] = round(max(0.0, wall - measured), 6)
    out = {
        "lanes": lanes,
        "measured_seconds": round(measured, 6),
        "attributed_fraction": (round(min(1.0, measured / wall), 4)
                                if wall > 0 else 0.0),
    }
    if wall > 0:
        out["lane_fractions"] = {
            k: round(v / wall, 4) for k, v in lanes.items()
        }
    return out


def _thread_names(records: List[dict], main_tid: int) -> Dict[tuple, str]:
    """(pid, tid) -> display name: ingest producer threads get their
    own labels (their spans are what makes the overlap visible)."""
    names: Dict[tuple, str] = {}
    producer_n: Dict[int, int] = {}
    for r in records:
        key = (r.get("pid", 0), r.get("tid", 0))
        if key in names:
            continue
        if r.get("name", "").startswith("ingest.") and \
                r.get("tid") != main_tid:
            n = producer_n.get(r.get("pid", 0), 0)
            producer_n[r.get("pid", 0)] = n + 1
            names[key] = f"ingest-producer-{n}"
    for r in records:
        key = (r.get("pid", 0), r.get("tid", 0))
        if key not in names:
            names[key] = "main" if r.get("tid") == main_tid \
                else f"worker-{len(names)}"
    return names


_META_KEYS = ("name", "ts", "dur", "pid", "tid")


def to_chrome_trace(session: dict, main_tid: Optional[int] = None) -> list:
    """Session records -> Chrome trace event array."""
    records = session.get("records") or []
    t0 = float(session.get("t0", 0.0))
    if main_tid is None:
        main_tid = threading.get_ident()
    events: List[dict] = []
    seen_pids = set()
    for key, tname in _thread_names(records, main_tid).items():
        pid, tid = key
        if pid not in seen_pids:
            seen_pids.add(pid)
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0,
                           "args": {"name": f"ballista pid {pid}"}})
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": tname}})
    for r in records:
        args = {k: v for k, v in r.items() if k not in _META_KEYS}
        ev = {
            "name": r.get("name", "?"),
            "cat": str(r.get("name", "?")).split(".")[0],
            "pid": r.get("pid", 0),
            "tid": r.get("tid", 0),
            "ts": round((float(r.get("ts", t0)) - t0) * 1e6, 1),
            "args": args,
        }
        if "dur" in r:
            ev["ph"] = "X"
            ev["dur"] = round(float(r["dur"]) * 1e6, 1)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return events


def build_artifact(session: dict) -> dict:
    """Merge a profiler session into the final artifact dict."""
    art = {
        "schema": session.get("schema", "ballista-profile-v1"),
        "label": session.get("label", "query"),
        "wall_seconds": session.get("wall_seconds", 0.0),
        "phases": session.get("phases", {}),
        "compile": session.get("compile", {}),
        "memory": session.get("memory", {}),
        "operators": session.get("operators"),
        "displayTimeUnit": "ms",
        "traceEvents": to_chrome_trace(session),
    }
    art.update(compute_lanes(session))
    art["otherData"] = {
        "label": art["label"],
        "wall_seconds": art["wall_seconds"],
        "attributed_fraction": art["attributed_fraction"],
    }
    return art


def write_artifact(session: dict, out_dir: Optional[str] = None,
                   out_path: Optional[str] = None) -> str:
    """Write the artifact JSON; returns its path. ``out_path`` pins the
    exact file, otherwise a timestamped name lands in ``out_dir``
    (default: cwd)."""
    art = build_artifact(session)
    if out_path is None:
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(art["label"]))[:48] or "query"
        fname = f"ballista-profile-{safe}-{int(time.time() * 1000)}.json"
        out_path = os.path.join(out_dir or os.getcwd(), fname)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(art, fh, default=str)
    return out_path
