"""Profile artifact export: Chrome-trace/Perfetto JSON + lane summary.

One artifact file serves two readers:

- **chrome://tracing / Perfetto** load it directly: the top-level
  object carries a ``traceEvents`` array (Complete ``"X"`` / Instant
  ``"i"`` / Metadata ``"M"`` events, microsecond timestamps) and both
  tools ignore the extra keys.
- **Programs / humans** read the summary keys: ``wall_seconds``,
  ``lanes`` (the named wall-time decomposition), ``phases``,
  ``compile``, ``memory``, ``operators``.

Lane semantics (``lanes`` + ``lane_fractions``): measured categories
are THREAD seconds summed from their spans — under the ingest pipeline
they overlap, so their sum may legitimately exceed wall time —

- ``parse`` / ``h2d``: ingest phase totals (file parse, host->device);
- ``compile_trace_lower``: governed first-call time (jaxpr trace +
  lowering + backend compile or persistent-cache retrieval) from
  ``compile.jit`` records;
- ``device_blocked``: host time blocked on device results
  (``device.block`` spans: batched count syncs, result fetches, join
  builds);
- ``host_dictionary``: host-side numpy dictionary work
  (``host.dictionary`` spans: unify/remap/union builds);
- ``xla_execute_other``: the remainder of the wall clock after the
  measured categories (clamped at 0) — on this engine dominated by XLA
  execution and dispatch, hence the name.

``attributed_fraction`` is the fraction of wall time covered by the
MEASURED lanes (the remainder lane deliberately excluded — including a
lane defined as "whatever is left" would make the metric identically
1.0 and meaningless). 1.0 means every wall second was inside an
instrumented category; a low value means the ``xla_execute_other``
remainder carries most of the attribution and should be read as "XLA
execute + uninstrumented host work". Overlapped thread-seconds beyond
the wall clock don't raise it past 1.0.

**Merged (distributed) sessions** — built by
``observability/distributed.py`` from the scheduler's flight-recorder
window plus every executor's per-task profile payload — flow through
the same exporter: records carry process identity (``role`` / ``exec``
tags), so each distinct (pid, role, executor) gets its OWN process
track (synthetic display pids keep an in-process LocalCluster's
scheduler and executors on separate tracks despite one OS pid); a
``scheduler.task_dispatch`` span and its matching ``executor.task``
span are connected with Chrome-trace flow arrows (``ph:"s"``/``"f"``);
and a synthetic "job timeline" process renders a stage/task Gantt lane.
Merged sessions carry no process-wide ingest phase deltas (concurrent
tasks would cross-attribute them), so the parse/h2d lanes fall back to
summing ``ingest.parse``/``ingest.h2d`` span durations.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

LANE_NAMES = ("parse", "h2d", "compile_trace_lower", "device_blocked",
              "host_dictionary", "shuffle_spill", "xla_execute_other")

# Span name -> lane, the declarative face of compute_lanes below (which
# also folds in phase-delta fallbacks and attr-based compile sums). The
# lane-coverage analysis pass reads this map + ledger.LEDGER_SPANS to
# flag span names that NO attribution surface maps — keep it in sync
# with the span names compute_lanes consumes.
LANE_SPANS = {
    "ingest.parse": "parse",
    "ingest.h2d": "h2d",
    "compile.jit": "compile_trace_lower",
    "compile.aot": "compile_trace_lower",
    "device.block": "device_blocked",
    "host.dictionary": "host_dictionary",
    "shuffle.spill": "shuffle_spill",
}


def compute_lanes(session: dict) -> dict:
    """The named wall-time decomposition (see module docstring)."""
    wall = float(session.get("wall_seconds", 0.0))
    phases = session.get("phases") or {}
    records = session.get("records") or []

    def span_sum(name: str, field: str = "dur") -> float:
        return float(sum(float(r.get(field, 0.0)) for r in records
                         if r.get("name") == name))

    # merged cluster sessions ship no process-wide phase deltas
    # (concurrent tasks would cross-attribute them): fall back to the
    # ingest span durations, which phases.py emits from the same blocks
    parse = float(phases.get("parse", 0.0)) or span_sum("ingest.parse")
    h2d = float(phases.get("h2d", 0.0)) or span_sum("ingest.h2d")
    lanes = {
        "parse": round(parse, 6),
        "h2d": round(h2d, 6),
        "device_blocked": round(span_sum("device.block"), 6),
        "host_dictionary": round(span_sum("host.dictionary"), 6),
        # disk time the shuffle governor's spill writes/re-reads add
        # (distributed/spill.py) — zero unless the memory budget forced
        # chunks to disk
        "shuffle_spill": round(span_sum("shuffle.spill"), 6),
    }
    compile_lane = sum(float(r.get("call_seconds", 0.0)) for r in records
                       if r.get("name") == "compile.jit")
    # AOT-loaded programs (compile/aot.py) never trace or lower; only
    # their measured backend compile/disk-retrieval seconds belong in
    # this lane — first-call execution is execution
    compile_lane += sum(float(r.get("compile_seconds", 0.0))
                        for r in records
                        if r.get("name") == "compile.aot")
    if compile_lane == 0.0:
        # no compile.jit records (tracing came up late): fall back to
        # the governor's process-stat delta
        comp = session.get("compile") or {}
        compile_lane = (float(comp.get("compile_seconds", 0.0))
                        + float(comp.get("trace_seconds", 0.0)))
    lanes["compile_trace_lower"] = round(compile_lane, 6)
    measured = sum(lanes.values())
    lanes["xla_execute_other"] = round(max(0.0, wall - measured), 6)
    out = {
        "lanes": lanes,
        "measured_seconds": round(measured, 6),
        "attributed_fraction": (round(min(1.0, measured / wall), 4)
                                if wall > 0 else 0.0),
    }
    if wall > 0:
        out["lane_fractions"] = {
            k: round(v / wall, 4) for k, v in lanes.items()
        }
    return out


def _process_key(r: dict) -> tuple:
    """Track identity of a record: OS pid alone is NOT enough — an
    in-process LocalCluster runs the scheduler and every executor under
    one pid, and their records are separated by the ``role``/``exec``
    tags process identity / per-task window extraction stamped on."""
    return (r.get("pid", 0), r.get("role", ""), r.get("exec", ""))


def _process_tracks(records: List[dict]) -> Dict[tuple, tuple]:
    """process key -> (display pid, label). Display pids are synthetic
    small ints (scheduler first, then executors by id) so two identities
    sharing an OS pid still render as distinct Perfetto process
    tracks; the real pid stays in the label."""
    keys: List[tuple] = []
    for r in records:
        k = _process_key(r)
        if k not in keys:
            keys.append(k)

    def order(k):
        pid, role, ex = k
        rank = {"scheduler": 0, "executor": 1}.get(role, 2)
        return (rank, ex, pid)

    keys.sort(key=order)
    out: Dict[tuple, tuple] = {}
    for i, k in enumerate(keys):
        pid, role, ex = k
        if role == "scheduler":
            label = f"scheduler (pid {pid})"
        elif role == "executor":
            label = f"executor {ex or '?'} (pid {pid})"
        else:
            label = f"ballista pid {pid}"
        out[k] = (i + 1, label)
    return out


def _thread_names(records: List[dict], main_tid: int) -> Dict[tuple, str]:
    """(process key, tid) -> display name: ingest producer threads and
    executor task threads get their own labels (their spans are what
    makes the overlap visible)."""
    names: Dict[tuple, str] = {}
    producer_n: Dict[tuple, int] = {}
    task_n: Dict[tuple, int] = {}
    for r in records:
        pkey = _process_key(r)
        key = (pkey, r.get("tid", 0))
        if key in names:
            continue
        name = r.get("name", "")
        if name.startswith("ingest.") and r.get("tid") != main_tid:
            n = producer_n.get(pkey, 0)
            producer_n[pkey] = n + 1
            names[key] = f"ingest-producer-{n}"
        elif name == "executor.task":
            n = task_n.get(pkey, 0)
            task_n[pkey] = n + 1
            names[key] = f"task-worker-{n}"
    for r in records:
        key = (_process_key(r), r.get("tid", 0))
        if key not in names:
            names[key] = "main" if r.get("tid") == main_tid \
                else f"worker-{len(names)}"
    return names


_META_KEYS = ("name", "ts", "dur", "pid", "tid")


def _rel_us(ts: float, t0: float) -> float:
    return round((float(ts) - t0) * 1e6, 1)


def _flow_events(records: List[dict], tracks: Dict[tuple, tuple],
                 t0: float) -> List[dict]:
    """Chrome-trace flow arrows from each ``scheduler.task_dispatch``
    span into the matching ``executor.task`` span (paired on the task
    key). The start binds mid-dispatch and the finish binds just inside
    the task slice so both attach to real slices in Perfetto."""
    dispatches = {}
    for r in records:
        if r.get("name") == "scheduler.task_dispatch" and "dur" in r \
                and r.get("task"):
            dispatches[r["task"]] = r
    out: List[dict] = []
    n = 0
    for r in records:
        if r.get("name") != "executor.task" or "dur" not in r:
            continue
        d = dispatches.get(r.get("task"))
        if d is None:
            continue
        n += 1
        out.append({
            "ph": "s", "cat": "taskflow", "name": "task_dispatch",
            "id": n, "pid": tracks[_process_key(d)][0],
            "tid": d.get("tid", 0),
            "ts": _rel_us(float(d["ts"]) + float(d["dur"]) / 2, t0),
        })
        out.append({
            "ph": "f", "bp": "e", "cat": "taskflow",
            "name": "task_dispatch", "id": n,
            "pid": tracks[_process_key(r)][0], "tid": r.get("tid", 0),
            "ts": _rel_us(float(r["ts"]) + min(float(r["dur"]) / 2,
                                               1e-4), t0),
        })
    return out


_GANTT_PID = 0  # synthetic process; real tracks start at display pid 1


def _gantt_events(records: List[dict], t0: float) -> List[dict]:
    """Synthetic "job timeline" process: one thread per stage, one slice
    per executor task — the job's stage/task Gantt chart."""
    tasks = [r for r in records
             if r.get("name") == "executor.task" and "dur" in r]
    if not tasks:
        return []
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": _GANTT_PID, "tid": 0,
         "args": {"name": "job timeline (stage/task gantt)"}},
        {"ph": "M", "name": "process_sort_index", "pid": _GANTT_PID,
         "tid": 0, "args": {"sort_index": -1}},
    ]
    seen_stages = set()
    for r in tasks:
        try:
            stage = int(r.get("stage", 0))
        except (TypeError, ValueError):
            stage = 0
        if stage not in seen_stages:
            seen_stages.add(stage)
            events.append({"ph": "M", "name": "thread_name",
                           "pid": _GANTT_PID, "tid": stage,
                           "args": {"name": f"stage {stage}"}})
        events.append({
            "ph": "X", "cat": "gantt",
            "name": f"task {r.get('task', '?')}",
            "pid": _GANTT_PID, "tid": stage,
            "ts": _rel_us(r["ts"], t0),
            "dur": round(float(r["dur"]) * 1e6, 1),
            "args": {"executor": r.get("exec")
                     or r.get("executor", "")},
        })
    return events


def to_chrome_trace(session: dict, main_tid: Optional[int] = None) -> list:
    """Session records -> Chrome trace event array."""
    records = session.get("records") or []
    t0 = float(session.get("t0", 0.0))
    if main_tid is None:
        main_tid = threading.get_ident()
    tracks = _process_tracks(records)
    events: List[dict] = []
    for (pid, label) in tracks.values():
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": pid, "tid": 0,
                       "args": {"sort_index": pid}})
    for (pkey, tid), tname in _thread_names(records, main_tid).items():
        events.append({"ph": "M", "name": "thread_name",
                       "pid": tracks[pkey][0], "tid": tid,
                       "args": {"name": tname}})
    for r in records:
        args = {k: v for k, v in r.items() if k not in _META_KEYS}
        ev = {
            "name": r.get("name", "?"),
            "cat": str(r.get("name", "?")).split(".")[0],
            "pid": tracks[_process_key(r)][0],
            "tid": r.get("tid", 0),
            "ts": _rel_us(r.get("ts", t0), t0),
            "args": args,
        }
        if "dur" in r:
            ev["ph"] = "X"
            ev["dur"] = round(float(r["dur"]) * 1e6, 1)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    events.extend(_flow_events(records, tracks, t0))
    events.extend(_gantt_events(records, t0))
    events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
    return events


def build_artifact(session: dict) -> dict:
    """Merge a profiler session into the final artifact dict."""
    art = {
        "schema": session.get("schema", "ballista-profile-v1"),
        "label": session.get("label", "query"),
        "wall_seconds": session.get("wall_seconds", 0.0),
        "phases": session.get("phases", {}),
        "compile": session.get("compile", {}),
        "memory": session.get("memory", {}),
        "operators": session.get("operators"),
        "displayTimeUnit": "ms",
        "traceEvents": to_chrome_trace(session),
    }
    if session.get("distributed"):
        # merged cluster artifact: which processes contributed
        art["distributed"] = session["distributed"]
    if session.get("flight_recorder"):
        # retroactive dump: the records came from the ring, not a
        # profiled window — spans older than the ring bound are absent
        art["flight_recorder"] = True
    art.update(compute_lanes(session))
    art["otherData"] = {
        "label": art["label"],
        "wall_seconds": art["wall_seconds"],
        "attributed_fraction": art["attributed_fraction"],
    }
    return art


def write_artifact_file(art: dict, out_dir: Optional[str] = None,
                        out_path: Optional[str] = None) -> str:
    """Write an already-built artifact dict; returns its path.
    ``out_path`` pins the exact file, otherwise a timestamped name
    derived from the artifact label lands in ``out_dir`` (default:
    cwd). The single naming/IO path for every artifact writer —
    standalone profiler, scheduler merge, remote df.profile()."""
    if out_path is None:
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in str(art.get("label", "query")))[:48] \
            or "query"
        fname = f"ballista-profile-{safe}-{int(time.time() * 1000)}.json"
        out_path = os.path.join(out_dir or os.getcwd(), fname)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(art, fh, default=str)
    return out_path


def write_artifact(session: dict, out_dir: Optional[str] = None,
                   out_path: Optional[str] = None) -> str:
    """Build + write a profiler session's artifact; returns its path."""
    return write_artifact_file(build_artifact(session), out_dir=out_dir,
                               out_path=out_path)
