"""Per-query latency ledger: always-on phase attribution for every query.

Every query — standalone or cluster, traced or not — accumulates a
small fixed-schema dict of phase durations so "p99 regressed"
localizes to "queue wait" vs "compile" vs "device" without a trace
rerun. Unlike the profiler's lane decomposition (export.compute_lanes,
which needs a trace/profile session), the ledger is assembled from
cheap stamps and counters that are already maintained on the hot path:

- the client stamps its envelope phases (``host_decode``,
  ``result_transfer``) through the thread-local collect window;
- the scheduler stamps ``admission_wait`` / ``queue_wait`` /
  ``planning`` around the gate, the admission queue and the planner;
- executors ship per-task phase deltas back on ``CompletedTask`` as
  ``ledger.<phase>`` keys riding the existing ``TaskProfile.phases``
  dict (no proto change), summed at job-terminal time;
- the standalone recorder extracts the same phases from the
  flight-recorder window it already mines for lanes.

The assembled ledger feeds the process-global :class:`LedgerLog`
(``system.latency``) and the SLO histograms + exemplar store in
``observability/metrics.py`` (``ballista_latency_*`` families,
``system.exemplars``). ``BALLISTA_LEDGER=0`` disables recording (the
overhead gate's control arm); the stamps themselves are cheap enough
to stay unconditional.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional

# The fixed phase schema. Every ledger carries every phase (0.0 when a
# path doesn't exercise it) so downstream consumers never key-check.
LEDGER_PHASES = (
    "admission_wait",    # scheduler: time inside the admission gate
    "queue_wait",        # scheduler: time held in the admission queue
    "planning",          # logical->physical planning (+fusion)
    "compile",           # XLA trace/lower/compile attributed to the query
    "device_execute",    # task execution time not otherwise attributed
    "shuffle_fetch",     # shuffle partition fetches (data plane reads)
    "shuffle_write",     # partition/shuffle IPC writes
    "cache_lookup",      # table/result cache probes (hit or miss)
    "host_decode",       # result bytes -> host arrays -> DataFrame
    "result_transfer",   # client-side result partition fetches
)

# Span name -> ledger phase, for phases extracted from flight-recorder
# windows (per-task on executors, per-collect standalone). The
# lane-coverage analysis pass reads this map (plus export.LANE_SPANS)
# to catch span names no attribution surface knows about.
LEDGER_SPANS = {
    "shuffle.fetch": "shuffle_fetch",
    "dataplane.write": "shuffle_write",
    "cache.lookup": "cache_lookup",
}

_TRUTHY_OFF = ("0", "off", "false", "no")

_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def ledger_enabled() -> bool:
    """``BALLISTA_LEDGER`` (default on): record per-query ledgers into
    the process log + SLO histograms. Cached; reconfigure() re-reads
    (same pattern as metrics_enabled)."""
    global _enabled
    with _enabled_lock:
        if _enabled is None:
            _enabled = os.environ.get(
                "BALLISTA_LEDGER", "on").lower() not in _TRUTHY_OFF
        return _enabled


def reconfigure() -> None:
    global _enabled
    with _enabled_lock:
        _enabled = None


# -- thread-local collect window ----------------------------------------------
# The client paths stamp phases measured around code they own (planning,
# host decode, result transfer) into a per-thread dict bound for the
# duration of one collect. stamp() is a no-op outside a window, so
# library code can stamp unconditionally.

_tls = threading.local()


def begin_collect() -> None:
    _tls.stamps = {}


def take_collect() -> Dict[str, float]:
    """Detach and return this thread's stamp window ({} when none)."""
    stamps = getattr(_tls, "stamps", None)
    _tls.stamps = None
    return stamps or {}


def stamp(phase: str, seconds: float) -> None:
    stamps = getattr(_tls, "stamps", None)
    if stamps is not None:
        stamps[phase] = stamps.get(phase, 0.0) + float(seconds)


@contextmanager
def ledger_phase(phase: str):
    """Accumulate the block's wall time into the active collect window
    (no-op when no window is bound — a perf_counter pair either way)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        stamp(phase, time.perf_counter() - t0)


# -- assembly -----------------------------------------------------------------

def span_phase_sums(records: Iterable[dict]) -> Dict[str, float]:
    """Sum LEDGER_SPANS durations out of a flight-recorder window."""
    out: Dict[str, float] = {}
    for r in records:
        phase = LEDGER_SPANS.get(r.get("name"))
        if phase is not None:
            out[phase] = out.get(phase, 0.0) + float(r.get("dur", 0.0))
    return out


def task_phase_key(phase: str) -> str:
    """The ``TaskProfile.phases`` key a per-task ledger delta rides
    (``ledger.<phase>`` — plain phase totals keep their own names)."""
    return "ledger." + phase


def task_ledger_phases(records: Iterable[dict], wall_seconds: float,
                       compile_seconds: float = 0.0) -> Dict[str, float]:
    """Per-task ledger deltas an executor ships with CompletedTask:
    span-derived phases plus compile, with ``device_execute`` as the
    task's unattributed remainder (device + host compute)."""
    phases = span_phase_sums(records)
    if compile_seconds > 0:
        phases["compile"] = phases.get("compile", 0.0) + compile_seconds
    measured = sum(phases.values())
    phases["device_execute"] = max(0.0, float(wall_seconds) - measured)
    return {task_phase_key(k): round(v, 6) for k, v in phases.items()}


def merge_task_phases(payloads: Iterable[dict]) -> Dict[str, float]:
    """Sum the ``ledger.*`` deltas out of per-task profile payloads
    (one entry per completed task, any number of executors — summing is
    the merge: phases are disjoint slices of task wall time)."""
    out: Dict[str, float] = {}
    for p in payloads or ():
        for key, v in (p.get("phases") or {}).items():
            if key.startswith("ledger."):
                phase = key[len("ledger."):]
                try:
                    out[phase] = out.get(phase, 0.0) + float(v)
                except (TypeError, ValueError):
                    continue
    return out


def build_ledger(job_id: str, wall_seconds: float, origin: str,
                 status: str,
                 phases: Optional[Dict[str, float]] = None) -> dict:
    """Normalize to the fixed schema: every LEDGER_PHASES key present,
    unknown keys dropped, ``unattributed_seconds`` as the remainder so
    phases + unattributed always reconstruct the wall time."""
    full = {name: 0.0 for name in LEDGER_PHASES}
    for k, v in (phases or {}).items():
        if k in full:
            try:
                full[k] = round(max(float(v), 0.0), 6)
            except (TypeError, ValueError):
                continue
    wall = max(float(wall_seconds or 0.0), 0.0)
    return {
        "job_id": job_id,
        "origin": origin,
        "status": status,
        "wall_seconds": round(wall, 6),
        "phases": full,
        "unattributed_seconds": round(
            max(0.0, wall - sum(full.values())), 6),
    }


def assemble_job_ledger(job_id: str, wall_seconds: float, status: str,
                        stamps: Optional[Dict[str, float]] = None,
                        task_payloads: Optional[List[dict]] = None,
                        origin: str = "cluster") -> dict:
    """The scheduler's job-terminal assembly: its own stamps
    (admission/queue/planning) + the summed per-task deltas."""
    phases = dict(stamps or {})
    for phase, v in merge_task_phases(task_payloads).items():
        phases[phase] = phases.get(phase, 0.0) + v
    return build_ledger(job_id, wall_seconds, origin, status, phases)


# -- the process log (system.latency) -----------------------------------------

def _log_capacity() -> int:
    try:
        return max(int(os.environ.get("BALLISTA_LEDGER_LOG", "256")), 1)
    except ValueError:
        return 256


class LedgerLog:
    """Bounded ring of recent query ledgers, per process."""

    def __init__(self, capacity: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(
            maxlen=capacity if capacity is not None else _log_capacity())

    def record(self, ledger: dict) -> None:
        entry = dict(ledger)
        entry.setdefault("recorded_at", time.time())
        with self._lock:
            self._ring.append(entry)

    def entries(self, since: Optional[float] = None) -> List[dict]:
        with self._lock:
            snap = list(self._ring)
        if since is not None:
            snap = [e for e in snap
                    if float(e.get("recorded_at", 0.0)) >= since]
        return snap

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def rows(self) -> List[dict]:
        """``system.latency``: one row per recent query per phase
        (plus the ``unattributed`` remainder row), oldest query first."""
        out: List[dict] = []
        for e in self.entries():
            wall = float(e.get("wall_seconds", 0.0))
            phases = dict(e.get("phases") or {})
            phases["unattributed"] = float(
                e.get("unattributed_seconds", 0.0))
            for phase in (*LEDGER_PHASES, "unattributed"):
                secs = float(phases.get(phase, 0.0))
                out.append({
                    "job_id": e.get("job_id"),
                    "origin": e.get("origin"),
                    "status": e.get("status"),
                    "phase": phase,
                    "seconds": round(secs, 6),
                    "fraction": round(secs / wall, 6) if wall > 0 else 0.0,
                    "wall_seconds": round(wall, 6),
                })
        return out


_log_lock = threading.Lock()
_process_log: Optional[LedgerLog] = None


def process_ledger_log() -> LedgerLog:
    global _process_log
    with _log_lock:
        if _process_log is None:
            _process_log = LedgerLog()
        return _process_log


def reset_process_log() -> None:
    """Test hook: drop the process log (capacity re-read from env)."""
    global _process_log
    with _log_lock:
        _process_log = None


def latency_rows() -> List[dict]:
    return process_ledger_log().rows()


def record_ledger(ledger: dict) -> None:
    """Record one assembled ledger: process log + SLO histograms with
    exemplars. The single gate the overhead knob controls."""
    if not ledger_enabled():
        return
    process_ledger_log().record(ledger)
    try:
        from .metrics import observe_query_ledger

        observe_query_ledger(ledger)
    except Exception:  # noqa: BLE001 - observability only
        pass
