"""Observability: operator metrics, EXPLAIN ANALYZE plumbing, tracing.

The measurement substrate the reference engine never grew (its
PartitionStats proto is declared but unpopulated, and DataFusion-side
operator metrics never cross the Ballista wire): every PhysicalPlan
carries a lock-cheap ``MetricsSet``; executors ship per-task metrics back
with task completion; the scheduler aggregates them per stage; and a
span-style tracer (``BALLISTA_TRACE=1``) writes JSON-lines trace files
covering scheduler events, task dispatch, shuffle fetch, and dataplane
I/O.
"""

from .metrics import (  # noqa: F401
    MetricsSet,
    QueryMetrics,
    collect_plan_metrics,
    force_metrics,
    instrument_execute,
    merge_operator_metrics,
    metrics_enabled,
    snapshot_plan_metrics,
)
from .tracing import trace_enabled, trace_event, trace_span  # noqa: F401
