"""Observability: operator metrics, EXPLAIN ANALYZE plumbing, tracing,
query profiler artifacts, memory accounting and the live health plane.

The measurement substrate the reference engine never grew (its
PartitionStats proto is declared but unpopulated, and DataFusion-side
operator metrics never cross the Ballista wire): every PhysicalPlan
carries a lock-cheap ``MetricsSet``; executors ship per-task metrics back
with task completion; the scheduler aggregates them per stage; a
span-style tracer (``BALLISTA_TRACE=1``) writes JSON-lines trace files
with structural span/parent ids and flow correlation; the profiler
(``df.profile()`` / ``BALLISTA_PROFILE=<dir>``) merges spans, ingest
phases, compile attribution and operator metrics into one
Chrome-trace/Perfetto artifact per query; ``memory.py`` tracks host
bytes by category plus device bytes; and ``health.py`` serves
``/healthz`` + Prometheus ``/metrics`` + ``/debug/queries`` on the
scheduler and every executor.
"""

from .metrics import (  # noqa: F401
    MetricsSet,
    QueryMetrics,
    collect_plan_metrics,
    force_metrics,
    instrument_execute,
    merge_operator_metrics,
    metrics_enabled,
    snapshot_plan_metrics,
)
from .tracing import (  # noqa: F401
    current_flow,
    flight_recorder_enabled,
    flow,
    ring_records,
    set_process_identity,
    trace_enabled,
    trace_event,
    trace_span,
)
from .health import (  # noqa: F401
    HealthServer,
    QueryLog,
    maybe_start_health_server,
    metrics_port_from_env,
    render_prometheus,
)
from .profiler import Profiler, profile_call, profile_dir  # noqa: F401
from .systables import (  # noqa: F401
    SYSTEM_TABLES,
    SystemSnapshot,
    SystemTableSource,
    build_query_record,
    is_system_table,
    record_query,
)
