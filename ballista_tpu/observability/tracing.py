"""Span-style tracing: ``BALLISTA_TRACE=1`` -> JSON-lines trace file per
process.

Coverage (each site tags its span name with the subsystem): scheduler
events (``scheduler.plan_job``, ``scheduler.task_dispatch``), executor
task execution (``executor.task``), shuffle fetch (``shuffle.fetch``),
dataplane I/O (``dataplane.write``), ingest phases (``ingest.*``),
compile activity (``compile.jit``), host dictionary work
(``host.dictionary``) and blocking device syncs (``device.block``).
A span line is::

    {"name": ..., "ts": <epoch start>, "dur": <seconds>, "pid": ...,
     "tid": ..., "sid": <span id>, "psid": <parent span id>, <attrs>}

Instant events carry no ``dur``/``sid`` (only the enclosing ``psid``).
``sid``/``psid`` are process-local monotonic ids kept on a per-thread
span stack, so the profiler (``observability/profiler.py``) can rebuild
the call tree instead of guessing from timestamps. Cross-process /
cross-thread flow correlation is STRUCTURAL: :func:`flow` binds
``job``/``stage``/``task`` attributes on the current thread, every
record emitted under it inherits them (explicit span attrs win), and
:func:`current_flow` lets pool handoffs (ingest producers) re-bind the
creator's flow on the worker thread.

Files land in ``BALLISTA_TRACE_DIR`` (default: the system temp dir) as
``ballista-trace-<pid>.jsonl`` so a multi-process cluster writes one
file per scheduler/executor process with no cross-process locking;
``BALLISTA_TRACE_FILE`` pins an exact path instead. Hygiene knobs:
``BALLISTA_TRACE_TRUNCATE=1`` opens the file fresh instead of appending
(long benchmark loops otherwise grow one file forever), and
``BALLISTA_TRACE_MAX_MB=<n>`` caps the file — once the cap is reached a
single ``trace.capped`` marker is written and further records are
dropped (never raising into the traced code). Writes are line-buffered
under a process-local lock — tracing is for diagnosis runs, not the
steady-state hot path, and the disabled path is a single cached boolean
check.

**Flight recorder**: independent of the trace FILE, every span/event
record is also appended to a bounded in-memory ring (a deque of the
most recent ``BALLISTA_FLIGHT_RECORDER_SPANS`` records, default 4096;
``BALLISTA_FLIGHT_RECORDER=0`` disables). The ring is always on by
default — it is what lets a query that crosses
``BALLISTA_SLOW_QUERY_SECS`` dump a RETROACTIVE profile artifact, and
what executors mine for the per-task profile windows shipped back with
``CompletedTask`` (observability/distributed.py). Ring appends build
the same record dict a file write would but skip the JSON encode and
the lock, so the measured warm-query overhead stays under the 5% gate.

**Process identity**: :func:`set_process_identity` stamps a role
(``scheduler`` / ``executor``) and short executor id onto every record
emitted by this process (``role`` / ``exec`` keys), so a merged
multi-process artifact can place each record on the right process
track. First writer wins — an in-process LocalCluster (scheduler and
executors sharing one tracer) relies on per-task window extraction to
re-tag executor records instead.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Optional

_lock = threading.Lock()
_state: dict = {"configured": False, "fh": None, "ring": None}
_span_ids = itertools.count(1)
_tls = threading.local()
# (role, short executor id) — set once per process; survives
# reconfigure() (identity is who the process IS, not how it traces)
_identity: dict = {}


def _configure_locked() -> None:
    # "configured" must be published LAST: _fh() double-checks it
    # WITHOUT the lock, so flipping it before the file handle exists
    # opens a window where a concurrent thread (ingest pipeline
    # producers trace from pool workers) reads fh=None and silently
    # drops its event
    prev_ring = _state.pop("prev_ring", None)
    if os.environ.get("BALLISTA_FLIGHT_RECORDER", "").lower() in (
            "0", "off", "false"):
        _state["ring"] = None
    else:
        try:
            cap = int(os.environ.get("BALLISTA_FLIGHT_RECORDER_SPANS",
                                     "4096"))
        except ValueError:
            cap = 4096
        ring = deque(maxlen=max(cap, 16)) if cap > 0 else None
        if ring is not None and prev_ring:
            # the flight recorder survives trace-FILE reconfiguration
            # (the profiler reconfigures at window start/stop; losing
            # the ring there would blind the retroactive dump)
            ring.extend(prev_ring)
        _state["ring"] = ring
    if os.environ.get("BALLISTA_TRACE", "").lower() not in ("1", "on",
                                                            "true"):
        _state["fh"] = None
        _state["configured"] = True
        return
    path = os.environ.get("BALLISTA_TRACE_FILE")
    if not path:
        trace_dir = os.environ.get("BALLISTA_TRACE_DIR",
                                   tempfile.gettempdir())
        path = os.path.join(trace_dir, f"ballista-trace-{os.getpid()}.jsonl")
    truncate = os.environ.get("BALLISTA_TRACE_TRUNCATE", "").lower() in (
        "1", "on", "true")
    try:
        _state["max_bytes"] = int(
            float(os.environ.get("BALLISTA_TRACE_MAX_MB", "0")) * 1e6)
    except ValueError:
        _state["max_bytes"] = 0
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        mode = "w" if truncate else "a"
        # the size cap covers the WHOLE file, appended history included
        _state["bytes"] = (os.path.getsize(path)
                           if not truncate and os.path.exists(path) else 0)
        _state["capped"] = False
        _state["fh"] = open(path, mode, buffering=1)
        _state["path"] = path
    except OSError:
        _state["fh"] = None
    _state["configured"] = True


def _fh():
    if not _state["configured"]:
        with _lock:
            if not _state["configured"]:
                _configure_locked()
    return _state["fh"]


def _ring():
    if not _state["configured"]:
        with _lock:
            if not _state["configured"]:
                _configure_locked()
    return _state["ring"]


def _recording() -> bool:
    """True when spans must be materialized at all: a trace file is
    open OR the flight-recorder ring is on."""
    if not _state["configured"]:
        with _lock:
            if not _state["configured"]:
                _configure_locked()
    return _state["fh"] is not None or _state["ring"] is not None


def trace_enabled() -> bool:
    return _fh() is not None


def flight_recorder_enabled() -> bool:
    return _ring() is not None


def trace_path() -> Optional[str]:
    return _state.get("path") if _fh() is not None else None


def reconfigure() -> None:
    """Re-read the BALLISTA_TRACE* env (tests flip it mid-process; a
    forked executor inherits env and configures itself on first use)."""
    with _lock:
        fh = _state.get("fh")
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        ring = _state.get("ring")
        _state.clear()
        _state.update({"configured": False, "fh": None, "ring": None,
                       "prev_ring": ring})


def set_process_identity(role: str, executor_id: Optional[str] = None
                         ) -> None:
    """Stamp this process's role (and short executor id) onto every
    record emitted from now on. First writer wins: in an in-process
    LocalCluster the scheduler and executors share one tracer, and
    executor records are re-tagged at per-task window extraction
    instead (observability/distributed.py)."""
    with _lock:
        # under the lock, "first writer wins" is exact: two concurrent
        # claimants (executor start racing a scheduler start in one
        # LocalCluster process) can no longer interleave role/exec
        if _identity:
            return
        _identity["role"] = role
        if executor_id:
            _identity["exec"] = executor_id[:8]


def process_identity() -> dict:
    return dict(_identity)


def ring_records(since: Optional[float] = None,
                 job: Optional[str] = None,
                 task: Optional[str] = None) -> list:
    """Snapshot of flight-recorder records, optionally filtered to those
    OVERLAPPING ``since`` (a span started before but still running past
    it counts) and/or carrying the given ``job``/``task`` flow attrs.
    Returns the ring's record dicts — callers must copy before
    mutating."""
    ring = _ring()
    if ring is None:
        return []
    snap = list(ring)
    if since is not None:
        # records append at emit time — span END order (spans emit at
        # __exit__ with end == ts + dur == now; events have dur 0) — so
        # the ring is end-time ordered: walk from the RIGHT and stop at
        # the first record ending before the window. Extraction cost is
        # bounded by the WINDOW size, not the ring size (per-task and
        # slow-query windows are tiny against a 4096-record ring).
        cut = since - 1e-6
        lo = len(snap)
        while lo > 0:
            r = snap[lo - 1]
            if float(r.get("ts", 0.0)) + float(r.get("dur", 0.0)) < cut:
                break
            lo -= 1
        snap = snap[lo:]
    if job is None and task is None:
        return snap
    out = []
    for r in snap:
        if job is not None and r.get("job") != job:
            continue
        if task is not None and r.get("task") != task:
            continue
        out.append(r)
    return out


# -- flow correlation ---------------------------------------------------------


def current_flow() -> dict:
    """The flow attributes bound on this thread (``{}`` when none).
    Pool handoffs capture this at submit time and re-bind it on the
    worker via :func:`flow` so producer spans stay correlated with the
    query/task that spawned them."""
    return dict(getattr(_tls, "flow", None) or {})


@contextmanager
def flow(**attrs):
    """Bind flow-correlation attributes (``job=...``, ``stage=...``,
    ``task=...``) on the current thread: every span/event emitted inside
    inherits them. Nested flows layer (inner keys win)."""
    prev = getattr(_tls, "flow", None)
    merged = dict(prev or {})
    merged.update({k: v for k, v in attrs.items() if v is not None})
    _tls.flow = merged
    try:
        yield
    finally:
        _tls.flow = prev


def _span_stack() -> list:
    st = getattr(_tls, "spans", None)
    if st is None:
        st = _tls.spans = []
    return st


def _emit(record: dict) -> None:
    ring = _ring()
    if ring is not None:
        # deque.append is atomic under the GIL; no lock, no JSON encode
        ring.append(record)
    fh = _fh()
    if fh is None:
        return
    line = json.dumps(record, default=str)
    with _lock:
        if _state.get("capped"):
            return
        cap = _state.get("max_bytes") or 0
        if cap and _state.get("bytes", 0) + len(line) + 1 > cap:
            _state["capped"] = True
            marker = json.dumps({"name": "trace.capped",
                                 "ts": time.time(), "pid": os.getpid(),
                                 "max_mb": cap / 1e6})
            try:
                fh.write(marker + "\n")
            except (OSError, ValueError):
                pass
            return
        try:
            fh.write(line + "\n")
            _state["bytes"] = _state.get("bytes", 0) + len(line) + 1
        except (OSError, ValueError):  # closed/full: drop, never raise
            pass


def _base_record(name: str, attrs: dict) -> dict:
    rec = {"name": name, "ts": time.time(),
           "pid": os.getpid(), "tid": threading.get_ident()}
    if _identity:
        rec.update(_identity)
    fl = getattr(_tls, "flow", None)
    if fl:
        rec.update(fl)
    rec.update(attrs)
    return rec


def trace_event(name: str, **attrs) -> None:
    """Instant event (no duration). Carries the enclosing span's id as
    ``psid`` so it nests in the reconstructed tree."""
    if not _recording():
        return
    rec = _base_record(name, attrs)
    st = _span_stack()
    if st:
        rec["psid"] = st[-1]
    _emit(rec)


class trace_span:
    """``with trace_span("executor.task", task=key): ...`` — records one
    line with the span's start time and duration (exceptions are noted
    as ``error=<ExcType>`` and re-raised). Each span gets a process-
    local ``sid`` and its enclosing span's ``psid``."""

    __slots__ = ("name", "attrs", "_t0", "_sid", "_psid")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        if not _recording():
            self._t0 = None
            return self
        self._t0 = time.time()
        st = _span_stack()
        self._psid = st[-1] if st else None
        self._sid = next(_span_ids)
        st.append(self._sid)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is not None:
            st = _span_stack()
            if st and st[-1] == self._sid:
                st.pop()
            rec = _base_record(self.name, self.attrs)
            rec["ts"] = self._t0
            rec["dur"] = time.time() - self._t0
            rec["sid"] = self._sid
            if self._psid is not None:
                rec["psid"] = self._psid
            if exc_type is not None:
                rec["error"] = exc_type.__name__
            _emit(rec)
        return False
