"""Span-style tracing: ``BALLISTA_TRACE=1`` -> JSON-lines trace file per
process.

Coverage (each site tags its span name with the subsystem): scheduler
events (``scheduler.plan_job``, ``scheduler.task_dispatch``), executor
task execution (``executor.task``), shuffle fetch (``shuffle.fetch``),
and dataplane I/O (``dataplane.write``). A span line is::

    {"name": ..., "ts": <epoch start>, "dur": <seconds>, "pid": ...,
     "tid": ..., <attrs>}

Instant events carry no ``dur``. Files land in ``BALLISTA_TRACE_DIR``
(default: the system temp dir) as ``ballista-trace-<pid>.jsonl`` so a
multi-process cluster writes one file per scheduler/executor process
with no cross-process locking; ``BALLISTA_TRACE_FILE`` pins an exact
path instead. Writes are line-buffered under a process-local lock —
tracing is for diagnosis runs, not the steady-state hot path, and the
disabled path is a single cached boolean check.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

_lock = threading.Lock()
_state: dict = {"configured": False, "fh": None}


def _configure_locked() -> None:
    # "configured" must be published LAST: _fh() double-checks it
    # WITHOUT the lock, so flipping it before the file handle exists
    # opens a window where a concurrent thread (ingest pipeline
    # producers trace from pool workers) reads fh=None and silently
    # drops its event
    if os.environ.get("BALLISTA_TRACE", "").lower() not in ("1", "on",
                                                            "true"):
        _state["fh"] = None
        _state["configured"] = True
        return
    path = os.environ.get("BALLISTA_TRACE_FILE")
    if not path:
        trace_dir = os.environ.get("BALLISTA_TRACE_DIR",
                                   tempfile.gettempdir())
        path = os.path.join(trace_dir, f"ballista-trace-{os.getpid()}.jsonl")
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        _state["fh"] = open(path, "a", buffering=1)
        _state["path"] = path
    except OSError:
        _state["fh"] = None
    _state["configured"] = True


def _fh():
    if not _state["configured"]:
        with _lock:
            if not _state["configured"]:
                _configure_locked()
    return _state["fh"]


def trace_enabled() -> bool:
    return _fh() is not None


def trace_path() -> Optional[str]:
    return _state.get("path") if _fh() is not None else None


def reconfigure() -> None:
    """Re-read the BALLISTA_TRACE* env (tests flip it mid-process; a
    forked executor inherits env and configures itself on first use)."""
    with _lock:
        fh = _state.get("fh")
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass
        _state.clear()
        _state.update({"configured": False, "fh": None})


def _emit(record: dict) -> None:
    fh = _fh()
    if fh is None:
        return
    line = json.dumps(record, default=str)
    with _lock:
        try:
            fh.write(line + "\n")
        except (OSError, ValueError):  # closed/full: drop, never raise
            pass


def trace_event(name: str, **attrs) -> None:
    """Instant event (no duration)."""
    if _fh() is None:
        return
    _emit({"name": name, "ts": time.time(),
           "pid": os.getpid(), "tid": threading.get_ident(), **attrs})


class trace_span:
    """``with trace_span("executor.task", task=key): ...`` — records one
    line with the span's start time and duration (exceptions are noted
    as ``error=<ExcType>`` and re-raised)."""

    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._t0 = time.time() if _fh() is not None else None
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is not None:
            rec = {"name": self.name, "ts": self._t0,
                   "dur": time.time() - self._t0,
                   "pid": os.getpid(), "tid": threading.get_ident(),
                   **self.attrs}
            if exc_type is not None:
                rec["error"] = exc_type.__name__
            _emit(rec)
        return False
