"""Live cluster health plane: ``/healthz``, ``/metrics``,
``/debug/queries``.

A lightweight stdlib HTTP server every long-running process (scheduler,
executor) can start next to its RPC port — no new dependencies, daemon
threads only, one instance per process role:

- ``GET /healthz`` — liveness: ``200 {"status": "ok", ...}`` with role,
  pid, uptime. Cluster tests poll this instead of sleeping.
- ``GET /metrics`` — Prometheus text exposition. Families come from the
  process's registered sample callbacks; names MUST exist in
  ``registry.PROCESS_METRICS`` (the renderer drops unknown names — the
  registry is the contract ``dev/check_metric_names.py`` lints).
- ``GET /debug/queries`` — JSON ring buffer of recent query summaries
  plus the slow-query subset (``BALLISTA_SLOW_QUERY_SECS``) and, when
  a live provider is wired, IN-FLIGHT queries (status "running").
- ``GET /debug/jobs[/<job_id>]`` — live job progress snapshots
  (scheduler only; the progress plane's HTTP face — per-stage
  completion fractions, rate-based ETA, task counts).
- ``GET /debug/profile/<job_id>`` — the job's merged Chrome-trace
  profile artifact (scheduler only; served from the distributed
  profiler's collector, built on demand from the flight recorder when
  no ambient/slow-query build happened).

Servers bind ``127.0.0.1`` by default (diagnosis plane, not a public
API); ``port=0`` picks an ephemeral port (read ``server.port``)."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from .registry import (HISTOGRAM_BUCKETS, PROCESS_METRICS,
                       histogram_snapshot)

log = logging.getLogger("ballista.health")

# sample: (family name, labels dict, numeric value)
Sample = Tuple[str, Dict[str, str], float]


def slow_query_secs() -> Optional[float]:
    """BALLISTA_SLOW_QUERY_SECS threshold, or None when unset/invalid."""
    v = os.environ.get("BALLISTA_SLOW_QUERY_SECS", "")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def slow_query_kill_secs() -> Optional[float]:
    """``BALLISTA_SLOW_QUERY_KILL_SECS``: upgrade the slow-query LOG to
    a KILL — the scheduler's reap pass cancels cluster jobs running
    longer than this, and standalone collects arm a watchdog that fires
    the query's cancel token. None when unset/invalid."""
    v = os.environ.get("BALLISTA_SLOW_QUERY_KILL_SECS", "")
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


class QueryLog:
    """Bounded ring of recent query summaries + the slow subset.

    ``record`` takes a summary dict (job_id/label, wall_seconds,
    state, ...); entries over the slow threshold are ALSO kept in a
    separate ring so a burst of fast queries can't evict the slow one
    being investigated. Thread-safe, lock-cheap."""

    def __init__(self, capacity: int = 128):
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=capacity)
        self._slow: deque = deque(maxlen=capacity)
        self.slow_total = 0
        # live progress plane: optional provider of IN-FLIGHT query
        # records (status "running", live wall seconds) appended to
        # every snapshot — they vanish/are overwritten the moment the
        # terminal record lands in the ring
        self.live_fn = None

    def record(self, summary: dict) -> None:
        entry = dict(summary)
        entry.setdefault("recorded_at", time.time())
        thr = slow_query_secs()
        is_slow = (thr is not None
                   and float(entry.get("wall_seconds", 0.0)) >= thr)
        with self._lock:
            self._recent.append(entry)
            if is_slow:
                self._slow.append(entry)
                self.slow_total += 1
        if is_slow:
            log.warning("slow query (>= %.3fs): %s", thr,
                        json.dumps(entry, default=str))

    def annotate(self, job_id: str, **fields) -> None:
        """Attach fields to already-recorded entries of a job —
        ``record`` copies its input, so late-arriving facts (the
        deferred profile-artifact path) land through here."""
        with self._lock:
            for ring in (self._recent, self._slow):
                for e in ring:
                    if e.get("job_id") == job_id:
                        e.update(fields)

    def snapshot(self) -> dict:
        live: List[dict] = []
        if self.live_fn is not None:
            try:
                live = list(self.live_fn())
            except Exception:  # noqa: BLE001 - advisory rows only
                live = []
        with self._lock:
            return {
                "queries": list(self._recent) + live,
                "slow_queries": list(self._slow),
                "slow_query_secs": slow_query_secs(),
                "slow_total": self.slow_total,
            }


def render_prometheus(samples: List[Sample]) -> str:
    """Prometheus text exposition (v0.0.4). Families are grouped, HELP/
    TYPE come from the registry; samples whose family the registry
    doesn't know are dropped (loudly, once per name)."""
    by_family: Dict[str, List[Sample]] = {}
    for name, labels, value in samples:
        if name not in PROCESS_METRICS:
            log.warning("dropping unregistered metric family %r "
                        "(add it to observability/registry.py)", name)
            continue
        by_family.setdefault(name, []).append((name, labels, value))
    lines: List[str] = []
    for name in sorted(by_family):
        kind, help_text = PROCESS_METRICS[name]
        ptype = "counter" if kind == "counter" else "gauge"
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {ptype}")
        for _, labels, value in by_family[name]:
            label_s = _label_str(labels)
            if float(value) == int(value):
                vs = str(int(value))
            else:
                vs = repr(float(value))
            lines.append(f"{name}{label_s} {vs}")
    return "\n".join(lines) + "\n"


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", "\\\\")
                         .replace('"', '\\"'))
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_histograms() -> str:
    """Prometheus text for every registered histogram family with
    observations (``registry.observe_histogram``): cumulative
    ``_bucket{le=...}`` series plus ``_sum`` / ``_count``."""
    lines: List[str] = []
    for family, rows in sorted(histogram_snapshot().items()):
        if PROCESS_METRICS.get(family, (None,))[0] != "histogram":
            continue  # registry is the gate, here too
        help_text = PROCESS_METRICS[family][1]
        lines.append(f"# HELP {family} {help_text}")
        lines.append(f"# TYPE {family} histogram")
        for labels, counts, total, n in rows:
            for le, c in zip(HISTOGRAM_BUCKETS, counts):
                ls = _label_str({**labels, "le": f"{le:g}"})
                lines.append(f"{family}_bucket{ls} {c}")
            ls = _label_str({**labels, "le": "+Inf"})
            lines.append(f"{family}_bucket{ls} {n}")
            ls = _label_str(labels)
            lines.append(f"{family}_sum{ls} {round(total, 6)}")
            lines.append(f"{family}_count{ls} {n}")
    return "\n".join(lines) + "\n" if lines else ""


def base_process_samples() -> List[Sample]:
    """Samples every role exports: RSS, tracked host bytes (+ per
    category), device bytes."""
    from . import memory as obs_memory

    from ..distributed import spill as _spill

    snap = obs_memory.memory_snapshot()
    gov = _spill.governor().stats()
    out: List[Sample] = [
        ("ballista_rss_bytes", {}, snap["rss_bytes"]),
        ("ballista_host_tracked_bytes", {}, snap["current_bytes"]),
        ("ballista_host_tracked_peak_bytes", {}, snap["peak_bytes"]),
        ("ballista_device_bytes", {}, snap["device_bytes"]),
        ("ballista_device_peak_bytes", {}, snap["peak_device_bytes"]),
        ("ballista_shuffle_inflight_bytes", {}, gov["inflight_bytes"]),
        ("ballista_spill_bytes_total", {}, gov["spilled_bytes_total"]),
    ]
    for cat, n in sorted(snap["by_category"].items()):
        out.append(("ballista_host_category_bytes", {"category": cat}, n))
    from ..cache import cache_counters

    cc = cache_counters()
    out.extend([
        ("ballista_cache_table_hits_total", {}, cc["table_cache_hits"]),
        ("ballista_cache_table_misses_total", {},
         cc["table_cache_misses"]),
        ("ballista_cache_table_fills_total", {}, cc["table_cache_fills"]),
        ("ballista_cache_table_evictions_total", {},
         cc["table_cache_evictions"]),
        ("ballista_cache_table_resident_bytes", {},
         cc["table_cache_resident_bytes"]),
        ("ballista_cache_result_hits_total", {}, cc["result_cache_hits"]),
        ("ballista_cache_result_misses_total", {},
         cc["result_cache_misses"]),
        ("ballista_cache_result_bytes", {}, cc["result_cache_bytes"]),
        ("ballista_cache_donated_buffers_total", {},
         cc["donated_buffers"]),
        ("ballista_cache_donated_bytes_total", {}, cc["donated_bytes"]),
    ])
    return out


class HealthServer:
    """The per-process health plane. ``samples_fn`` returns the role's
    metric samples (base process samples are appended automatically);
    ``query_log`` feeds ``/debug/queries``."""

    def __init__(self, role: str, port: int = 0,
                 samples_fn: Optional[Callable[[], List[Sample]]] = None,
                 query_log: Optional[QueryLog] = None,
                 host: str = "127.0.0.1",
                 profile_fn: Optional[Callable[[str],
                                              Optional[dict]]] = None,
                 jobs_fn: Optional[Callable[[Optional[str]],
                                            object]] = None):
        self.role = role
        self.query_log = query_log or QueryLog()
        self._samples_fn = samples_fn
        # profile_fn(job_id) -> merged profile artifact dict (or None):
        # serves /debug/profile/<job_id> on the scheduler
        self._profile_fn = profile_fn
        # jobs_fn(None) -> live job progress snapshots, jobs_fn(id) ->
        # one snapshot or None: serves /debug/jobs[/<job_id>] (live
        # progress plane, scheduler only)
        self._jobs_fn = jobs_fn
        self._started_at = time.time()
        plane = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silent: no stdout spam
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/healthz":
                        body = json.dumps(plane.healthz()).encode()
                        self._send(200, body, "application/json")
                    elif path == "/metrics":
                        body = plane.metrics_text().encode()
                        self._send(200, body,
                                   "text/plain; version=0.0.4")
                    elif path == "/debug/queries":
                        body = json.dumps(plane.query_log.snapshot(),
                                          default=str).encode()
                        self._send(200, body, "application/json")
                    elif path == "/debug/jobs" and \
                            plane._jobs_fn is not None:
                        body = json.dumps(
                            {"jobs": plane._jobs_fn(None)},
                            default=str).encode()
                        self._send(200, body, "application/json")
                    elif path.startswith("/debug/jobs/") and \
                            plane._jobs_fn is not None:
                        jid = path[len("/debug/jobs/"):]
                        # empty id ("/debug/jobs/") must 404, not leak
                        # the whole-list shape through the falsy branch
                        snap = plane._jobs_fn(jid) if jid else None
                        if snap is None:
                            self._send(404, b"unknown job", "text/plain")
                        else:
                            body = json.dumps(snap, default=str).encode()
                            self._send(200, body, "application/json")
                    elif path.startswith("/debug/profile/") and \
                            plane._profile_fn is not None:
                        job_id = path[len("/debug/profile/"):]
                        art = plane._profile_fn(job_id)
                        if art is None:
                            self._send(404, b"no profile for that job",
                                       "text/plain")
                        else:
                            body = json.dumps(art, default=str).encode()
                            self._send(200, body, "application/json")
                    else:
                        self._send(404, b"not found", "text/plain")
                except Exception:  # noqa: BLE001 - never kill the plane
                    try:
                        self._send(500, b"internal error", "text/plain")
                    except Exception:  # noqa: BLE001 - peer went away
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"health-{role}-{self.port}",
        )
        self._thread.start()

    def healthz(self) -> dict:
        return {
            "status": "ok",
            "role": self.role,
            "pid": os.getpid(),
            "uptime_seconds": round(time.time() - self._started_at, 3),
        }

    def metrics_text(self) -> str:
        samples: List[Sample] = [
            ("ballista_up", {}, 1),
            ("ballista_uptime_seconds", {},
             time.time() - self._started_at),
        ]
        if self._samples_fn is not None:
            try:
                samples.extend(self._samples_fn())
            except Exception:  # noqa: BLE001 - plane must stay up
                log.exception("metrics sample callback failed")
        samples.extend(base_process_samples())
        return render_prometheus(samples) + render_histograms()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 - already down
            pass


def metrics_port_from_env(default: int = -1) -> int:
    """BALLISTA_METRICS_PORT: -1 = off, 0 = ephemeral, else fixed."""
    try:
        return int(os.environ.get("BALLISTA_METRICS_PORT", str(default)))
    except ValueError:
        return default


def maybe_start_health_server(role: str, port: Optional[int],
                              samples_fn=None, query_log=None,
                              profile_fn=None, jobs_fn=None
                              ) -> Optional[HealthServer]:
    """Start a health server unless disabled (``port`` None/negative)."""
    if port is None or port < 0:
        return None
    try:
        return HealthServer(role, port, samples_fn=samples_fn,
                            query_log=query_log, profile_fn=profile_fn,
                            jobs_fn=jobs_fn)
    except OSError as e:
        log.warning("health plane for %s failed to bind port %s: %s",
                    role, port, e)
        return None
