"""Distributed query profiler: merged per-job artifacts + flight
recorder plumbing.

Three pieces, all built on the flight-recorder ring in ``tracing.py``:

- **Executor side** — :func:`capture_task_profile` mines the ring for
  the spans a just-completed task emitted (matched STRUCTURALLY by the
  task's flow attribute, so concurrent tasks in one process never
  cross-attribute), tags them with the executor's identity, bounds them
  (record count + serialized bytes), and packages them with the ingest
  phase / compile-governor deltas and a memory snapshot. The executor
  ships the package back inside ``CompletedTask.profile`` (proto
  ``TaskProfile``; records travel as one JSON blob because span attrs
  are free-form).

- **Scheduler side** — :class:`JobProfileCollector` keeps a bounded
  per-job collection of those task payloads, and :func:`merged_session`
  joins them with the scheduler's own ring window (``plan_job`` /
  ``task_dispatch`` spans, matched by the ``job`` flow attr) into ONE
  profiler session: per-process identity preserved, duplicates dropped
  by (pid, sid) — an in-process LocalCluster shares one ring, so the
  scheduler's window would otherwise re-contain every executor span —
  and ``export.build_artifact`` renders it with per-process tracks,
  task flow arrows, the stage/task Gantt lane, and cluster-aggregated
  named wall-time lanes.

- **Retroactive slow-query dump** — :func:`watch_slow_query` wraps a
  standalone collect with near-zero cost (two snapshot dict copies when
  ``BALLISTA_SLOW_QUERY_SECS`` is set, nothing otherwise); a query that
  crosses the threshold dumps a merged artifact AFTER the fact from the
  ring — no re-run with profiling enabled needed. Artifacts land in
  ``BALLISTA_SLOW_QUERY_DIR`` (default: ``BALLISTA_PROFILE`` dir, else
  the system temp dir).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from . import memory as obs_memory
from . import tracing

log = logging.getLogger("ballista.profiler")

# bounds on one task's shipped profile window: the ring itself bounds
# span RETENTION, these bound what crosses the wire per completion
TASK_PROFILE_MAX_RECORDS = 2000
TASK_PROFILE_MAX_BYTES = 1 << 19  # 512 KiB of records JSON
# per-JOB bound on collected record bytes (scheduler side): keeps the
# merged artifact — and its GetJobProfile serialization — comfortably
# under the 64 MB transport cap however many tasks the job ran
JOB_PROFILE_MAX_BYTES = 32 << 20


def task_profile_enabled() -> bool:
    """BALLISTA_TASK_PROFILE: executors ship per-task profile windows
    with CompletedTask (default on; the payload is bounded and the
    capture is a ring scan, not a trace re-run)."""
    return os.environ.get("BALLISTA_TASK_PROFILE", "").lower() not in (
        "0", "off", "false")


def _phase_delta(now: Dict[str, float], before: Dict[str, float]) -> dict:
    return {k: round(float(now.get(k, 0.0)) - float(before.get(k, 0.0)), 6)
            for k in set(now) | set(before)}


def _compile_delta(now: dict, before: dict) -> dict:
    out = {}
    for k in ("backend_compiles", "compile_seconds", "trace_seconds",
              "persistent_cache_hits"):
        if k in now:
            v = now[k] - before.get(k, 0)
            out[k] = round(v, 6) if isinstance(v, float) else v
    return out


def capture_task_profile(task_key: str, t0: float, wall: float,
                         executor_id: str,
                         phases0: Optional[dict] = None,
                         compile0: Optional[dict] = None) -> dict:
    """Build the profile payload for one completed task from the flight
    recorder. Records are matched by the ``task`` flow attr (every span
    emitted under the task's flow binding — ingest producers included —
    carries it) and FORCE-tagged with this executor's identity: in an
    in-process LocalCluster all executors share one ring and the
    process-level identity stamp belongs to whichever component
    initialized first."""
    from ..compile import compile_stats
    from ..ingest import phase_totals

    matched: List[dict] = []
    for r in tracing.ring_records(since=t0, task=task_key):
        # in-process: the scheduler's dispatch span carries the same
        # task attr but belongs to the scheduler's window
        if str(r.get("name", "")).startswith("scheduler."):
            continue
        r = dict(r)
        r["role"] = "executor"
        r["exec"] = executor_id[:8]
        matched.append(r)
    # the task's own root span lands in the ring LAST (spans are
    # emitted at __exit__), so a chronological keep-earliest truncation
    # would drop exactly the record the merged artifact anchors on (the
    # Gantt slice, the flow-arrow endpoint, the task-worker thread
    # name). Reserve it off the budget before the chronological fill.
    root_idx = next((i for i in range(len(matched) - 1, -1, -1)
                     if matched[i].get("name") == "executor.task"), None)
    root_enc = (json.dumps(matched[root_idx], default=str)
                if root_idx is not None else None)
    max_bytes = TASK_PROFILE_MAX_BYTES - (len(root_enc) if root_enc else 0)
    max_records = TASK_PROFILE_MAX_RECORDS - (1 if root_enc else 0)
    records: List[dict] = []
    encoded: List[str] = []
    truncated = 0
    nbytes = 0
    kept_other = 0
    full = False
    for i, r in enumerate(matched):
        if i == root_idx:
            records.append(r)
            encoded.append(root_enc)
            continue
        if full:  # prefix semantics: past the first overflow, only count
            truncated += 1
            continue
        enc = json.dumps(r, default=str)
        if kept_other >= max_records or nbytes + len(enc) > max_bytes:
            full = True
            truncated += 1
            continue
        nbytes += len(enc)
        kept_other += 1
        records.append(r)
        encoded.append(enc)
    out = {
        # the wire encoding is a byproduct of the size bound above:
        # serde ships it as-is instead of re-serializing the record list
        "records_json": "[" + ",".join(encoded) + "]",
        "t0": t0,
        "wall_seconds": round(wall, 6),
        "pid": os.getpid(),
        "role": "executor",
        "executor_id": executor_id[:8],
        "records": records,
        # process-wide deltas: with concurrent tasks these can
        # cross-attribute — the merged artifact's lanes therefore come
        # from the span records, these ride along as context
        "phases": _phase_delta(phase_totals(), phases0 or {}),
        "compile": _compile_delta(compile_stats(), compile0 or {}),
        "memory": obs_memory.memory_snapshot(),
    }
    # per-task latency-ledger deltas ride the same free-form phases
    # dict as "ledger.<phase>" keys (no proto change): span-derived
    # fetch/write/cache slices + the compile delta, with the remainder
    # as device_execute. The scheduler sums them across tasks at
    # job-terminal time (ledger.assemble_job_ledger).
    try:
        from . import ledger as _ledger

        out["phases"].update(_ledger.task_ledger_phases(
            matched, wall,
            compile_seconds=float(
                out["compile"].get("compile_seconds", 0.0)
                + out["compile"].get("trace_seconds", 0.0))))
    except Exception:  # noqa: BLE001 - observability only
        log.exception("task ledger extraction failed")
    if truncated:
        out["records_truncated"] = truncated
    return out


# ---------------------------------------------------------------------------
# Scheduler-side merge
# ---------------------------------------------------------------------------


def merged_session(job_id: str, scheduler_records: List[dict],
                   task_profiles: List[dict], wall_seconds: float,
                   label: Optional[str] = None) -> dict:
    """Join the scheduler's ring window with every executor task payload
    into one profiler session (export.build_artifact renders it)."""
    def _dedup_key(r: dict):
        # spans dedup structurally by (pid, sid); instant events carry
        # no sid, so they key on (pid, tid, ts, name) instead
        if r.get("sid") is not None:
            return (r.get("pid"), "sid", r.get("sid"))
        return (r.get("pid"), r.get("tid"), r.get("ts"), r.get("name"))

    seen = {_dedup_key(r)
            for p in task_profiles for r in p.get("records") or []}
    records: List[dict] = []
    for r in scheduler_records:
        # in-process cluster: the scheduler's ring ALSO holds the
        # executor-window records — drop the duplicates structurally
        if _dedup_key(r) in seen:
            continue
        r = dict(r)
        r.setdefault("role", "scheduler")
        records.append(r)
    executors = []
    memory: Dict[str, dict] = {}
    compile_total: dict = {}
    for p in task_profiles:
        records.extend(p.get("records") or [])
        ex = p.get("executor_id", "?")
        if ex not in executors:
            executors.append(ex)
        memory[ex] = p.get("memory") or {}
        for k, v in (p.get("compile") or {}).items():
            compile_total[k] = compile_total.get(k, 0) + v
    t0 = min((float(r.get("ts", 0.0)) for r in records), default=0.0)
    return {
        "schema": "ballista-profile-v1",
        "label": label or f"job-{job_id}",
        "t0": t0,
        "wall_seconds": round(float(wall_seconds), 6),
        # no process-wide phase deltas: compute_lanes falls back to the
        # ingest.* span sums across all processes
        "phases": {},
        "compile": compile_total,
        "memory": {"scheduler": obs_memory.memory_snapshot(),
                   "executors": memory},
        "operators": None,
        "records": records,
        "distributed": {
            "job_id": job_id,
            "num_task_profiles": len(task_profiles),
            "executors": executors,
        },
    }


class JobProfileCollector:
    """Bounded per-job collection of executor task-profile payloads plus
    the artifacts built from them. The scheduler keeps ONE instance;
    everything here is advisory observability state — bounded rings,
    never the source of truth for scheduling."""

    def __init__(self, max_jobs: int = 16, max_tasks_per_job: int = 512):
        self._lock = threading.Lock()
        self._max_jobs = max_jobs
        self._max_tasks = max_tasks_per_job
        # job_id -> {"tasks": [profile...], "summary": dict|None,
        #            "artifact": dict|None, "path": str|None}
        self._jobs: Dict[str, dict] = {}
        self._order: List[str] = []

    def _slot(self, job_id: str) -> dict:
        # caller holds the lock
        slot = self._jobs.get(job_id)
        if slot is None:
            slot = {"tasks": [], "bytes": 0, "summary": None,
                    "artifact": None, "partial": None, "path": None}
            self._jobs[job_id] = slot
            self._order.append(job_id)
            while len(self._order) > self._max_jobs:
                self._jobs.pop(self._order.pop(0), None)
        return slot

    def add_task_profile(self, job_id: str, profile: dict,
                         nbytes: Optional[int] = None) -> None:
        """``nbytes``: the wire size of the payload's record blob (the
        caller usually has it from the proto). Counted toward a per-job
        byte cap so a long job's many task windows can't grow the
        merged artifact past what the transport can return."""
        if nbytes is None:
            nbytes = sum(len(str(r)) for r in profile.get("records") or [])
        with self._lock:
            slot = self._slot(job_id)
            if len(slot["tasks"]) < self._max_tasks and \
                    slot["bytes"] + nbytes <= JOB_PROFILE_MAX_BYTES:
                slot["tasks"].append(profile)
                slot["bytes"] += nbytes

    def finalize(self, job_id: str, summary: dict) -> None:
        """Record the job's terminal summary (wall seconds, state, plan
        digest) so on-demand artifact builds after completion have the
        window metadata."""
        with self._lock:
            self._slot(job_id)["summary"] = dict(summary)

    def set_artifact(self, job_id: str, artifact: dict,
                     path: Optional[str]) -> None:
        with self._lock:
            slot = self._slot(job_id)
            slot["artifact"] = artifact
            slot["path"] = path

    def artifact_path(self, job_id: str) -> Optional[str]:
        with self._lock:
            slot = self._jobs.get(job_id)
            return slot["path"] if slot else None

    def task_payloads(self, job_id: str) -> List[dict]:
        """The job's collected per-task profile payloads (shared list
        snapshot; callers must not mutate the payload dicts)."""
        with self._lock:
            slot = self._jobs.get(job_id)
            return list(slot["tasks"]) if slot else []

    def build(self, job_id: str,
              wall_seconds: Optional[float] = None,
              sched_records: Optional[List[dict]] = None) -> Optional[dict]:
        """The job's merged artifact: the cached one when a prior build
        exists, else built now from the collected task payloads + the
        scheduler's ring window (``sched_records`` when the caller
        already snapshotted it — the deferred terminal build does, so
        later queries can't evict this job's spans first). None for
        unknown jobs. A build for a job that is NOT yet terminal (no
        finalized summary — e.g. a /debug/profile hit mid-job) is
        returned but never cached, so it cannot poison the artifact the
        terminal transition builds."""
        from . import export

        with self._lock:
            slot = self._jobs.get(job_id)
            if slot is None:
                return None
            if slot["artifact"] is not None:
                return slot["artifact"]
            terminal = slot["summary"] is not None
            if not terminal:
                # mid-job builds get polled (df.profile() waits for the
                # terminal one at 100-250ms intervals): serve a briefly
                # cached partial instead of re-merging every poll
                pa = slot.get("partial")
                if pa is not None and time.time() - pa[0] < 0.5:
                    return pa[1]
            tasks = list(slot["tasks"])
            summary = slot["summary"] or {}
        if wall_seconds is None:
            wall_seconds = float(summary.get("wall_seconds", 0.0))
        if sched_records is None:
            sched_records = tracing.ring_records(job=job_id)
        if not tasks and not sched_records:
            return None
        session = merged_session(job_id, sched_records, tasks,
                                 wall_seconds)
        if not terminal:
            session["distributed"]["partial"] = True
        if summary.get("plan_digest"):
            session["distributed"]["plan_digest"] = summary["plan_digest"]
        art = export.build_artifact(session)
        with self._lock:
            # cache (races build the same value; last write wins)
            if terminal:
                self._slot(job_id)["artifact"] = art
            else:
                self._slot(job_id)["partial"] = (time.time(), art)
        return art


# ---------------------------------------------------------------------------
# Retroactive slow-query dump (standalone path)
# ---------------------------------------------------------------------------


def slow_query_dir() -> str:
    """Where retroactive slow-query artifacts land:
    ``BALLISTA_SLOW_QUERY_DIR`` > ``BALLISTA_PROFILE`` dir > tempdir."""
    import tempfile

    v = os.environ.get("BALLISTA_SLOW_QUERY_DIR")
    if v:
        return v
    from .profiler import profile_dir

    d = profile_dir()
    return d if d is not None else tempfile.gettempdir()


def slow_query_max_artifacts() -> int:
    """``BALLISTA_SLOW_QUERY_MAX_ARTIFACTS`` (default 32): cap on
    retained slow-query dumps in ``slow_query_dir()`` — under sustained
    overload every slow query writes one, so an uncapped directory
    grows without bound. 0 disables pruning."""
    try:
        return max(int(os.environ.get(
            "BALLISTA_SLOW_QUERY_MAX_ARTIFACTS", "32")), 0)
    except ValueError:
        return 32


def prune_slow_query_artifacts(out_dir: Optional[str] = None) -> int:
    """Delete the OLDEST ``ballista-profile-*.json`` dumps past the
    max-artifacts cap (oldest by mtime — the newest dumps are the ones
    an operator is about to look at). Only artifact-named files are
    touched: the slow-query dir may be a shared profile dir. Returns
    the number of files removed; never raises."""
    cap = slow_query_max_artifacts()
    if cap <= 0:
        return 0
    d = out_dir or slow_query_dir()
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith("ballista-profile-")
                 and n.endswith(".json")]
    except OSError:
        return 0
    if len(names) <= cap:
        return 0
    entries = []
    for n in names:
        path = os.path.join(d, n)
        try:
            entries.append((os.path.getmtime(path), path))
        except OSError:  # raced a concurrent prune/delete
            continue
    entries.sort()
    removed = 0
    for _, path in entries[:max(len(entries) - cap, 0)]:
        try:
            os.remove(path)
            removed += 1
        except OSError:
            continue
    if removed:
        log.info("pruned %d slow-query artifact(s) past the %d-file cap "
                 "in %s", removed, cap, d)
    return removed


def dump_ring_artifact(label: str, t0: float, wall: float,
                       phases0: Optional[dict] = None,
                       compile0: Optional[dict] = None,
                       out_dir: Optional[str] = None) -> Optional[str]:
    """Write a profile artifact for the window [t0, now] straight from
    the flight recorder — the retroactive path, used when a query turns
    out slow AFTER it ran unprofiled. Returns the artifact path, or
    None when the ring is off/empty."""
    from ..compile import compile_stats
    from ..ingest import phase_totals
    from . import export

    records = tracing.ring_records(since=t0)
    if not records:
        return None
    session = {
        "schema": "ballista-profile-v1",
        "label": label,
        "t0": t0,
        "wall_seconds": round(wall, 6),
        "phases": _phase_delta(phase_totals(), phases0 or {}),
        "compile": _compile_delta(compile_stats(), compile0 or {}),
        "memory": obs_memory.memory_snapshot(),
        "operators": None,
        "records": records,
        "flight_recorder": True,
    }
    dest = out_dir or slow_query_dir()
    path = export.write_artifact(session, out_dir=dest)
    prune_slow_query_artifacts(dest)
    return path


@contextmanager
def watch_slow_query(label_fn: Callable[[], str],
                     artifact_out: Optional[list] = None):
    """Wrap a standalone collect: when ``BALLISTA_SLOW_QUERY_SECS`` is
    set and the wrapped block takes at least that long, dump a
    retroactive artifact from the flight recorder. Costs nothing when
    the threshold is unset; never raises into the query.
    ``artifact_out`` (a list) receives the written artifact path so the
    caller can link it from the query-history record."""
    from .health import slow_query_secs

    thr = slow_query_secs()
    if thr is None or not tracing.flight_recorder_enabled():
        yield
        return
    from ..compile import compile_stats
    from ..ingest import phase_totals

    phases0 = phase_totals()
    compile0 = compile_stats()
    t0 = time.time()
    try:
        yield
    finally:
        wall = time.time() - t0
        if wall >= thr:
            try:
                label = f"slow-{label_fn()}"
            except Exception:  # noqa: BLE001 - label is cosmetic
                label = "slow-query"
            try:
                path = dump_ring_artifact(label, t0, wall,
                                          phases0=phases0,
                                          compile0=compile0)
                if path and artifact_out is not None:
                    artifact_out.append(path)
                if path:
                    log.warning(
                        "slow query (%.3fs >= %.3fs): retroactive "
                        "profile artifact written: %s", wall, thr, path)
            except Exception:  # noqa: BLE001 - never fail the query
                log.exception("retroactive slow-query dump failed")
