"""SQL-queryable ``system.*`` tables + the durable query-history log.

Every telemetry surface the engine grew in PRs 1-7 (per-operator
MetricsSet, profiler lane decomposition, Prometheus families,
``/debug/queries``) was a side channel: an HTTP endpoint, a JSON
artifact, a bench line. This module dogfoods the engine instead — its
own telemetry becomes relational tables served by the engine itself:

- ``system.queries``   — recent queries (bounded ring) + the durable
  on-disk history (``BALLISTA_QUERY_LOG_DIR``): job id, plan digest,
  status, wall seconds, output rows, peak memory, profile artifact.
- ``system.query_lanes`` — one row per query x named wall-time lane
  (the profiler's decomposition: parse / h2d / compile_trace_lower /
  device_blocked / host_dictionary / xla_execute_other).
- ``system.operators`` — per-operator MetricsSet rows of the last N
  queries, long format (one row per operator x metric).
- ``system.compile``   — compile-governor entries: signature, calls,
  compiles, elapsed compile seconds, persistent-cache hits, AOT loads.
- ``system.cache``     — warm-path serving caches (docs/caching.md):
  one row per device-resident table entry / host result-cache entry.
- ``system.executors`` — executor heartbeat resources (cluster) or one
  row for the current process (standalone).
- ``system.settings``  — every ``BALLISTA_*`` knob: effective value,
  default, source, description (the registry ``dev/check_knob_docs.py``
  lints against the source tree and the README knob table).

ONE snapshot layer feeds every surface: the query records built by
:func:`build_query_record` are what ``/debug/queries`` serves (via
``health.QueryLog``), what the history log persists, and what
``system.queries`` scans materialize — so the surfaces cannot drift.
System tables are ordinary plans (a :class:`SystemTableSource` scan),
so EXPLAIN / EXPLAIN ANALYZE, whole-stage fusion and the profiler all
apply to them for free.

Standalone vs cluster semantics: a standalone context scans the
CURRENT PROCESS's snapshot; a remote context fetches rows from the
SCHEDULER (``GetSystemTable`` RPC) at scan/ship time, so
``system.executors`` / ``system.queries`` reflect the whole cluster,
not the client process.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

from ..datatypes import Float64, Int64, Schema, Utf8, schema as make_schema
from ..logical import TableSource

# ---------------------------------------------------------------------------
# Knob registry (system.settings + dev/check_knob_docs.py)
# ---------------------------------------------------------------------------

# name -> (default as the docs state it, description). The single
# source of truth for BALLISTA_* env knobs: dev/check_knob_docs.py
# fails tier-1 when a knob read in the source is missing here (or from
# the README knob table), and vice versa.
KNOBS: Dict[str, tuple] = {
    # compile governor / shape bucketing (docs/compile_cache.md)
    "BALLISTA_SHAPE_BUCKETS": ("on", "quantize batch capacities onto the "
                                     "canonical geometric ladder"),
    "BALLISTA_SHAPE_BUCKETS_FLOOR": ("1024", "smallest ladder rung"),
    "BALLISTA_SHAPE_BUCKETS_GROWTH": ("2", "geometric ladder step"),
    "BALLISTA_FUSION": ("on", "whole-stage fusion: one governed XLA "
                              "program per pipeline stage"),
    "BALLISTA_FUSION_AOT_DIR": ("off", "serialize fused-stage programs "
                                       "(jax.export) into this directory"),
    "BALLISTA_PREWARM": ("off", "AOT-compile fused stages concurrently "
                                "with parse/H2D"),
    "BALLISTA_XLA_CACHE": ("~/.cache/ballista-tpu-xla-<cpu-tag>",
                           "persistent XLA compilation cache dir "
                           "(empty = disabled)"),
    "BALLISTA_XLA_CACHE_MIN_COMPILE_SECS": ("0", "only disk-cache kernels "
                                                 "compiling at least this "
                                                 "long"),
    "BALLISTA_JIT_CACHE_ENTRIES": ("1024", "per-namespace LRU bound on "
                                           "governed jit entries"),
    "BALLISTA_JIT_TRACES_PER_ENTRY": ("128", "clear an entry's in-memory "
                                             "trace cache past this many "
                                             "specializations"),
    # ingest (docs/ingest.md)
    "BALLISTA_INGEST_THREADS": ("min(cpu_count, 8)", "shared ingest pool "
                                                     "width"),
    "BALLISTA_PREFETCH_BATCHES": ("2", "per-scan bounded prefetch depth "
                                       "(0 = serial pull loop)"),
    "BALLISTA_SCAN_THREADS": ("cpu count", "native C++ scanner threads "
                                           "within one file"),
    "BALLISTA_SCAN_CHUNK_BYTES": ("1073741824", "text scan chunk size"),
    # kernels / execution
    "BALLISTA_DICT_REGISTRY": ("on", "process-wide dictionary registry: "
                                     "interned string dictionaries, "
                                     "cached integer remaps, epoch-keyed "
                                     "AOT artifacts (off = legacy "
                                     "object-array unify/remap; "
                                     "docs/strings.md)"),
    "BALLISTA_PALLAS": ("off", "force the Pallas dense-aggregation kernel "
                               "(off/on/interpret)"),
    "BALLISTA_JOIN_SWAP": ("on", "planner may swap join build/probe sides "
                                 "by estimated size"),
    "BALLISTA_JOIN_SYNC_WINDOW": ("8", "deferred-sync join build window "
                                       "(batches)"),
    "BALLISTA_JOIN_SYNC_WINDOW_BYTES": ("1073741824", "deferred-sync join "
                                                      "build window cap "
                                                      "(bytes)"),
    "BALLISTA_NARROW_WIRE": ("auto", "narrow integer wire encoding for "
                                     "shuffle IPC"),
    "BALLISTA_ALLOW_MIMALLOC": ("off", "skip the jemalloc pool guard for "
                                       "pyarrow"),
    # distributed / streaming shuffle (docs/shuffle.md)
    "BALLISTA_NATIVE_DATAPLANE": ("on", "serve shuffle partitions from the "
                                        "native C++ daemon (off = Python)"),
    "BALLISTA_SHUFFLE_CHUNK_BYTES": ("4194304", "max Arrow-IPC record-"
                                                "batch / wire-frame size "
                                                "on the shuffle path"),
    "BALLISTA_SHUFFLE_MEM_BUDGET": ("268435456", "per-process cap on "
                                                 "in-flight shuffle "
                                                 "buffer bytes"),
    "BALLISTA_SHUFFLE_SPILL_WATERMARK": ("0.8", "budget fraction past "
                                                "which fetched chunks "
                                                "divert to disk"),
    "BALLISTA_SHUFFLE_SPILL_DIR": ("tempdir/ballista-spill-<pid>",
                                   "directory for size-rotated spill "
                                   "segments"),
    "BALLISTA_SHUFFLE_SPILL_FILE_MB": ("64", "spill segment rotation "
                                             "size"),
    "BALLISTA_SHUFFLE_WINDOW_BYTES": ("4x chunk bytes", "flow-control "
                                                        "window: max "
                                                        "unacked in-"
                                                        "flight bytes "
                                                        "per peer "
                                                        "stream"),
    "BALLISTA_MESH_GROUP_ACK_TIMEOUT": ("3600", "multi-process mesh group "
                                                "broadcast ack timeout "
                                                "(seconds)"),
    # observability (docs/observability.md)
    "BALLISTA_METRICS": ("on", "per-operator MetricsSet collection "
                               "(EXPLAIN ANALYZE forces it back on)"),
    "BALLISTA_METRICS_PORT": ("off", "health plane port (0 = ephemeral, "
                                     "-1 = off)"),
    "BALLISTA_TRACE": ("off", "span tracing to a JSON-lines file"),
    "BALLISTA_TRACE_FILE": ("auto", "pin the exact trace file path"),
    "BALLISTA_TRACE_DIR": ("tempdir", "directory for per-process trace "
                                      "files"),
    "BALLISTA_TRACE_TRUNCATE": ("off", "open the trace file fresh instead "
                                       "of appending"),
    "BALLISTA_TRACE_MAX_MB": ("unbounded", "cap the trace file size"),
    "BALLISTA_FLIGHT_RECORDER": ("on", "always-on bounded in-memory ring "
                                       "of recent spans"),
    "BALLISTA_FLIGHT_RECORDER_SPANS": ("4096", "flight-recorder ring "
                                               "capacity"),
    "BALLISTA_PROFILE": ("off", "write one Chrome-trace profile artifact "
                                "per query into this directory"),
    "BALLISTA_TASK_PROFILE": ("on", "executors ship per-task profile "
                                    "windows with CompletedTask"),
    "BALLISTA_SLOW_QUERY_SECS": ("off", "slow-query threshold: ring entry "
                                        "+ retroactive profile artifact"),
    "BALLISTA_SLOW_QUERY_DIR": ("profile dir, else tempdir",
                                "where retroactive slow-query artifacts "
                                "land"),
    "BALLISTA_SLOW_QUERY_MAX_ARTIFACTS": ("32", "retained slow-query "
                                                "dumps per directory; "
                                                "oldest deleted past the "
                                                "cap (0 = unbounded)"),
    "BALLISTA_LEDGER": ("on", "always-on per-query latency ledger: phase "
                              "attribution into system.latency + "
                              "ballista_latency_* SLO histograms with "
                              "exemplars"),
    "BALLISTA_LEDGER_LOG": ("256", "recent query ledgers retained per "
                                   "process (system.latency window)"),
    "BALLISTA_QUERY_LOG_DIR": ("off", "durable query-history log "
                                      "directory (JSON lines, size-capped "
                                      "rotation; feeds system.queries "
                                      "across restarts)"),
    "BALLISTA_QUERY_LOG_MAX_MB": ("16", "rotate the query-history log "
                                        "past this size (one rotated "
                                        "segment is kept)"),
    # live progress & session metering plane (docs/observability.md)
    "BALLISTA_PROGRESS_INTERVAL_SECS": ("1.0", "cadence of executor "
                                               "TaskProgress piggybacks "
                                               "and ambient standalone "
                                               "sampling (0/off disables "
                                               "the plane)"),
    "BALLISTA_EXECUTOR_STALE_SECS": ("15", "heartbeat age past which "
                                           "system.executors marks a row "
                                           "stale=true"),
    # query lifecycle control plane (docs/robustness.md)
    "BALLISTA_SLOW_QUERY_KILL_SECS": ("off", "upgrade the slow-query log "
                                             "to a KILL: cancel queries "
                                             "running longer than this "
                                             "(both paths)"),
    "BALLISTA_CANCEL_ON_TIMEOUT": ("on", "a client-side job timeout "
                                         "issues a best-effort CancelJob "
                                         "before raising (off = old "
                                         "abandon-the-job behavior)"),
    "BALLISTA_DRAIN_TIMEOUT_SECS": ("20", "graceful drain bound: "
                                          "in-flight tasks get this long "
                                          "to finish before being "
                                          "cancelled"),
    "BALLISTA_FAULTS": ("off", "deterministic fault injection spec "
                               "(point=trigger[;...]; see "
                               "docs/robustness.md)"),
    "BALLISTA_POLL_BACKOFF_MAX_SECS": ("8", "executor poll-loop backoff "
                                            "ceiling while the scheduler "
                                            "is unreachable"),
    "BALLISTA_MAX_TASK_RECOVERIES": ("3", "recovery events allowed per "
                                          "job (transient retry, fetch "
                                          "recovery, lease reap) before "
                                          "the job fails"),
    "BALLISTA_SPECULATION_LAG_FACTOR": ("3.0", "duplicate a running task "
                                               "when its sampled row rate "
                                               "x this factor trails the "
                                               "stage median (<=1 = age "
                                               "trigger only)"),
    "BALLISTA_ADMISSION_RETRY": ("on", "remote_collect honors admission "
                                       "shed retry-after (sleep + "
                                       "resubmit within the job "
                                       "timeout; off = raise "
                                       "immediately)"),
    "BALLISTA_CONTROLPLANE_COST_FEEDBACK": (
        "on", "planner consults persisted per-digest stage costs for "
              "initial partition counts and join strategy (off = "
              "static defaults; AQE still corrects mid-flight)"),
    "BALLISTA_CONTROLPLANE_COST_TARGET_PARTITION_BYTES": (
        "67108864", "cost feedback sizes shuffle partition counts so "
                    "each partition carries about this many observed "
                    "shuffle bytes"),
    # warm-path serving caches (docs/caching.md)
    "BALLISTA_TABLE_CACHE": ("on", "pin hot scan outputs device-resident "
                                   "across queries (parse + H2D skipped "
                                   "on repeat scans)"),
    "BALLISTA_TABLE_CACHE_BUDGET_MB": ("512", "device-memory budget for "
                                              "pinned table batches"),
    "BALLISTA_TABLE_CACHE_WATERMARK": ("0.9", "budget fraction past which "
                                              "fills evict coldest "
                                              "entries (never block)"),
    "BALLISTA_RESULT_CACHE": ("off", "plan-fingerprint result cache: "
                                     "repeat collects of an identical "
                                     "plan over unchanged inputs return "
                                     "host-cached rows"),
    "BALLISTA_RESULT_CACHE_BUDGET_MB": ("64", "host-memory budget for "
                                              "cached query results"),
    "BALLISTA_DONATION": ("on", "donate single-consumer intermediate "
                                "buffers into governed programs "
                                "(donate_argnums in-place reuse)"),
}

# dynamic env-name families: read via computed names, documented as
# patterns (the lint accepts any BALLISTA_* literal covered by one)
KNOB_PREFIXES: Dict[str, str] = {
    "BALLISTA_ADAPTIVE_": "adaptive.* setting fallbacks "
                          "(adaptive/config.py)",
    "BALLISTA_SCHEDULER_": "scheduler binary config overrides "
                           "(distributed/config.py)",
    "BALLISTA_EXECUTOR_": "executor binary config overrides "
                          "(distributed/config.py)",
    "BALLISTA_ADMISSION_": "admission.* setting fallbacks "
                           "(distributed/admission.py; quotas, "
                           "saturation bound, queue timeout — see "
                           "docs/robustness.md)",
    "BALLISTA_AUTOSCALE_": "autoscale.* setting fallbacks "
                           "(distributed/controlplane/autoscaler.py; "
                           "fleet bounds, backlog/ETA thresholds, "
                           "cooldown — see docs/robustness.md)",
    "BALLISTA_CONTROLPLANE_": "controlplane.* setting fallbacks "
                              "(distributed/controlplane/; cost "
                              "feedback — see docs/robustness.md)",
}


def settings_rows() -> List[dict]:
    """``system.settings``: one row per registered knob with its
    EFFECTIVE value (env wins over default), plus any set env var from
    the dynamic families."""
    rows = []
    for name, (default, desc) in sorted(KNOBS.items()):
        env = os.environ.get(name)
        rows.append({
            "name": name,
            "value": env if env is not None else default,
            "default": default,
            "source": "env" if env is not None else "default",
            "description": desc,
        })
    for prefix, desc in sorted(KNOB_PREFIXES.items()):
        for name in sorted(os.environ):
            if name.startswith(prefix) and name not in KNOBS:
                rows.append({
                    "name": name, "value": os.environ[name],
                    "default": "", "source": "env", "description": desc,
                })
    return rows


# ---------------------------------------------------------------------------
# Table schemas
# ---------------------------------------------------------------------------

SYSTEM_SCHEMAS: Dict[str, Schema] = {
    "system.queries": make_schema(
        ("job_id", Utf8), ("plan_digest", Utf8), ("status", Utf8),
        ("started_at", Float64), ("wall_seconds", Float64),
        ("output_rows", Int64), ("num_stages", Int64),
        ("peak_host_bytes", Int64), ("peak_device_bytes", Int64),
        ("profile_artifact", Utf8), ("error", Utf8),
        ("cancel_reason", Utf8), ("origin", Utf8),
        # admission plane: live 1-based queue position while a job is
        # held in the scheduler's admission queue (NULL otherwise)
        ("queue_position", Int64),
    ),
    "system.query_lanes": make_schema(
        ("job_id", Utf8), ("plan_digest", Utf8), ("lane", Utf8),
        ("seconds", Float64), ("fraction", Float64),
    ),
    "system.operators": make_schema(
        ("job_id", Utf8), ("plan_digest", Utf8), ("stage_id", Int64),
        ("op_index", Int64), ("operator", Utf8), ("depth", Int64),
        ("metric", Utf8), ("value", Float64),
    ),
    "system.compile": make_schema(
        ("namespace", Utf8), ("signature", Utf8), ("calls", Int64),
        ("compiles", Int64), ("compile_seconds", Float64),
        ("persistent_cache_hits", Int64), ("aot_loads", Int64),
    ),
    "system.executors": make_schema(
        ("executor_id", Utf8), ("host", Utf8), ("port", Int64),
        ("num_devices", Int64), ("rss_bytes", Int64),
        ("device_bytes", Int64), ("inflight_tasks", Int64),
        ("ingest_pool_depth", Int64), ("peak_host_bytes", Int64),
        # shuffle memory governor (distributed/spill.py): governed
        # in-flight shuffle buffer bytes + cumulative spill, per
        # heartbeat
        ("shuffle_inflight_bytes", Int64), ("spill_bytes_total", Int64),
        # live progress plane: scheduler-side clock minus the last
        # heartbeat; stale=1 past BALLISTA_EXECUTOR_STALE_SECS (or when
        # the executor never heartbeated this scheduler lifetime)
        ("heartbeat_age_seconds", Float64), ("stale", Int64),
    ),
    "system.settings": make_schema(
        ("name", Utf8), ("value", Utf8), ("default", Utf8),
        ("source", Utf8), ("description", Utf8),
    ),
    # live progress plane (observability/progress.py): running tasks,
    # per-stage completion fractions, cumulative per-session metering
    "system.tasks": make_schema(
        ("job_id", Utf8), ("stage_id", Int64), ("partition_id", Int64),
        ("executor_id", Utf8), ("operator", Utf8),
        ("rows_so_far", Int64), ("bytes_so_far", Int64),
        ("elapsed_seconds", Float64),
    ),
    "system.stages": make_schema(
        ("job_id", Utf8), ("stage_id", Int64), ("tasks_total", Int64),
        ("tasks_running", Int64), ("tasks_completed", Int64),
        ("fraction", Float64), ("eta_seconds", Float64),
        ("rows_so_far", Int64), ("bytes_so_far", Int64),
    ),
    "system.sessions": make_schema(
        ("session_id", Utf8), ("queries", Int64),
        ("wall_seconds", Float64), ("task_seconds", Float64),
        ("device_blocked_seconds", Float64), ("bytes_shuffled", Int64),
        ("peak_host_bytes", Int64), ("peak_device_bytes", Int64),
        # warm-path cache attribution (docs/caching.md): scans served
        # from the device table cache / collects served from the
        # result cache, accumulated per session
        ("table_cache_hits", Int64), ("result_cache_hits", Int64),
        ("started_at", Float64), ("last_active", Float64),
    ),
    # warm-path serving caches (cache/residency.py + cache/results.py):
    # one row per live entry across both tiers
    "system.cache": make_schema(
        ("tier", Utf8), ("entry", Utf8), ("bytes", Int64),
        ("hits", Int64), ("age_seconds", Float64),
        ("idle_seconds", Float64),
    ),
    # admission plane (distributed/admission.py): recent gate/pump
    # decisions — the scheduler's ring on the cluster path, empty
    # standalone (collects never pass an admission gate)
    "system.admission": make_schema(
        ("job_id", Utf8), ("session_id", Utf8), ("decision", Utf8),
        ("reason", Utf8), ("priority", Float64),
        ("cluster_load", Int64), ("queue_wait_seconds", Float64),
        ("retry_after_seconds", Float64), ("decided_at", Float64),
    ),
    # elastic control plane (distributed/controlplane/autoscaler.py):
    # recent scale-up/scale-down decisions — the scheduler's ring on
    # the cluster path, empty standalone or with the autoscaler off
    "system.autoscaler": make_schema(
        ("decided_at", Float64), ("action", Utf8), ("reason", Utf8),
        ("executors", Int64), ("target", Int64), ("backlog", Int64),
        ("inflight_tasks", Int64), ("eta_seconds", Float64),
        ("drained", Utf8),
    ),
    # latency ledger (observability/ledger.py): one row per recent
    # query per phase (plus an "unattributed" remainder row) — the
    # always-on SLO attribution surface
    "system.latency": make_schema(
        ("job_id", Utf8), ("origin", Utf8), ("status", Utf8),
        ("phase", Utf8), ("seconds", Float64), ("fraction", Float64),
        ("wall_seconds", Float64),
    ),
    # SLO histogram exemplars (observability/metrics.py): the most
    # recent worst offender per latency bucket, full ledger attached
    "system.exemplars": make_schema(
        ("family", Utf8), ("phase", Utf8), ("bucket_le", Float64),
        ("job_id", Utf8), ("seconds", Float64),
        ("wall_seconds", Float64), ("ledger_json", Utf8),
    ),
}

SYSTEM_TABLES = tuple(sorted(SYSTEM_SCHEMAS))


def is_system_table(name: str) -> bool:
    return name in SYSTEM_SCHEMAS


# ---------------------------------------------------------------------------
# Query records: the ONE builder every surface shares
# ---------------------------------------------------------------------------


def build_query_record(job_id: str, status: str, wall_seconds: float,
                       plan_digest: Optional[str] = None,
                       output_rows: Optional[int] = None,
                       num_stages: Optional[int] = None,
                       started_at: Optional[float] = None,
                       peak_host_bytes: Optional[int] = None,
                       peak_device_bytes: Optional[int] = None,
                       profile_artifact: Optional[str] = None,
                       error: Optional[str] = None,
                       cancel_reason: Optional[str] = None,
                       lanes: Optional[dict] = None,
                       origin: str = "standalone") -> dict:
    """The canonical query summary dict: what the /debug/queries ring,
    the durable history log and ``system.queries`` scans all carry.
    ``state`` is kept as an alias of ``status`` for pre-existing
    consumers of the ring shape."""
    rec = {
        "job_id": job_id,
        "status": status,
        "state": status,  # legacy ring key
        "wall_seconds": round(float(wall_seconds), 4),
        "origin": origin,
    }
    if plan_digest:
        rec["plan_digest"] = plan_digest
    if output_rows is not None:
        rec["output_rows"] = int(output_rows)
    if num_stages is not None:
        rec["num_stages"] = int(num_stages)
    if started_at is not None:
        rec["started_at"] = float(started_at)
    if peak_host_bytes is not None:
        rec["peak_host_bytes"] = int(peak_host_bytes)
    if peak_device_bytes is not None:
        rec["peak_device_bytes"] = int(peak_device_bytes)
    if profile_artifact:
        rec["profile_artifact"] = profile_artifact
    if error:
        rec["error"] = str(error)[:300]
    if cancel_reason:
        rec["cancel_reason"] = str(cancel_reason)
    if lanes:
        rec["lanes"] = {k: float(v) for k, v in lanes.items()}
    return rec


# ---------------------------------------------------------------------------
# Durable query-history log (BALLISTA_QUERY_LOG_DIR)
# ---------------------------------------------------------------------------

_HISTORY_FILE = "query_history.jsonl"


class QueryHistoryLog:
    """Bounded on-disk JSON-lines history with size-capped rotation.

    One line per terminal query record; when the file crosses the byte
    cap it rotates to ``.1`` (one rotated segment kept, so disk usage
    is bounded at ~2x the cap). Appends reopen the file each time
    (O_APPEND) so several engine processes sharing the directory — a
    scheduler next to a standalone context — interleave whole lines
    instead of clobbering a shared handle. Readers dedup by job_id,
    LAST line wins: late-arriving facts (a deferred profile artifact or
    lane decomposition) are appended as an enriched repeat line."""

    def __init__(self, directory: str, max_bytes: Optional[int] = None):
        self.dir = directory
        if max_bytes is None:
            try:
                max_bytes = int(float(os.environ.get(
                    "BALLISTA_QUERY_LOG_MAX_MB", "16")) * 1e6)
            except ValueError:
                max_bytes = 16_000_000
        self.max_bytes = max(max_bytes, 4096)
        self._lock = threading.Lock()
        self.path = os.path.join(directory, _HISTORY_FILE)

    def append(self, record: dict) -> None:
        """Best-effort durable append; never raises into the query."""
        line = json.dumps(record, default=str)
        with self._lock:
            try:
                os.makedirs(self.dir, exist_ok=True)
                try:
                    if os.path.getsize(self.path) + len(line) + 1 > \
                            self.max_bytes:
                        os.replace(self.path, self.path + ".1")
                except OSError:
                    pass  # no file yet
                with open(self.path, "a") as fh:
                    fh.write(line + "\n")
            except OSError:
                import logging

                logging.getLogger("ballista.systables").warning(
                    "query-history append failed (dir %s)", self.dir,
                    exc_info=True)

    def read(self) -> List[dict]:
        """All surviving history records, oldest first (rotated segment
        before the live file), duplicates by job_id collapsed to the
        LAST occurrence."""
        records: List[dict] = []
        for path in (self.path + ".1", self.path):
            try:
                with open(path) as fh:
                    for line in fh:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if isinstance(rec, dict):
                            records.append(rec)
            except OSError:
                continue
        by_job: Dict[str, dict] = {}
        order: List[str] = []
        for rec in records:
            jid = str(rec.get("job_id", ""))
            if jid not in by_job:
                order.append(jid)
            by_job[jid] = rec
        return [by_job[j] for j in order]


_history_lock = threading.Lock()
_history_cache: dict = {}  # dir -> QueryHistoryLog


def query_log_dir() -> Optional[str]:
    v = os.environ.get("BALLISTA_QUERY_LOG_DIR", "")
    if not v or v.lower() in ("0", "off", "false"):
        return None
    return v


def history_log() -> Optional[QueryHistoryLog]:
    """The process's history log for the current
    ``BALLISTA_QUERY_LOG_DIR`` (None when unset)."""
    d = query_log_dir()
    if d is None:
        return None
    with _history_lock:
        log = _history_cache.get(d)
        if log is None:
            log = _history_cache[d] = QueryHistoryLog(d)
        return log


def record_query(record: dict, query_log=None) -> None:
    """Record a terminal query: into the given ring (``health.QueryLog``
    — the scheduler's, or this process's default), and into the durable
    history log when configured. The one write path every surface
    shares."""
    (query_log or process_query_log()).record(record)
    hist = history_log()
    if hist is not None:
        hist.append(record)


def annotate_query(job_id: str, query_log=None, **fields) -> None:
    """Attach late-arriving facts (profile artifact path, lanes) to a
    recorded query: updates the ring entries in place and appends an
    enriched history line (readers keep the last line per job)."""
    ql = query_log or process_query_log()
    ql.annotate(job_id, **fields)
    hist = history_log()
    if hist is not None:
        entry = next((e for e in ql.snapshot()["queries"]
                      if e.get("job_id") == job_id), None)
        if entry is not None:
            hist.append(entry)


# -- process-global stores (standalone surface) ------------------------------

_process_lock = threading.Lock()
_process_query_log = None
_local_job_ids = itertools.count(1)


def process_query_log():
    """This process's query ring: what a standalone context records
    into and what its ``system.queries`` scans read."""
    global _process_query_log
    with _process_lock:
        if _process_query_log is None:
            from .health import QueryLog

            _process_query_log = QueryLog()
            # live progress plane: in-flight standalone collects show
            # up as status="running" rows with live wall seconds
            from . import progress as obs_progress

            _process_query_log.live_fn = \
                obs_progress.local_live_query_records
        return _process_query_log


def _reset_process_state_for_tests() -> None:
    """Drop the in-memory rings (NOT the on-disk history): simulates a
    fresh process for restart-survival tests."""
    global _process_query_log
    with _process_lock:
        _process_query_log = None
    _OPERATOR_STORE.clear()
    with _history_lock:
        _history_cache.clear()
    from . import progress as obs_progress

    obs_progress._reset_process_state_for_tests()


class OperatorStore:
    """Bounded ring of per-query operator-metric snapshots feeding
    ``system.operators``. Entries hold a PROVIDER so the standalone
    path can defer the device sync + plan walk to scan time (the < 5%
    collect-overhead gate forbids eager harvesting); a provider
    returning None (the plan re-ran and reset its metrics, or was
    collected) drops the entry's rows."""

    def __init__(self, cap: int = 32):
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=cap)

    def record(self, job_id: str, plan_digest: str,
               provider: Callable[[], Optional[List[dict]]]) -> None:
        with self._lock:
            self._entries.append((job_id, plan_digest or "", provider))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def rows(self) -> List[dict]:
        with self._lock:
            entries = list(self._entries)
        out: List[dict] = []
        for job_id, digest, provider in entries:
            try:
                op_rows = provider()
            except Exception:  # noqa: BLE001 - observability only
                op_rows = None
            if not op_rows:
                continue
            for i, r in enumerate(op_rows):
                base = {
                    "job_id": job_id, "plan_digest": digest,
                    "stage_id": int(r.get("stage_id", 0)),
                    "op_index": i,
                    "operator": str(r.get("operator", "")),
                    "depth": int(r.get("depth", 0)),
                }
                for metric, value in sorted(
                        (r.get("metrics") or {}).items()):
                    try:
                        v = float(value)
                    except (TypeError, ValueError):
                        continue
                    out.append({**base, "metric": metric, "value": v})
        return out


_OPERATOR_STORE = OperatorStore()


def operator_store() -> OperatorStore:
    return _OPERATOR_STORE


def plan_metrics_provider(phys) -> Callable[[], Optional[List[dict]]]:
    """Deferred standalone operator harvest: a weakly-referenced plan
    plus a metrics epoch. If the plan re-ran (reset bumped the epoch)
    or was collected, the snapshot no longer describes the recorded
    query and the provider declines."""
    ref = weakref.ref(phys)
    epoch = getattr(phys, "_metrics_epoch", 0)
    cache: dict = {}

    def provide() -> Optional[List[dict]]:
        if "rows" in cache:
            return cache["rows"]
        plan = ref()
        if plan is None or getattr(plan, "_metrics_epoch", 0) != epoch:
            return None
        from .metrics import collect_plan_metrics

        rows = [{**r, "stage_id": 0}
                for r in collect_plan_metrics(plan)]
        cache["rows"] = rows
        return rows

    return provide


def stage_metrics_provider(stage_metrics: dict) -> Callable[[], List[dict]]:
    """Cluster-side operator rows: materialized once from the completed
    JobStatus's per-stage aggregation (already host data)."""
    rows: List[dict] = []
    for sid in sorted(stage_metrics or {}):
        for r in (stage_metrics[sid].get("operators") or []):
            rows.append({**r, "stage_id": sid})
    return lambda: rows


# ---------------------------------------------------------------------------
# Standalone query recorder (hooked into BallistaContext._standalone_collect)
# ---------------------------------------------------------------------------


class StandaloneQueryRecorder:
    """Times one standalone collect and records its terminal summary —
    with real profiler lanes, computed from the always-on flight
    recorder — into the shared snapshot layer. Every step is
    best-effort: observability must never fail or slow the query
    meaningfully (the < 5% warm-q1 gate covers this path, history log
    on AND off)."""

    def __init__(self, plan, session_id: str = ""):
        from ..compile import compile_stats
        from ..ingest import phase_totals
        from . import profiler as obs_profiler
        from . import progress as obs_progress

        self.job_id = f"local-{os.getpid()}-{next(_local_job_ids)}"
        self.session_id = session_id
        try:
            self.digest = obs_profiler.plan_digest(plan)
        except Exception:  # noqa: BLE001 - digest is advisory
            self.digest = ""
        self.artifact_path: Optional[str] = None
        self._phases0 = phase_totals()
        self._compile0 = compile_stats()
        self._t0 = time.time()
        # latency ledger (ledger.py): open the thread-local stamp
        # window the collect path writes planning/host_decode into;
        # _finish_inner assembles + records the full ledger
        self.ledger: Optional[dict] = None
        from . import ledger as obs_ledger

        obs_ledger.begin_collect()
        # live progress plane: register the collect with the in-flight
        # surfaces (system.tasks/stages, running system.queries rows);
        # the executed plan attaches once planned (attach_current_plan)
        self.handle = obs_progress.start_local_query(
            self.job_id, session_id, self.digest)

    def _lanes(self, wall: float, records) -> Optional[dict]:
        from ..compile import compile_stats
        from ..ingest import phase_totals
        from .export import compute_lanes

        if records is None:
            return None
        phases1 = phase_totals()
        compile1 = compile_stats()
        session = {
            "wall_seconds": wall,
            "phases": {k: phases1.get(k, 0.0) - self._phases0.get(k, 0.0)
                       for k in ("parse", "h2d")},
            "compile": {k: compile1.get(k, 0) - self._compile0.get(k, 0)
                        for k in ("compile_seconds", "trace_seconds")},
            "records": records,
        }
        return compute_lanes(session)["lanes"]

    def _build_ledger(self, wall: float, status: str, records) -> None:
        """Assemble + record this collect's latency ledger: the TLS
        stamp window (planning/host_decode) + span sums out of the SAME
        ring extraction the lanes use + the compile governor delta,
        with ``device_execute`` as the remainder — phases sum exactly
        to the wall time."""
        from . import ledger as obs_ledger
        from ..compile import compile_stats

        # always detach the window, even when recording is off — a
        # stale window would soak up stamps from later unrecorded runs
        stamps = obs_ledger.take_collect()
        if not obs_ledger.ledger_enabled():
            return
        phases = dict(stamps)
        if records:
            for phase, secs in obs_ledger.span_phase_sums(
                    records).items():
                phases[phase] = phases.get(phase, 0.0) + secs
        compile1 = compile_stats()
        comp = sum(
            float(compile1.get(k, 0.0)) - float(self._compile0.get(k, 0.0))
            for k in ("compile_seconds", "trace_seconds"))
        if comp > 0:
            phases["compile"] = phases.get("compile", 0.0) + comp
        measured = sum(phases.values())
        phases["device_execute"] = max(0.0, wall - measured)
        self.ledger = obs_ledger.build_ledger(
            self.job_id, wall, origin="standalone", status=status,
            phases=phases)
        obs_ledger.record_ledger(self.ledger)

    def finish(self, status: str, result=None, phys=None,
               error: Optional[BaseException] = None) -> None:
        try:
            self._finish_inner(status, result, phys, error)
        except Exception:  # noqa: BLE001 - never fail the query
            import logging

            logging.getLogger("ballista.systables").warning(
                "query record failed for %s", self.job_id, exc_info=True)
        finally:
            from . import progress as obs_progress

            try:
                obs_progress.finish_local_query(self.handle, status)
            except Exception:  # noqa: BLE001 - advisory
                pass

    def _finish_inner(self, status, result, phys, error) -> None:
        from . import memory as obs_memory
        from . import tracing

        wall = time.time() - self._t0
        # ONE ring extraction feeds both the lane decomposition and the
        # ledger's span-derived phases
        records = None
        try:
            if tracing.flight_recorder_enabled():
                records = tracing.ring_records(since=self._t0)
        except Exception:  # noqa: BLE001 - advisory
            records = None
        lanes = None
        try:
            lanes = self._lanes(wall, records)
        except Exception:  # noqa: BLE001 - lanes are advisory
            lanes = None
        # a cooperatively-cancelled query is terminal "cancelled", not a
        # failure; the reason (client/deadline/slow-query-kill/drain)
        # rides the record so system.queries can answer "who killed it"
        cancel_reason = None
        from ..errors import QueryCancelled

        if isinstance(error, QueryCancelled):
            status = "cancelled"
            cancel_reason = error.reason
        try:
            self._build_ledger(wall, status, records)
        except Exception:  # noqa: BLE001 - observability only
            pass
        rec = build_query_record(
            self.job_id, status, wall,
            plan_digest=self.digest,
            output_rows=(len(result) if result is not None else None),
            num_stages=1,
            started_at=self._t0,
            peak_host_bytes=obs_memory.peak_host_bytes(),
            peak_device_bytes=obs_memory.peak_device_bytes(),
            profile_artifact=self.artifact_path,
            error=error,
            cancel_reason=cancel_reason,
            lanes=lanes,
            origin="standalone",
        )
        record_query(rec)
        if phys is not None and status == "completed":
            _OPERATOR_STORE.record(self.job_id, self.digest,
                                   plan_metrics_provider(phys))
        # per-session metering (system.sessions): the standalone face
        # of the scheduler's terminal-transition accumulation; wall
        # doubles as task seconds (one in-process "task")
        from . import progress as obs_progress

        obs_progress.process_session_meter().record(
            self.session_id,
            wall_seconds=wall,
            task_seconds=wall,
            device_blocked_seconds=(lanes or {}).get(
                "device_blocked", 0.0),
            bytes_shuffled=0,
            peak_host_bytes=obs_memory.peak_host_bytes(),
            peak_device_bytes=obs_memory.peak_device_bytes(),
        )


# ---------------------------------------------------------------------------
# Snapshot builder: table name -> rows
# ---------------------------------------------------------------------------


def _query_table_records(query_log) -> List[dict]:
    """History rows (oldest, restart-surviving) + the in-memory ring;
    ring entries win on job_id collisions (they carry annotations)."""
    ring = (query_log or process_query_log()).snapshot()["queries"]
    ring_ids = {str(e.get("job_id", "")) for e in ring}
    hist = history_log()
    out: List[dict] = []
    if hist is not None:
        for rec in hist.read():
            if str(rec.get("job_id", "")) not in ring_ids:
                out.append({**rec, "origin": "history"})
    out.extend(ring)
    return out


def _queries_rows(query_log) -> List[dict]:
    rows = []
    for rec in _query_table_records(query_log):
        rows.append({
            "job_id": rec.get("job_id"),
            "plan_digest": rec.get("plan_digest"),
            "status": rec.get("status", rec.get("state")),
            "started_at": rec.get("started_at"),
            "wall_seconds": rec.get("wall_seconds"),
            "output_rows": rec.get("output_rows"),
            "num_stages": rec.get("num_stages"),
            "peak_host_bytes": rec.get("peak_host_bytes"),
            "peak_device_bytes": rec.get("peak_device_bytes"),
            "profile_artifact": rec.get("profile_artifact"),
            "error": rec.get("error"),
            "cancel_reason": rec.get("cancel_reason"),
            "origin": rec.get("origin"),
            "queue_position": rec.get("queue_position"),
        })
    return rows


def _query_lanes_rows(query_log) -> List[dict]:
    rows = []
    for rec in _query_table_records(query_log):
        lanes = rec.get("lanes")
        if not isinstance(lanes, dict):
            continue
        wall = float(rec.get("wall_seconds") or 0.0)
        for lane, secs in sorted(lanes.items()):
            try:
                s = float(secs)
            except (TypeError, ValueError):
                continue
            rows.append({
                "job_id": rec.get("job_id"),
                "plan_digest": rec.get("plan_digest"),
                "lane": lane,
                "seconds": round(s, 6),
                "fraction": round(s / wall, 4) if wall > 0 else None,
            })
    return rows


def _compile_rows() -> List[dict]:
    from ..compile.governor import governor

    return governor().entry_rows()


def _local_executor_rows() -> List[dict]:
    """Standalone ``system.executors``: one row describing the current
    process as its own single executor."""
    import socket

    from . import memory as obs_memory
    from ..ingest import pool_queue_depth

    try:
        import jax

        n_devices = len(jax.devices())
    except Exception:  # noqa: BLE001 - backend not initializable
        n_devices = 0
    gov = _gov_stats()
    return [{
        "executor_id": "standalone",
        "host": socket.gethostname(),
        "port": 0,
        "num_devices": n_devices,
        "rss_bytes": obs_memory.rss_bytes(),
        "device_bytes": obs_memory.device_bytes(),
        "inflight_tasks": 0,
        "ingest_pool_depth": pool_queue_depth(),
        "peak_host_bytes": obs_memory.peak_host_bytes(),
        "shuffle_inflight_bytes": gov["inflight_bytes"],
        "spill_bytes_total": gov["spilled_bytes_total"],
        # the current process IS the executor: its heartbeat is now
        "heartbeat_age_seconds": 0.0,
        "stale": 0,
    }]


def _gov_stats() -> dict:
    from ..distributed import spill as _spill

    return _spill.governor().stats()


def _local_tasks_rows() -> List[dict]:
    from . import progress as obs_progress

    return obs_progress.local_task_rows()


def _local_stages_rows() -> List[dict]:
    from . import progress as obs_progress

    return obs_progress.local_stage_rows()


def _session_rows() -> List[dict]:
    from . import progress as obs_progress

    rows = obs_progress.process_session_meter().rows()
    # records persisted by older builds predate the cache-attribution
    # columns; surface them as 0, not NULL
    for r in rows:
        r.setdefault("table_cache_hits", 0)
        r.setdefault("result_cache_hits", 0)
    return rows


def _cache_rows() -> List[dict]:
    from ..cache.residency import process_table_cache
    from ..cache.results import process_result_cache

    return (process_table_cache().entry_rows()
            + process_result_cache().entry_rows())


class SystemSnapshot:
    """The shared snapshot layer: one instance per serving surface (the
    process default for standalone contexts, one owned by the scheduler
    service for the cluster), all tables built from the same stores the
    other surfaces read."""

    def __init__(self, query_log=None, operators: Optional[OperatorStore] = None,
                 executors_fn: Optional[Callable[[], List[dict]]] = None,
                 tasks_fn: Optional[Callable[[], List[dict]]] = None,
                 stages_fn: Optional[Callable[[], List[dict]]] = None,
                 sessions_fn: Optional[Callable[[], List[dict]]] = None,
                 admission_fn: Optional[Callable[[], List[dict]]] = None,
                 autoscaler_fn: Optional[Callable[[], List[dict]]] = None):
        self._query_log = query_log
        self._operators = operators
        self._executors_fn = executors_fn or _local_executor_rows
        # live progress plane: the scheduler wires its JobProgressTracker
        # here; the standalone defaults read the local query handles
        self._tasks_fn = tasks_fn or _local_tasks_rows
        self._stages_fn = stages_fn or _local_stages_rows
        self._sessions_fn = sessions_fn or _session_rows
        # admission plane: the scheduler wires its controller's decision
        # ring; standalone has no gate, so the table is empty
        self._admission_fn = admission_fn or (lambda: [])
        # elastic control plane: the scheduler wires its autoscaler's
        # decision ring; standalone never autoscales, so empty
        self._autoscaler_fn = autoscaler_fn or (lambda: [])

    def table_rows(self, table: str) -> List[dict]:
        if table not in SYSTEM_SCHEMAS:
            raise KeyError(f"unknown system table {table!r}")
        if table == "system.queries":
            return _queries_rows(self._query_log)
        if table == "system.query_lanes":
            return _query_lanes_rows(self._query_log)
        if table == "system.operators":
            return (self._operators or _OPERATOR_STORE).rows()
        if table == "system.compile":
            return _compile_rows()
        if table == "system.cache":
            return _cache_rows()
        if table == "system.executors":
            return self._executors_fn()
        if table == "system.tasks":
            return self._tasks_fn()
        if table == "system.stages":
            return self._stages_fn()
        if table == "system.sessions":
            return self._sessions_fn()
        if table == "system.admission":
            return self._admission_fn()
        if table == "system.autoscaler":
            return self._autoscaler_fn()
        if table == "system.latency":
            # process-global ledger log: standalone queries land here
            # directly; on the cluster path the scheduler assembles the
            # job ledger at terminal time into its own process log
            from . import ledger as _ledger

            return _ledger.latency_rows()
        if table == "system.exemplars":
            from . import metrics as _metrics

            return _metrics.exemplar_rows()
        return settings_rows()


_PROCESS_SNAPSHOT = SystemSnapshot()


def process_snapshot() -> SystemSnapshot:
    """The standalone (current-process) snapshot."""
    return _PROCESS_SNAPSHOT


# ---------------------------------------------------------------------------
# Virtual scan source
# ---------------------------------------------------------------------------


def rows_to_batches(schema: Schema, rows: List[dict]):
    """Row dicts -> at most one ColumnBatch (None/missing values become
    NULLs via validity masks). Empty input yields no batches."""
    import numpy as np

    from ..columnar import ColumnBatch, Dictionary

    if not rows:
        return []
    n = len(rows)
    arrays: Dict[str, "np.ndarray"] = {}
    dicts: Dict[str, Dictionary] = {}
    valids: Dict[str, "np.ndarray"] = {}
    for f in schema.fields:
        raw = [r.get(f.name) for r in rows]
        valid = np.asarray([v is not None for v in raw], dtype=bool)
        if f.dtype.kind == "utf8":
            d, codes = Dictionary.encode(
                ["" if v is None else str(v) for v in raw])
            dicts[f.name] = d
            arrays[f.name] = codes
        elif f.dtype.kind == "float64":
            vals = np.zeros(n, dtype=np.float64)
            for i, v in enumerate(raw):
                if v is not None:
                    try:
                        vals[i] = float(v)
                    except (TypeError, ValueError):
                        valid[i] = False
            arrays[f.name] = vals
        else:  # integral
            vals = np.zeros(n, dtype=f.dtype.device_dtype())
            for i, v in enumerate(raw):
                if v is not None:
                    try:
                        vals[i] = int(v)
                    except (TypeError, ValueError):
                        valid[i] = False
            arrays[f.name] = vals
        if not valid.all():
            valids[f.name] = valid
    return [ColumnBatch.from_numpy(schema, arrays, dicts,
                                   validity=valids or None)]


class SystemTableSource(TableSource):
    """Scan source for one ``system.*`` table.

    Three hydration modes, resolved in order:

    - ``rows`` given (deserialized on an executor, or scheduler-planned
      raw SQL): scan the materialized snapshot as shipped;
    - ``fetcher`` given (a remote context): rows come from the
      SCHEDULER — fetched fresh at every scan / serialization, so
      cluster scans see cluster state;
    - neither (standalone): rows come from this process's snapshot,
      rebuilt at every scan so repeated collects see fresh telemetry.
    """

    def __init__(self, table: str,
                 fetcher: Optional[Callable[[], List[dict]]] = None,
                 rows: Optional[List[dict]] = None):
        if table not in SYSTEM_SCHEMAS:
            from ..errors import PlanError

            raise PlanError(f"unknown system table {table!r} "
                            f"(known: {', '.join(SYSTEM_TABLES)})")
        self.table = table
        self._fetcher = fetcher
        self._rows = rows

    def table_schema(self) -> Schema:
        return SYSTEM_SCHEMAS[self.table]

    def num_partitions(self) -> int:
        return 1

    def current_rows(self) -> List[dict]:
        if self._rows is not None:
            return self._rows
        if self._fetcher is not None:
            return self._fetcher()
        return process_snapshot().table_rows(self.table)

    def estimated_rows(self) -> Optional[int]:
        if self._rows is not None:
            return len(self._rows)
        return None  # building the snapshot just to estimate is wasteful

    def scan(self, partition: int,
             projection: Optional[Sequence[str]] = None):
        schema = self.table_schema()
        for batch in rows_to_batches(schema, self.current_rows()):
            if projection is None:
                yield batch
            else:
                sub = schema.project(projection)
                cols = [batch.column(n) for n in projection]
                yield batch.with_columns(sub, cols)

    def source_descriptor(self) -> dict:
        # serialization point (a plan shipping to the scheduler /
        # executors): materialize the rows NOW so the remote side scans
        # the snapshot the submitting surface saw
        return {
            "kind": "system",
            "path": self.table,
            "rows_json": json.dumps(self.current_rows(), default=str),
        }
