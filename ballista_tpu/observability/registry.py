"""Metric name registry: the single source of truth for metric names.

Two sections:

- :data:`OPERATOR_METRICS` — names recorded on per-operator
  ``MetricsSet`` instances (``add_counter`` / ``add_time`` /
  ``set_gauge``). ``dev/check_metric_names.py`` lints every literal
  call site in the package against this table, so a typo'd or
  undocumented metric name fails tier-1 instead of silently forking the
  namespace.
- :data:`PROCESS_METRICS` — Prometheus families the health plane
  exports (``observability/health.py`` renders ``# HELP``/``# TYPE``
  lines from here and refuses to export a family this table doesn't
  know).

Kinds: ``counter`` (monotonic int, summed on merge), ``timer``
(``elapsed_*`` seconds, summed on merge), ``gauge`` (last/max value,
max-ed on merge), ``histogram`` (Prometheus cumulative-bucket
histograms, observed through :func:`observe_histogram` in this module
so the family gate covers them too).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Tuple

log = logging.getLogger("ballista.health")

# -- per-operator MetricsSet names -------------------------------------------

OPERATOR_METRICS = {
    # recorded automatically by instrument_execute
    "output_rows": ("counter", "live rows yielded (device counts, lazy)"),
    "output_batches": ("counter", "batches yielded"),
    "elapsed_compute": ("timer", "cumulative wall time inside the "
                                 "operator's generator, children included"),
    "elapsed_self": ("timer", "derived: elapsed_compute minus children"),
    "peak_host_bytes": ("gauge", "peak tracked host bytes observed while "
                                 "this operator yielded"),
    "peak_device_bytes": ("gauge", "peak device bytes observed while this "
                                   "operator yielded"),
    # compile governor attribution
    "compile_count": ("counter", "XLA backend compiles attributed to the "
                                 "operator's governed calls"),
    "elapsed_compile": ("timer", "first-call compile (+first batch) time"),
    "persistent_cache_hits": ("counter", "disk-cache hits that skipped a "
                                         "compile"),
    # ingest phases
    "elapsed_parse": ("timer", "file -> host arrays parse time"),
    "elapsed_h2d": ("timer", "host -> device transfer time"),
    "elapsed_prefetch_wait": ("timer", "consumer time blocked on the "
                                       "prefetch queue"),
    "prefetched_batches": ("counter", "batches served through the "
                                      "prefetch queue"),
    # operator-specific
    "compact_count": ("counter", "adaptive post-filter compactions taken"),
    "expand_reruns": ("counter", "expanding-probe capacity re-runs"),
    "bytes_read": ("counter", "shuffle reader input bytes"),
    "local_reads": ("counter", "shuffle partitions read from local disk"),
    "remote_fetches": ("counter", "shuffle partitions fetched over the "
                                  "data plane"),
    "spilled_bytes": ("counter", "fetched shuffle chunk bytes diverted "
                                 "to disk past the memory budget "
                                 "watermark"),
    "bytes_written": ("counter", "partition/shuffle output bytes"),
    "elapsed_write": ("timer", "partition IPC write time"),
    "selectivity": ("gauge", "filter pass fraction"),
    "table_cache_hits": ("counter", "partition scans served from the "
                                    "device-resident table cache "
                                    "(parse + H2D skipped)"),
}

# -- Prometheus families exported by the health plane ------------------------

PROCESS_METRICS = {
    "ballista_up": ("gauge", "1 while the process serves its health plane"),
    "ballista_uptime_seconds": ("gauge", "seconds since process start"),
    "ballista_rss_bytes": ("gauge", "resident set size of the process"),
    "ballista_host_tracked_bytes": ("gauge", "host bytes currently tracked "
                                            "by category accounting"),
    "ballista_host_tracked_peak_bytes": ("gauge", "peak tracked host bytes"),
    "ballista_host_category_bytes": ("gauge", "tracked host bytes by "
                                              "category label"),
    "ballista_device_bytes": ("gauge", "device bytes in use (live arrays / "
                                       "allocator stats)"),
    "ballista_device_peak_bytes": ("gauge", "peak observed device bytes"),
    # shuffle memory governor (distributed/spill.py)
    "ballista_shuffle_inflight_bytes": ("gauge", "governed in-flight "
                                                 "shuffle buffer bytes"),
    "ballista_spill_bytes_total": ("counter", "shuffle chunk bytes "
                                              "spilled to disk past the "
                                              "budget watermark"),
    # executor
    "ballista_inflight_tasks": ("gauge", "tasks currently executing"),
    "ballista_ingest_pool_depth": ("gauge", "queued work items waiting on "
                                            "the ingest pool"),
    "ballista_tasks_completed_total": ("counter", "tasks completed"),
    "ballista_tasks_failed_total": ("counter", "tasks failed"),
    # scheduler
    "ballista_executors_live": ("gauge", "executors with an unexpired "
                                         "lease"),
    "ballista_jobs_submitted_total": ("counter", "jobs accepted by "
                                                 "ExecuteQuery"),
    "ballista_jobs_completed_total": ("counter", "jobs completed"),
    "ballista_jobs_failed_total": ("counter", "jobs failed"),
    "ballista_jobs_cancelled_total": ("counter", "jobs cooperatively "
                                                 "cancelled (client, "
                                                 "deadline, slow-query "
                                                 "kill, drain)"),
    "ballista_tasks_cancelled_total": ("counter", "task attempts aborted "
                                                  "by a cancel token "
                                                  "(job cancel or "
                                                  "executor drain)"),
    "ballista_tasks_dispatched_total": ("counter", "task definitions "
                                                   "handed to executors"),
    "ballista_ready_queue_depth": ("gauge", "tasks in the ready queue"),
    # live progress plane (scheduler)
    "ballista_tasks_running": ("gauge", "tasks currently running across "
                                        "all live jobs (progress "
                                        "tracker view)"),
    "ballista_job_progress_fraction": ("gauge", "per-live-job completion "
                                                "fraction 0..1 (label "
                                                "job=...)"),
    "ballista_slow_queries_total": ("counter", "completed queries over "
                                               "BALLISTA_SLOW_QUERY_SECS"),
    # scheduler-side aggregation of executor heartbeat gauges
    "ballista_executor_rss_bytes": ("gauge", "per-executor RSS from the "
                                             "last heartbeat"),
    "ballista_executor_device_bytes": ("gauge", "per-executor device bytes "
                                                "from the last heartbeat"),
    "ballista_executor_inflight_tasks": ("gauge", "per-executor inflight "
                                                  "tasks"),
    "ballista_executor_ingest_pool_depth": ("gauge", "per-executor ingest "
                                                     "pool queue depth"),
    "ballista_executor_peak_host_bytes": ("gauge", "per-executor peak "
                                                   "tracked host bytes"),
    # distributed profiler (scheduler)
    "ballista_query_lane_seconds": ("histogram",
                                    "per-query named wall-time lane "
                                    "seconds (label lane=...), observed "
                                    "when a merged profile artifact is "
                                    "built for a job"),
    "ballista_stage_seconds": ("histogram",
                               "summed task seconds per completed stage "
                               "(label stage=...), observed at job "
                               "completion"),
    # always-on latency ledger (observability/ledger.py + metrics.py):
    # SLO histograms observed once per terminal query; each bucket
    # keeps its most recent worst-offender exemplar (system.exemplars)
    "ballista_latency_seconds": ("histogram",
                                 "end-to-end query wall seconds, "
                                 "observed from the per-query latency "
                                 "ledger at terminal time"),
    "ballista_latency_phase_seconds": ("histogram",
                                       "per-query ledger phase seconds "
                                       "(label phase=admission_wait|"
                                       "queue_wait|planning|compile|"
                                       "device_execute|...)"),
    # admission plane (scheduler; distributed/admission.py)
    "ballista_admission_queue_depth": ("gauge", "submissions waiting in "
                                                "the admission queue"),
    "ballista_admission_admitted_total": ("counter", "submissions "
                                                     "admitted (at the "
                                                     "gate or from the "
                                                     "queue)"),
    "ballista_admission_queued_total": ("counter", "submissions held in "
                                                   "the admission queue "
                                                   "at the gate"),
    "ballista_admission_sheds_total": ("counter", "submissions shed with "
                                                  "a retryable error "
                                                  "(budget, queue-full, "
                                                  "queue-timeout, "
                                                  "draining)"),
    "ballista_admission_queue_wait_seconds": ("histogram",
                                              "time submissions spent in "
                                              "the admission queue "
                                              "(label outcome=admitted|"
                                              "shed)"),
    # warm-path cache tiers (ballista_tpu/cache/)
    "ballista_cache_table_hits_total": ("counter", "partition scans served "
                                                   "from the device-"
                                                   "resident table cache"),
    "ballista_cache_table_misses_total": ("counter", "partition scans that "
                                                     "found no resident "
                                                     "entry"),
    "ballista_cache_table_fills_total": ("counter", "partitions pinned "
                                                    "into the table "
                                                    "cache"),
    "ballista_cache_table_evictions_total": ("counter", "pinned partitions "
                                                        "evicted for "
                                                        "budget"),
    "ballista_cache_table_resident_bytes": ("gauge", "device bytes pinned "
                                                     "by the table cache "
                                                     "governor"),
    "ballista_cache_result_hits_total": ("counter", "collects served from "
                                                    "the plan-fingerprint "
                                                    "result cache"),
    "ballista_cache_result_misses_total": ("counter", "result-cache "
                                                      "lookups that "
                                                      "executed"),
    "ballista_cache_result_bytes": ("gauge", "host bytes held by cached "
                                             "result sets"),
    "ballista_cache_donated_buffers_total": ("counter", "governed calls "
                                                        "that donated a "
                                                        "transient batch's "
                                                        "device buffers"),
    "ballista_cache_donated_bytes_total": ("counter", "device bytes "
                                                      "donated through "
                                                      "fused stages"),
    # autoscaler (scheduler; distributed/controlplane/autoscaler.py)
    "ballista_autoscale_target_executors": ("gauge", "fleet size the "
                                                     "autoscaler is "
                                                     "steering toward"),
    "ballista_autoscale_ups_total": ("counter", "scale-up decisions "
                                                "acted on (executor "
                                                "spawned)"),
    "ballista_autoscale_downs_total": ("counter", "scale-down decisions "
                                                  "acted on (executor "
                                                  "drained)"),
}

# -- process-level histograms -------------------------------------------------
# Cumulative-bucket histograms the health plane renders as
# ``<family>_bucket{le=...}`` / ``_sum`` / ``_count``. One fixed bucket
# ladder serves every family (they all measure seconds).

HISTOGRAM_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0)

_hist_lock = threading.Lock()
# family -> labelkey (sorted items tuple) -> [per-bucket counts, sum, n]
_histograms: Dict[str, Dict[tuple, list]] = {}


def observe_histogram(family: str, labels: Dict[str, str],
                      value: float) -> None:
    """Record one observation. The family must be registered in
    PROCESS_METRICS with kind ``histogram`` — same gate the renderer
    applies to counters/gauges."""
    kind = PROCESS_METRICS.get(family, (None,))[0]
    if kind != "histogram":
        log.warning("dropping observation for unregistered histogram "
                    "family %r (add it to observability/registry.py)",
                    family)
        return
    key = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
    v = float(value)
    with _hist_lock:
        cells = _histograms.setdefault(family, {})
        cell = cells.get(key)
        if cell is None:
            cell = cells[key] = [[0] * len(HISTOGRAM_BUCKETS), 0.0, 0]
        counts, _, _ = cell
        for i, le in enumerate(HISTOGRAM_BUCKETS):
            if v <= le:
                counts[i] += 1
        cell[1] += v
        cell[2] += 1


def histogram_snapshot() -> Dict[str, List[Tuple[dict, list, float, int]]]:
    """{family: [(labels, bucket counts, sum, count), ...]} — consumed
    by the health plane's renderer."""
    out: Dict[str, List[Tuple[dict, list, float, int]]] = {}
    with _hist_lock:
        for family, cells in _histograms.items():
            rows = []
            for key, (counts, total, n) in sorted(cells.items()):
                rows.append((dict(key), list(counts), total, n))
            out[family] = rows
    return out


def reset_histograms() -> None:
    with _hist_lock:
        _histograms.clear()


def operator_metric_names() -> set:
    return set(OPERATOR_METRICS)


def process_metric_names() -> set:
    return set(PROCESS_METRICS)
