"""Host/device memory accounting for queries and the health plane.

Host side: instrumented allocation sites (scan parse buffers,
dictionaries, shuffle IPC buffers, cache occupancy) call
:func:`record_host_bytes` / :func:`release_host_bytes` with a category
tag, so the engine can say *what kind* of host memory a query holds —
``rss`` alone can't distinguish a dictionary explosion from shuffle
buffering. Tracking is byte-counting only (no allocator hooks): cheap
ints under a small lock, updated at batch/file granularity, never per
row.

Device side: JAX exposes either allocator stats
(``device.memory_stats()``, real accelerators) or live array sizes
(``jax.live_arrays()``, the CPU backend). Sampling live arrays walks a
global list, so :func:`device_bytes` rate-limits real samples
(``_SAMPLE_MIN_INTERVAL``) and returns the cached value in between —
callers on the batch path (``instrument_execute``) get a cheap read,
and the peak is tracked across whatever samples happen.

Peaks are monotone by construction (``max`` accumulation); per-query
code that wants a fresh baseline calls :func:`reset_peaks`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

_lock = threading.Lock()
_by_category: Dict[str, int] = {}
_peak_by_category: Dict[str, int] = {}
_current_total = 0
_peak_total = 0

# device sampling state
_SAMPLE_MIN_INTERVAL = 0.25  # seconds between real live-array walks
_device_cached = 0
_device_sampled_at = 0.0
_device_peak = 0


def record_host_bytes(category: str, nbytes: int) -> None:
    """Account ``nbytes`` of host memory under ``category`` (one of
    ``batches``, ``dictionaries``, ``shuffle``, ``cache`` by
    convention; free-form tags are fine)."""
    global _current_total, _peak_total
    n = int(nbytes)
    if n <= 0:
        return
    with _lock:
        cur = _by_category.get(category, 0) + n
        _by_category[category] = cur
        if cur > _peak_by_category.get(category, 0):
            _peak_by_category[category] = cur
        _current_total += n
        if _current_total > _peak_total:
            _peak_total = _current_total


def release_host_bytes(category: str, nbytes: int) -> None:
    global _current_total
    n = int(nbytes)
    if n <= 0:
        return
    with _lock:
        cur = _by_category.get(category, 0)
        taken = min(cur, n)  # never go negative on double-release
        _by_category[category] = cur - taken
        _current_total -= taken


class track_host_bytes:
    """Context manager for TRANSIENT host buffers: records on entry,
    releases on exit — the peak still captures the high-water mark."""

    __slots__ = ("category", "nbytes")

    def __init__(self, category: str, nbytes: int):
        self.category = category
        self.nbytes = int(nbytes)

    def __enter__(self):
        record_host_bytes(self.category, self.nbytes)
        return self

    def __exit__(self, *exc):
        release_host_bytes(self.category, self.nbytes)
        return False


def current_host_bytes() -> int:
    return _current_total


def peak_host_bytes() -> int:
    return _peak_total


def host_memory_snapshot() -> dict:
    with _lock:
        return {
            "current_bytes": _current_total,
            "peak_bytes": _peak_total,
            "by_category": dict(_by_category),
            "peak_by_category": dict(_peak_by_category),
        }


def _sample_device_bytes() -> Optional[int]:
    """One real device-memory sample, or None when JAX is unusable."""
    try:
        import jax

        total = 0
        saw_stats = False
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 - backend without stats
                stats = None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                saw_stats = True
        if saw_stats:
            return total
        # CPU backend: no allocator stats — sum live array sizes
        return int(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
    except Exception:  # noqa: BLE001 - no jax / backend not initialized
        return None


def device_bytes(refresh: bool = False) -> int:
    """Device bytes in use. Rate-limited: a real sample happens at most
    every ``_SAMPLE_MIN_INTERVAL`` seconds unless ``refresh=True``; the
    cached value is returned in between (hot-path callers must stay
    cheap)."""
    global _device_cached, _device_sampled_at, _device_peak
    now = time.monotonic()
    if refresh or now - _device_sampled_at >= _SAMPLE_MIN_INTERVAL:
        _device_sampled_at = now  # stamp even on failure: no retry storm
        sampled = _sample_device_bytes()
        if sampled is not None:
            _device_cached = sampled
            if sampled > _device_peak:
                _device_peak = sampled
    return _device_cached


def peak_device_bytes(refresh: bool = False) -> int:
    if refresh:
        device_bytes(refresh=True)
    return _device_peak


def rss_bytes() -> int:
    """CURRENT resident set size of this process. Gauges (heartbeats,
    /metrics) need the live value — a process that spiked and freed
    must read low again. Linux: /proc/self/status VmRSS; elsewhere the
    peak (:func:`peak_rss_bytes`) is the best available approximation."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return peak_rss_bytes()


def peak_rss_bytes() -> int:
    """Lifetime peak resident set size (ru_maxrss is KB on Linux,
    bytes on macOS) — the bench trajectory metric."""
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss if sys.platform == "darwin" else rss * 1024)
    except Exception:  # noqa: BLE001 - platforms without resource
        return 0


def reset_peaks() -> None:
    """Re-baseline the peak trackers (per-query profiling / tests).
    Current occupancy is kept — peaks restart from it."""
    global _peak_total, _device_peak
    with _lock:
        _peak_total = _current_total
        for k, v in _by_category.items():
            _peak_by_category[k] = v
    _device_peak = device_bytes(refresh=True)


def memory_snapshot() -> dict:
    """Full snapshot for artifacts / the health plane."""
    out = host_memory_snapshot()
    out["device_bytes"] = device_bytes()
    out["peak_device_bytes"] = _device_peak
    out["rss_bytes"] = rss_bytes()
    return out
