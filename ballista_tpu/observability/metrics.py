"""Lock-cheap per-operator metrics.

Design constraints (why this doesn't look like a classic metrics
registry):

- **No device syncs on the hot path.** ``batch.num_rows`` is a device
  scalar; blocking on it per batch would serialize host against device
  (the engine spends real effort avoiding exactly that — see
  physical/base.py's deferred-sync compaction). ``record_output_batch``
  therefore only APPENDS the scalar; ``values()`` resolves all pending
  scalars in one ``jax.device_get`` at read time, when the query is done
  and the transfer is effectively free.
- **No locks.** Counters are plain Python ints mutated under the GIL.
  Partitions of one operator instance may run on different executor
  worker threads; a lost increment under that interleaving skews a
  heuristic display value, never correctness — same benign-race policy
  as the adaptive compaction counters in physical/base.py.
- **Zero per-operator boilerplate.** ``PhysicalPlan.__init_subclass__``
  wraps every ``execute`` override with :func:`instrument_execute`, so
  every operator (including future ones) records ``output_rows``,
  ``output_batches`` and ``elapsed_compute`` without touching its code.

``elapsed_compute`` is CUMULATIVE wall time spent inside the operator's
generator, children included (fused pipeline chains attribute the whole
chain to the outermost op). Self-time is derived at display time as
``own - sum(children)`` — see :func:`collect_plan_metrics`.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Dict, Iterable, List, Optional

# -- global enablement -------------------------------------------------------

# metrics default ON: the per-batch cost is two perf_counter() calls and
# a list append (gated < 5% on q1 by tests/test_observability.py).
# BALLISTA_METRICS=0 turns collection off; EXPLAIN ANALYZE forces it back
# on dynamically for the plans it executes.
_DISABLED = os.environ.get("BALLISTA_METRICS", "1").lower() in (
    "0", "off", "false")
_FORCED = 0  # EXPLAIN ANALYZE nesting depth (benign race across threads)


def metrics_enabled() -> bool:
    return _FORCED > 0 or not _DISABLED


def reconfigure() -> None:
    """Re-read BALLISTA_METRICS (tests flip the env mid-process)."""
    global _DISABLED
    _DISABLED = os.environ.get("BALLISTA_METRICS", "1").lower() in (
        "0", "off", "false")


class force_metrics:
    """Context manager: collect metrics even when globally disabled
    (EXPLAIN ANALYZE must always measure the plan it executes)."""

    def __enter__(self):
        global _FORCED
        _FORCED += 1
        return self

    def __exit__(self, *exc):
        global _FORCED
        _FORCED -= 1
        return False


# -- MetricsSet ---------------------------------------------------------------


class MetricsSet:
    """Per-operator metric store: counters (ints), timers (seconds),
    gauges (last/max value), plus a pending list of device row-count
    scalars resolved lazily at read time."""

    __slots__ = ("_counters", "_timers", "_gauges", "_pending_rows",
                 "_rows_floor")

    def __init__(self):
        self._counters: Dict[str, int] = {}
        self._timers: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._pending_rows: List = []
        self._rows_floor = 0

    # recording (hot path) --------------------------------------------------

    def add_counter(self, name: str, value: int = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + value

    def add_time(self, name: str, secs: float) -> None:
        self._timers[name] = self._timers.get(name, 0.0) + secs

    def set_gauge(self, name: str, value: float) -> None:
        # always float: Python type is the kind discriminator downstream
        # (serde encodes float -> gauge oneof, int -> counter; merge
        # max-es floats and sums ints) — an integral gauge must not
        # silently turn into a summed counter on the wire
        self._gauges[name] = float(value)

    def reset(self) -> None:
        self._counters.clear()
        self._timers.clear()
        self._gauges.clear()
        self._pending_rows.clear()
        self._rows_floor = 0

    def record_output_batch(self, batch) -> None:
        """Append the batch's (device-scalar) live row count without
        syncing; bump the batch counter."""
        self._counters["output_batches"] = \
            self._counters.get("output_batches", 0) + 1
        self._pending_rows.append(batch.num_rows)

    # reading ---------------------------------------------------------------

    def _resolve_rows(self) -> None:
        if not self._pending_rows:
            return
        # NOTE: a snapshot_rows() racing this window (pending swapped
        # out, sum not yet committed) computes a transiently LOW total;
        # the _rows_floor clamp there keeps the sampled value monotone
        pending, self._pending_rows = self._pending_rows, []
        try:
            import jax

            from .tracing import trace_span

            with trace_span("device.block", site="metrics.rows",
                            n=len(pending)):
                counts = jax.device_get(pending)  # one transfer for all
        except Exception:  # noqa: BLE001 - already-host scalars
            counts = pending
        self._counters["output_rows"] = (
            self._counters.get("output_rows", 0)
            + int(sum(int(c) for c in counts))
        )

    def snapshot_rows(self) -> int:
        """Non-destructive, non-blocking row count for the live
        progress sampler: the committed counter plus the pending device
        scalars that are ALREADY resolved. Never blocks on in-flight
        compute (unready scalars are skipped) and never clears the
        pending list, so ``values()`` keeps the authoritative
        accounting. Monotone by clamp: a read racing ``_resolve_rows``
        (pending swapped out, counter not yet bumped) would compute a
        transiently low total, so the last returned value is a floor."""
        total = int(self._counters.get("output_rows", 0))
        ready = []
        for p in list(self._pending_rows):
            is_ready = getattr(p, "is_ready", None)
            try:
                if is_ready is None or is_ready():
                    ready.append(p)
            except Exception:  # noqa: BLE001 - deleted buffer etc.
                continue
        if ready:
            try:
                import jax

                from .tracing import trace_span

                # ready scalars only — the transfer is tiny, but it IS
                # a sync; spanning keeps the lane sum exact
                with trace_span("device.block", site="metrics.rows",
                                n=len(ready)):
                    counts = jax.device_get(ready)
            except Exception:  # noqa: BLE001 - already-host scalars
                counts = ready
            try:
                total += int(sum(int(c) for c in counts))
            except Exception:  # noqa: BLE001 - advisory only
                pass
        total = max(total, self._rows_floor)
        self._rows_floor = total
        return total

    def values(self) -> Dict[str, float]:
        """Resolved snapshot: counters as ints, timers/gauges as floats.
        Timer names keep their ``elapsed_`` prefix so aggregation can
        tell the kinds apart without a side table."""
        self._resolve_rows()
        out: Dict[str, float] = dict(self._counters)
        out.update(self._timers)
        out.update(self._gauges)
        return out

    def value(self, name: str, default=None):
        return self.values().get(name, default)

    def is_empty(self) -> bool:
        return not (self._counters or self._timers or self._gauges
                    or self._pending_rows)

    def summary(self) -> str:
        """Compact ``k=v`` rendering for plan annotation (EXPLAIN
        ANALYZE), stable order: rows, batches, timers, the rest."""
        vals = self.values()
        parts = []
        for key in ("output_rows", "output_batches"):
            if key in vals:
                parts.append(f"{key}={int(vals.pop(key))}")
        for key in sorted(k for k in vals if k.startswith("elapsed_")):
            parts.append(f"{key}={_fmt_secs(vals.pop(key))}")
        for key in sorted(vals):
            v = vals[key]
            parts.append(f"{key}={int(v) if float(v).is_integer() else v}")
        return ", ".join(parts)


def _fmt_secs(secs: float) -> str:
    if secs >= 1.0:
        return f"{secs:.3f}s"
    if secs >= 0.001:
        return f"{secs * 1e3:.3f}ms"
    return f"{secs * 1e6:.1f}µs"


# -- execute() instrumentation ------------------------------------------------


def instrument_execute(fn):
    """Wrap a PhysicalPlan.execute generator so each call records
    output rows/batches and cumulative wall time on the operator's
    MetricsSet. Applied automatically by PhysicalPlan.__init_subclass__;
    idempotent via the ``_obs_wrapped`` marker."""
    if getattr(fn, "_obs_wrapped", False):
        return fn

    @functools.wraps(fn)
    def execute(self, partition: int):
        if not metrics_enabled():
            yield from fn(self, partition)
            return
        from . import memory as obs_memory

        m = self.metrics()
        it = fn(self, partition)
        perf = time.perf_counter
        host_peak = obs_memory.current_host_bytes
        dev_peak = obs_memory.device_bytes
        acc = 0.0
        try:
            while True:
                t0 = perf()
                try:
                    batch = next(it)
                except StopIteration:
                    acc += perf() - t0
                    return
                acc += perf() - t0
                m.record_output_batch(batch)
                # monotone per-operator memory high-water marks: cheap
                # reads of the process trackers (device sampling is
                # rate-limited inside device_bytes)
                g = m._gauges
                hb = host_peak()
                if hb > g.get("peak_host_bytes", 0.0):
                    g["peak_host_bytes"] = float(hb)
                db = dev_peak()
                if db > g.get("peak_device_bytes", 0.0):
                    g["peak_device_bytes"] = float(db)
                yield batch
        finally:
            # finally (not loop exit): a consumer abandoning the stream
            # early (LimitExec) must still flush accrued time
            m.add_time("elapsed_compute", acc)

    execute._obs_wrapped = True
    return execute


# -- harvesting / aggregation -------------------------------------------------


def resolve_all_pending(metrics_sets: Iterable[MetricsSet]) -> None:
    """Resolve every set's pending device row counts in ONE
    ``jax.device_get`` — per-set resolution pays a separate transfer
    (and dispatch-queue sync) per operator, which is what the < 5%
    overhead gate would otherwise spend its budget on."""
    sets = [m for m in metrics_sets if m._pending_rows]
    if not sets:
        return
    pending: List = []
    spans: List[int] = []
    for m in sets:
        spans.append(len(m._pending_rows))
        pending.extend(m._pending_rows)
        m._pending_rows = []
    try:
        import jax

        from .tracing import trace_span

        with trace_span("device.block", site="metrics.rows",
                        n=len(pending)):
            counts = jax.device_get(pending)
    except Exception:  # noqa: BLE001 - already-host scalars
        counts = pending
    i = 0
    for m, n in zip(sets, spans):
        m._counters["output_rows"] = (
            m._counters.get("output_rows", 0)
            + int(sum(int(c) for c in counts[i:i + n]))
        )
        i += n


def _plan_nodes(plan) -> List:
    nodes: List = []

    def gather(node):
        nodes.append(node)
        for c in node.children():
            gather(c)

    gather(plan)
    return nodes


def resolve_plan_pending(plan) -> None:
    """Resolve every operator's pending device row counts in one
    batched transfer. Call before rendering (``pretty_metrics``), else
    each operator's ``values()`` pays its own device_get."""
    resolve_all_pending(n.metrics() for n in _plan_nodes(plan))


def reset_plan_metrics(plan) -> None:
    """Zero every operator's MetricsSet. EXPLAIN ANALYZE re-runs a
    possibly cached plan and must report THIS run, not the lifetime
    accumulation. The root's metrics EPOCH is bumped so deferred
    harvesters (system.operators' lazy snapshot of a past query) can
    tell that their values were clobbered by a newer run."""
    for n in _plan_nodes(plan):
        n.metrics().reset()
    try:
        plan._metrics_epoch = getattr(plan, "_metrics_epoch", 0) + 1
    except AttributeError:
        pass  # slotted plan node: epoch tracking degrades gracefully


def _fused_members(node) -> list:
    """Operators a fused stage absorbed (physical/fusion.py): the
    aggregate/distinct stage's pipeline chain, or a join's fused probe
    chain. Outermost first, matching plan-render order."""
    chain = list(getattr(node, "chain", ()) or ())
    chain += list(getattr(node, "probe_chain", ()) or ())
    return list(reversed(chain))


def collect_plan_metrics(plan) -> List[dict]:
    """Pre-order walk of a physical plan -> one row per operator:
    ``{"operator", "depth", "metrics"}``. ``elapsed_compute`` is
    cumulative (subtree); a derived ``elapsed_self`` (own minus direct
    children) is added when timers are present so hot operators stand
    out without double counting."""
    resolve_plan_pending(plan)

    rows: List[dict] = []

    def walk(node, depth: int) -> float:
        vals = node.metrics().values()
        row = {"operator": node.display(), "depth": depth, "metrics": vals}
        rows.append(row)
        # whole-stage fusion: operators absorbed into this node's traced
        # program still get a row (marked), so metric consumers see the
        # full logical plan; their work is attributed to the host row,
        # same convention as pipeline-chain members
        for member in _fused_members(node):
            rows.append({"operator": member.display() + " [fused]",
                         "depth": depth + 1, "metrics": {}})
        child_time = 0.0
        for c in node.children():
            child_time += walk(c, depth + 1)
        own = vals.get("elapsed_compute", 0.0)
        if own:
            vals["elapsed_self"] = max(own - child_time, 0.0)
        # an operator fused into a pipeline chain records no time of its
        # own; its subtree's cumulative time is still its children's —
        # returning 0 here would misattribute grandchild time to the
        # chain head's elapsed_self
        return max(own, child_time)

    walk(plan, 0)
    return rows


def merge_operator_metrics(per_task: Iterable[List[dict]]) -> List[dict]:
    """Merge several tasks' collect_plan_metrics outputs (tasks of one
    stage share an identical plan shape, so rows align positionally;
    a shape mismatch falls back to merging the common prefix).
    Counters and ``elapsed_*`` timers sum; other gauges keep the max."""
    merged: List[dict] = []
    for rows in per_task:
        for i, row in enumerate(rows):
            if i >= len(merged):
                merged.append({"operator": row["operator"],
                               "depth": row["depth"],
                               "metrics": dict(row["metrics"])})
                continue
            tgt = merged[i]["metrics"]
            for k, v in row["metrics"].items():
                if k.startswith("elapsed_") or not isinstance(v, float):
                    tgt[k] = tgt.get(k, 0) + v
                else:
                    tgt[k] = max(tgt.get(k, v), v)
    return merged


def snapshot_plan_metrics(phys) -> "QueryMetrics":
    """Standalone-mode QueryMetrics off an executed physical plan: one
    synthetic stage 0 (there is no stage decomposition in-process).
    Standalone DataFrames cache their physical plan across ``collect()``
    calls, but the collect path resets the plan's MetricsSets before
    each run, so the snapshot covers the most recent collect only."""
    ops = collect_plan_metrics(phys)
    total = ops[0]["metrics"].get("elapsed_compute", 0.0) if ops else 0.0
    return QueryMetrics({0: {"num_tasks": 1, "elapsed_total": total,
                             "operators": ops}})


class QueryMetrics:
    """Per-query stage/operator metric breakdown returned by
    ``BallistaContext.last_query_metrics()``.

    ``stages`` maps stage_id -> {"num_tasks": int, "elapsed_total":
    float, "operators": [{"operator", "depth", "metrics"}, ...]}.
    Standalone queries report a single stage 0.
    """

    def __init__(self, stages: Dict[int, dict]):
        self.stages = dict(stages)

    def stage_ids(self) -> List[int]:
        return sorted(self.stages)

    def operators(self) -> List[dict]:
        """All operator rows across stages, tagged with their stage."""
        out = []
        for sid in self.stage_ids():
            for row in self.stages[sid].get("operators", []):
                out.append({**row, "stage_id": sid})
        return out

    def total_output_rows(self) -> int:
        """Output rows of the final stage's root operator. The last
        stage (highest id — DistributedPlanner appends the root stage
        last) produces the query result; earlier stages feed shuffles,
        so summing every stage's root would count intermediates."""
        for sid in reversed(self.stage_ids()):
            ops = self.stages[sid].get("operators")
            if ops:
                return int(ops[0]["metrics"].get("output_rows", 0))
        return 0

    def pretty(self) -> str:
        lines = []
        for sid in self.stage_ids():
            st = self.stages[sid]
            head = f"Stage {sid} [tasks={st.get('num_tasks', 1)}"
            if st.get("elapsed_total"):
                head += f", elapsed={_fmt_secs(st['elapsed_total'])}"
            lines.append(head + "]")
            for row in st.get("operators", []):
                ms = MetricsSet()
                for k, v in row["metrics"].items():
                    if k.startswith("elapsed_"):
                        ms.add_time(k, v)
                    elif isinstance(v, float):  # type is the kind
                        ms.set_gauge(k, v)
                    else:
                        ms.add_counter(k, int(v))
                ann = ms.summary()
                lines.append("  " * (row["depth"] + 1) + row["operator"]
                             + (f"  [{ann}]" if ann else ""))
        return "\n".join(lines) + "\n"

    def __repr__(self) -> str:
        n_ops = sum(len(s.get("operators", []))
                    for s in self.stages.values())
        return (f"QueryMetrics(stages={self.stage_ids()}, "
                f"operators={n_ops})")


# -- SLO latency histograms with exemplars ------------------------------------
# Fed once per terminal query from the latency ledger
# (observability/ledger.py): a fixed-bucket end-to-end histogram plus a
# per-phase family, where every (family, labels, bucket) cell retains
# the job id + full ledger of its MOST RECENT observation — so the top
# occupied bucket (the p99 tail) always carries a concrete exemplar
# query instead of an anonymous count. Surfaced as system.exemplars.

SLO_LATENCY_FAMILY = "ballista_latency_seconds"
SLO_PHASE_FAMILY = "ballista_latency_phase_seconds"

import threading as _threading  # noqa: E402 - section-local dependency

_exemplar_lock = _threading.Lock()
# (family, labels-key tuple, bucket index) -> exemplar dict. Bucket
# index is the first HISTOGRAM_BUCKETS edge >= value; len(buckets) is
# the +Inf overflow bucket.
_exemplars: Dict[tuple, dict] = {}


def _bucket_index(value: float) -> int:
    from .registry import HISTOGRAM_BUCKETS

    for i, le in enumerate(HISTOGRAM_BUCKETS):
        if value <= le:
            return i
    return len(HISTOGRAM_BUCKETS)


def _bucket_le(index: int) -> float:
    from .registry import HISTOGRAM_BUCKETS

    if index >= len(HISTOGRAM_BUCKETS):
        return float("inf")
    return HISTOGRAM_BUCKETS[index]


def _note_exemplar(family: str, labels: Dict[str, str], value: float,
                   ledger: dict) -> None:
    key = (family,
           tuple(sorted((str(k), str(v)) for k, v in labels.items())),
           _bucket_index(value))
    with _exemplar_lock:
        _exemplars[key] = {
            "job_id": ledger.get("job_id"),
            "seconds": round(float(value), 6),
            "wall_seconds": float(ledger.get("wall_seconds", 0.0)),
            "ledger": ledger,
        }


def observe_query_ledger(ledger: dict) -> None:
    """Observe one query's ledger into the SLO families: end-to-end
    wall + every phase (zeros included, so ``_count`` is queries per
    cell and phase fractions divide cleanly)."""
    from .registry import observe_histogram

    wall = float(ledger.get("wall_seconds", 0.0))
    observe_histogram(SLO_LATENCY_FAMILY, {}, wall)
    _note_exemplar(SLO_LATENCY_FAMILY, {}, wall, ledger)
    for phase, secs in (ledger.get("phases") or {}).items():
        labels = {"phase": phase}
        observe_histogram(SLO_PHASE_FAMILY, labels, float(secs))
        _note_exemplar(SLO_PHASE_FAMILY, labels, float(secs), ledger)


def exemplar_rows() -> List[dict]:
    """``system.exemplars``: one row per retained (family, labels,
    bucket) exemplar, widest buckets last. ``ledger_json`` carries the
    exemplar query's FULL ledger."""
    import json

    with _exemplar_lock:
        snap = dict(_exemplars)
    rows = []
    for (family, labels_key, idx), ex in sorted(
            snap.items(), key=lambda kv: (kv[0][0], kv[0][1], kv[0][2])):
        rows.append({
            "family": family,
            "phase": dict(labels_key).get("phase", ""),
            "bucket_le": _bucket_le(idx),
            "job_id": ex.get("job_id"),
            "seconds": ex.get("seconds"),
            "wall_seconds": ex.get("wall_seconds"),
            "ledger_json": json.dumps(ex.get("ledger") or {},
                                      sort_keys=True),
        })
    return rows


def reset_latency_exemplars() -> None:
    """Test hook: drop retained exemplars (histogram cells are cleared
    separately via registry.reset_histograms)."""
    with _exemplar_lock:
        _exemplars.clear()
