"""Live query progress & per-session resource metering plane.

Everything the observability stack built before this module is
*post-hoc*: the profiler, the ``system.*`` tables and the lane
histograms describe queries that already finished. This module answers
the live questions — "what is job X doing right now, how far along is
it, and how much has this session consumed?" — the data plane the
multi-tenant serving work (ROADMAP item 5: admission control,
per-session quotas) reads its signals from.

Three cooperating pieces, ONE snapshot shape on both execution paths:

- **Executor-side sampling.** Each running task's operator
  ``MetricsSet`` is sampled on a bounded cadence
  (``BALLISTA_PROGRESS_INTERVAL_SECS``) without forcing a device sync
  (:meth:`MetricsSet.snapshot_rows` resolves only already-ready
  scalars), and compact ``TaskProgress`` records piggyback on the
  existing ``PollWork`` heartbeat. Reports are best-effort by
  contract: a dropped, delayed or failed report must never affect
  scheduling or results (the ``scheduler.progress_report`` fault point
  pins that in the chaos sweep).

- **The scheduler's live job model.** :class:`JobProgressTracker`
  folds progress samples and task-state transitions into per-stage
  completion fractions (observed rows vs the task's own
  ``estimated_rows()`` leaf estimate — exact for shuffle readers,
  file-size heuristics for scans), a rate-based ETA, and
  running/queued/completed task counts. Served through the extended
  ``GetJobStatus`` RPC, ``/debug/jobs[/<job_id>]``, Prometheus gauges
  (``ballista_job_progress_fraction``, ``ballista_tasks_running``) and
  the live ``system.tasks`` / ``system.stages`` tables. Job fractions
  are clamped monotone non-decreasing and reach exactly 1.0 at the
  completed terminal transition.

- **Per-session metering.** :class:`SessionMeter` accumulates, per
  client session (``session.id`` travels with the query settings),
  queries run, wall/task seconds, device-blocked seconds, shuffle
  bytes and peak host/device bytes — fed from the same
  completed-task stream at the job's terminal transition (standalone
  collects feed it from :class:`StandaloneQueryRecorder`). Durable
  next to the query-history log (``sessions.json`` under
  ``BALLISTA_QUERY_LOG_DIR``), served as ``system.sessions``.

Standalone parity: every standalone collect registers a
:class:`LocalQueryHandle`; a sampler thread over the executing plan's
``MetricsSet`` drives ``df.collect(on_progress=cb)`` and the same
handle feeds ``system.tasks`` / ``system.stages`` / in-flight
``system.queries`` rows, so both paths report through one shape.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("ballista.progress")

# the ONE snapshot shape (pinned by tests/test_progress.py)
JOB_PROGRESS_KEYS = frozenset({
    "job_id", "status", "fraction", "eta_seconds", "wall_seconds",
    "tasks_total", "tasks_running", "tasks_queued", "tasks_completed",
    "stages",
})
STAGE_PROGRESS_KEYS = frozenset({
    "stage_id", "tasks_total", "tasks_running", "tasks_completed",
    "fraction", "eta_seconds", "rows_so_far", "bytes_so_far",
})

# a running task never reports more than this fraction complete — only
# its completion report can close the gap (keeps fractions honest under
# row-estimate error and guarantees 1.0 is reached exactly once)
RUNNING_TASK_FRACTION_CAP = 0.95


def progress_interval_secs() -> Optional[float]:
    """``BALLISTA_PROGRESS_INTERVAL_SECS``: cadence of executor
    progress piggybacks and ambient standalone sampling. Default 1.0;
    ``0``/``off`` disables the plane (collect(on_progress=) still
    samples, at its own default cadence)."""
    v = os.environ.get("BALLISTA_PROGRESS_INTERVAL_SECS", "1.0")
    if v.lower() in ("off", "false", "no", ""):
        return None
    try:
        f = float(v)
    except ValueError:
        return 1.0
    if f <= 0:  # "0", "0.0", negatives: all mean OFF
        return None
    return max(f, 0.05)


def speculation_lag_factor() -> float:
    """``BALLISTA_SPECULATION_LAG_FACTOR`` (default 3.0): duplicate a
    running task when its observed row rate times this factor still
    trails its stage's median sampled rate. Values <= 1 disable the
    rate trigger (age fallback only)."""
    try:
        return float(os.environ.get(
            "BALLISTA_SPECULATION_LAG_FACTOR", "3.0") or 3.0)
    except ValueError:
        return 3.0


def executor_stale_secs() -> float:
    """``BALLISTA_EXECUTOR_STALE_SECS``: heartbeat age past which
    ``system.executors`` marks a row ``stale=true``."""
    try:
        return max(float(os.environ.get(
            "BALLISTA_EXECUTOR_STALE_SECS", "15") or 15), 0.1)
    except ValueError:
        return 15.0


# ---------------------------------------------------------------------------
# Plan sampling (shared by the executor piggyback and the standalone
# sampler): rows/bytes so far + current operator, no device sync forced
# ---------------------------------------------------------------------------


def _plan_nodes_with_depth(plan) -> List[Tuple[int, object]]:
    out: List[Tuple[int, object]] = []

    def walk(node, depth):
        out.append((depth, node))
        for c in node.children():
            walk(c, depth + 1)

    walk(plan, 0)
    return out


def plan_input_estimate(plan, per_partition: bool = False) -> int:
    """Total estimated input rows of the plan's LEAF operators (scans,
    shuffle readers — exact for the latter). 0 = unknown (any leaf
    declining makes the total untrustworthy for a fraction).

    ``per_partition=True`` divides each leaf's estimate by its
    partition count: a cluster task executes ONE partition of the
    shared stage plan, so its denominator is the stage input's
    per-partition share, not the whole stage (assumes an even split —
    advisory, and the running-task fraction cap absorbs skew)."""
    total = 0.0
    for _, node in _plan_nodes_with_depth(plan):
        if node.children():
            continue
        try:
            est = node.estimated_rows()
        except Exception:  # noqa: BLE001 - advisory
            est = None
        if est is None:
            return 0
        if per_partition:
            try:
                n = node.output_partitioning().num_partitions or 1
            except Exception:  # noqa: BLE001 - advisory
                n = 1
            est = est / max(int(n), 1)
        total += est
    return int(total)


def sample_plan(plan, input_rows_total: Optional[int] = None) -> dict:
    """One progress sample off an executing plan's MetricsSets:
    ``rows_so_far`` (leaf output rows — input consumed), ``bytes_so_far``
    (shuffle bytes read), ``input_rows_total`` and the shallowest
    operator observed producing output (the pipeline's current head).
    Never blocks on in-flight device compute."""
    rows = 0
    bytes_ = 0
    operator = ""
    op_depth = None
    for depth, node in _plan_nodes_with_depth(plan):
        m = node.metrics()
        if not node.children():
            rows += m.snapshot_rows()
        br = m._counters.get("bytes_read", 0)
        if br:
            bytes_ += int(br)
        active = m._counters.get("output_batches", 0) or m._pending_rows
        if active and (op_depth is None or depth < op_depth):
            op_depth = depth
            operator = node.display()
    if input_rows_total is None:
        input_rows_total = plan_input_estimate(plan)
    return {
        "rows_so_far": int(rows),
        "bytes_so_far": int(bytes_),
        "input_rows_total": int(input_rows_total or 0),
        "operator": operator,
    }


def _fraction_of(sample: Optional[dict]) -> float:
    """Partial completion of one RUNNING task from its latest sample."""
    if not sample:
        return 0.0
    est = int(sample.get("input_rows_total") or 0)
    if est <= 0:
        return 0.0
    f = sample.get("rows_so_far", 0) / est
    return max(0.0, min(f, RUNNING_TASK_FRACTION_CAP))


def _copy_snap(snap: dict) -> dict:
    """Copy a snapshot one level deeper than dict(): the stage dicts
    must not be shared between the tracker's cache/final stores and
    callers — finish() mutates stage rows in place."""
    out = dict(snap)
    out["stages"] = [dict(s) for s in snap.get("stages") or []]
    return out


def force_completed(snap: dict) -> dict:
    """Make a snapshot report exact completion — job AND stage rows.
    The ONE terminal-forcing rule, shared by the tracker's frozen
    final snapshot and the client's terminal callback (which can
    observe the completed KV before the tracker's finish() runs)."""
    snap["fraction"] = 1.0
    snap["eta_seconds"] = 0.0
    snap["tasks_running"] = snap["tasks_queued"] = 0
    snap["tasks_completed"] = snap["tasks_total"]
    for s in snap.get("stages") or []:
        s["fraction"] = 1.0
        s["eta_seconds"] = 0.0
        s["tasks_running"] = 0
        s["tasks_completed"] = s["tasks_total"]
    return snap


def _eta(fraction: float, wall: float) -> Optional[float]:
    """Rate-based remaining-time estimate: assumes progress continues
    at the observed average rate. None below 2% (the rate is noise)."""
    if fraction < 0.02 or wall <= 0:
        return None
    if fraction >= 1.0:
        return 0.0
    return round(wall * (1.0 - fraction) / fraction, 3)


# ---------------------------------------------------------------------------
# Scheduler-side live job model
# ---------------------------------------------------------------------------


class JobProgressTracker:
    """Folds executor ``TaskProgress`` samples + scheduler task state
    into live per-stage/job progress snapshots.

    Owned by the SchedulerService; reads task statuses from the
    scheduler state at snapshot time (no second event stream to drift).
    Bounded: at most ``cap`` jobs tracked (oldest evicted); terminal
    jobs keep ONE final snapshot so ``/debug/jobs/<id>`` can answer for
    recently finished work."""

    def __init__(self, state=None, cap: int = 128):
        self._state = state
        self._cap = cap
        self._lock = threading.Lock()
        # job_id -> {"t0", "samples": {(sid, pid): sample},
        #            "last_fraction", "final": dict | None}
        self._jobs: "OrderedDict[str, dict]" = OrderedDict()

    def register_job(self, job_id: str) -> None:
        with self._lock:
            if job_id not in self._jobs:
                self._jobs[job_id] = {"t0": time.time(), "samples": {},
                                      "last_fraction": 0.0, "final": None}
                while len(self._jobs) > self._cap:
                    self._jobs.popitem(last=False)

    def record_report(self, job_id: str, stage_id: int, partition_id: int,
                      sample: dict) -> None:
        """One TaskProgress report off a PollWork. Unknown jobs are
        registered on the fly (scheduler restart); everything is
        advisory, so no validation beyond bounds."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                # evicted / post-restart job: seed t0 from the durable
                # start stamp, else wall_seconds (and the rate-based
                # ETA built on it) would restart from this report
                t0 = None
                if self._state is not None:
                    try:
                        t0 = self._state.job_started_at(job_id)
                    except Exception:  # noqa: BLE001 - advisory
                        t0 = None
                self._jobs[job_id] = job = {
                    "t0": t0 or time.time(), "samples": {},
                    "last_fraction": 0.0, "final": None}
                while len(self._jobs) > self._cap:
                    self._jobs.popitem(last=False)
            key = (int(stage_id), int(partition_id))
            prev = job["samples"].get(key)
            if prev is not None and int(sample.get("stage_version", 0)) \
                    < int(prev.get("stage_version", 0)):
                return  # superseded attempt: an adaptive re-plan bumped
                # the stage version — the dead task's counts must not
                # pollute the new attempt's fraction
            # runaway guard: updates to known tasks always land, a
            # pathological key space stops growing at the bound
            if prev is not None or len(job["samples"]) < 4096:
                job["samples"][key] = sample
                # fresh data: the next snapshot must see it (the cache
                # only dedupes polls BETWEEN heartbeats)
                job.pop("cache", None)

    # -- rate-based speculation (ROADMAP 5a: the scheduler CONSUMES the
    # progress model) ---------------------------------------------------------

    # a sample younger than this carries too little signal for a rate
    MIN_RATE_ELAPSED_SECS = 1.0

    def _stage_rates(self, job_id: str, stage_id: int
                     ) -> List[Tuple[int, float]]:
        """(partition_id, rows/sec) for every usably-sampled task of
        the stage — one locked pass over the sample map."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return []
            samples = [(k[1], s) for k, s in job["samples"].items()
                       if k[0] == int(stage_id)]
        out: List[Tuple[int, float]] = []
        for pid, s in samples:
            elapsed = float(s.get("elapsed_seconds") or 0.0)
            if elapsed < self.MIN_RATE_ELAPSED_SECS:
                continue
            out.append((pid, float(s.get("rows_so_far", 0)) / elapsed))
        return out

    @staticmethod
    def _lag_verdict(rates: List[Tuple[int, float]], partition_id: int,
                     factor: float) -> Optional[bool]:
        mine: Optional[float] = None
        sibling_rates: List[float] = []
        for pid, rate in rates:
            if pid == int(partition_id):
                mine = rate
            else:
                sibling_rates.append(rate)
        if mine is None or not sibling_rates:
            return None
        sibling_rates.sort()
        median = sibling_rates[len(sibling_rates) // 2]
        if median <= 0:
            return None
        return mine * factor < median

    def is_lagging(self, job_id: str, stage_id: int, partition_id: int,
                   factor: Optional[float] = None) -> Optional[bool]:
        """Rate verdict for one running task, from the stage's latest
        progress samples: True = its observed row rate times
        ``BALLISTA_SPECULATION_LAG_FACTOR`` still trails the median
        rate of its stage SIBLINGS (duplicate it); False = measurably
        keeping up (do not); None = no verdict — the task or its stage
        has no usable samples, the caller falls back to the age
        trigger. A sampled task stuck at 0 rows reads rate 0 and lags
        any progressing stage."""
        if factor is None:
            factor = speculation_lag_factor()
        if factor <= 1.0:
            return None
        return self._lag_verdict(self._stage_rates(job_id, stage_id),
                                 partition_id, factor)

    def speculation_lag_fn(self):
        """The ``lag_fn`` SchedulerState.speculative_task consumes, or
        None when the progress plane is off (pure age fallback). The
        returned closure is built fresh per speculation SCAN and caches
        one rate snapshot per (job, stage) — the scan calls it for
        every running task, and rescanning the sample map (plus the env
        read) per task would be O(tasks x samples) on the PollWork
        handler thread."""
        if progress_interval_secs() is None:
            return None
        factor = speculation_lag_factor()
        if factor <= 1.0:
            return None
        rate_cache: Dict[Tuple[str, int], List[Tuple[int, float]]] = {}

        def lag(t) -> Optional[bool]:
            key = (t.partition.job_id, t.partition.stage_id)
            rates = rate_cache.get(key)
            if rates is None:
                rates = rate_cache[key] = self._stage_rates(*key)
            return self._lag_verdict(rates, t.partition.partition_id,
                                     factor)

        return lag

    # -- snapshots -----------------------------------------------------------

    def _task_states(self, job_id: str):
        st = self._state
        if st is None:
            return []
        try:
            return st.get_task_statuses(job_id)
        except Exception:  # noqa: BLE001 - diagnosis plane
            return []

    def snapshot(self, job_id: str) -> Optional[dict]:
        """The job's live progress snapshot (the ONE shape), or None
        when the tracker never saw the job. Briefly cached (half the
        progress cadence): building a snapshot prefix-scans and
        unpickles every task status, and clients poll GetJobStatus at
        100ms — the RPC handler threads must not pay O(tasks) per poll
        for information that only changes on heartbeats."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            if job["final"] is not None:
                return _copy_snap(job["final"])
            cached = job.get("cache")
            if cached is not None and \
                    time.time() - cached[0] < self._snapshot_ttl():
                return _copy_snap(cached[1])
            samples = dict(job["samples"])
            t0 = job["t0"]
            last_fraction = job["last_fraction"]
        status = "running"
        st = self._state
        if st is not None:
            try:
                js = st.get_job_status(job_id)
                if js is not None:
                    status = js.state
            except Exception:  # noqa: BLE001
                pass
        wall = max(time.time() - t0, 0.0)
        stages: Dict[int, dict] = {}
        totals = running = queued = completed = 0
        for t in self._task_states(job_id):
            sid = t.partition.stage_id
            srow = stages.setdefault(sid, {
                "stage_id": sid, "tasks_total": 0, "tasks_running": 0,
                "tasks_completed": 0, "fraction": 0.0, "eta_seconds": None,
                "rows_so_far": 0, "bytes_so_far": 0, "_units": 0.0,
                "_t0": None,
            })
            srow["tasks_total"] += 1
            totals += 1
            if t.started_at:
                srow["_t0"] = min(srow["_t0"] or t.started_at,
                                  t.started_at)
            if t.state == "completed":
                srow["tasks_completed"] += 1
                completed += 1
                srow["_units"] += 1.0
                # keep the units the shape promises (leaf input rows
                # consumed / wire bytes): the task's last retained
                # sample — its output stats are a DIFFERENT unit, and
                # on a selective stage swapping to them at completion
                # makes the counter jump backwards
                sample = samples.get((sid, t.partition.partition_id))
                if sample:
                    srow["rows_so_far"] += int(sample.get("rows_so_far", 0))
                    srow["bytes_so_far"] += \
                        int(sample.get("bytes_so_far", 0))
                else:  # plane off / task outran the first heartbeat
                    stats = t.stats or {}
                    srow["rows_so_far"] += int(stats.get("num_rows", 0))
                    srow["bytes_so_far"] += int(stats.get("num_bytes", 0))
            elif t.state == "running":
                srow["tasks_running"] += 1
                running += 1
                sample = samples.get((sid, t.partition.partition_id))
                srow["_units"] += _fraction_of(sample)
                if sample:
                    srow["rows_so_far"] += int(sample.get("rows_so_far", 0))
                    srow["bytes_so_far"] += \
                        int(sample.get("bytes_so_far", 0))
            else:
                queued += 1
        stage_rows = []
        now = time.time()
        for sid in sorted(stages):
            srow = stages[sid]
            units = srow.pop("_units")
            st0 = srow.pop("_t0")
            n = srow["tasks_total"]
            f = units / n if n else 0.0
            if status == "completed":
                f = 1.0
            srow["fraction"] = round(f, 4)
            # a stage's rate is measured from ITS first task start —
            # the job wall includes upstream stages' runtime and would
            # inflate a late stage's ETA by orders of magnitude
            stage_wall = max(now - st0, 0.0) if st0 else wall
            srow["eta_seconds"] = _eta(f, stage_wall)
            stage_rows.append(srow)
        fraction = (sum(s["fraction"] * s["tasks_total"]
                        for s in stage_rows) / totals) if totals else 0.0
        if status == "completed":
            fraction, running, queued = 1.0, 0, 0
            completed = totals
        # monotone non-decreasing per job (estimates fluctuating between
        # samples must never show progress going backwards)
        fraction = max(fraction, last_fraction)
        fraction = min(fraction, 1.0)
        snap = {
            "job_id": job_id,
            "status": status,
            "fraction": round(fraction, 4),
            "eta_seconds": _eta(fraction, wall),
            "wall_seconds": round(wall, 3),
            "tasks_total": totals,
            "tasks_running": running,
            "tasks_queued": queued,
            "tasks_completed": completed,
            "stages": stage_rows,
        }
        with self._lock:
            job = self._jobs.get(job_id)
            if job is not None and job["final"] is None:
                job["last_fraction"] = fraction
                job["cache"] = (time.time(), _copy_snap(snap))
        return snap

    @staticmethod
    def _snapshot_ttl() -> float:
        return min(max((progress_interval_secs() or 1.0) / 2, 0.05), 0.5)

    def finish(self, job_id: str, status: str) -> None:
        """Terminal transition: freeze one final snapshot (fraction
        exactly 1.0 for completed jobs) and drop the sample store."""
        snap = self.snapshot(job_id)
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or snap is None:
                return
            # own copy before mutating: snapshot() may have handed the
            # same stage dicts to a concurrent reader via the cache
            snap = _copy_snap(snap)
            snap["status"] = status
            if status == "completed":
                force_completed(snap)
            job["final"] = snap
            job["samples"] = {}

    def live_snapshots(self) -> List[dict]:
        """Snapshots of every non-terminal tracked job (the /debug/jobs
        list, the Prometheus gauges, system.stages)."""
        with self._lock:
            live = [j for j, rec in self._jobs.items()
                    if rec["final"] is None]
        out = []
        for job_id in live:
            snap = self.snapshot(job_id)
            if snap is not None and snap["status"] in ("queued", "running"):
                out.append(snap)
        return out

    def task_rows(self) -> List[dict]:
        """``system.tasks``: one row per RUNNING task of every live
        job, joined with the latest progress sample."""
        with self._lock:
            live = {j: dict(rec["samples"])
                    for j, rec in self._jobs.items()
                    if rec["final"] is None}
        rows: List[dict] = []
        now = time.time()
        for job_id, samples in live.items():
            for t in self._task_states(job_id):
                if t.state != "running":
                    continue
                sample = samples.get(
                    (t.partition.stage_id, t.partition.partition_id)) or {}
                elapsed = (now - t.started_at) if t.started_at else None
                rows.append({
                    "job_id": job_id,
                    "stage_id": t.partition.stage_id,
                    "partition_id": t.partition.partition_id,
                    "executor_id": t.executor_id or "",
                    "operator": sample.get("operator"),
                    "rows_so_far": sample.get("rows_so_far"),
                    "bytes_so_far": sample.get("bytes_so_far"),
                    "elapsed_seconds": round(elapsed, 3)
                    if elapsed is not None else None,
                })
        return rows

    def stage_rows(self) -> List[dict]:
        """``system.stages``: the per-stage progress rows of every live
        job."""
        rows: List[dict] = []
        for snap in self.live_snapshots():
            for s in snap["stages"]:
                rows.append({"job_id": snap["job_id"], **s})
        return rows


# ---------------------------------------------------------------------------
# Per-session resource metering (system.sessions)
# ---------------------------------------------------------------------------

_SESSIONS_FILE = "sessions.json"
SESSION_SETTING = "session.id"


class SessionMeter:
    """Cumulative per-session resource accounting.

    One record per client session id: queries run, wall seconds,
    task seconds (summed executor task time — the cluster's "cpu"
    proxy), device-blocked seconds (from the lane decomposition, when
    it lands), shuffle bytes, peak host/device bytes. Durable when a
    directory is given: the whole (small, bounded) map is atomically
    rewritten and reloaded at construction, so metering survives
    restarts next to the query-history log. Disk writes are DEBOUNCED
    (at most one per ``SAVE_INTERVAL_SECS``, plus a ``flush()`` at
    interpreter exit) — the save must not tax the collect/terminal hot
    paths per query; a hard kill can lose the last interval's updates,
    best-effort like the rest of the plane. Saves re-read
    the file and keep session ids this process never touched, so
    concurrent writers (scheduler + a standalone process sharing the
    dir) don't erase each other's sessions — same-session counters
    from two processes remain last-writer-wins (best-effort, like the
    rest of the plane)."""

    CAP = 256
    SAVE_INTERVAL_SECS = 2.0

    def __init__(self, directory: Optional[str] = None):
        self._lock = threading.Lock()
        self._dir = directory
        self._sessions: "OrderedDict[str, dict]" = OrderedDict()
        self._last_save = 0.0
        self._dirty = False
        if directory:
            self._load()

    def _path(self) -> Optional[str]:
        if not self._dir:
            return None
        return os.path.join(self._dir, _SESSIONS_FILE)

    def _load(self) -> None:
        path = self._path()
        try:
            with open(path) as fh:
                data = json.load(fh)
            if isinstance(data, dict):
                for sid, rec in data.items():
                    if isinstance(rec, dict):
                        self._sessions[str(sid)] = rec
        except (OSError, ValueError):
            pass  # no file yet / corrupt: start fresh

    def _maybe_save_locked(self) -> None:
        """Debounced durability: write through at most once per
        ``SAVE_INTERVAL_SECS`` — per-query file I/O on the collect /
        terminal-transition paths is exactly what the overhead gates
        forbid. ``flush()`` (registered atexit for process meters)
        writes out whatever the debounce skipped."""
        self._dirty = True
        if self._path() is None:
            return
        if time.time() - self._last_save >= self.SAVE_INTERVAL_SECS:
            self._save_locked()

    def flush(self) -> None:
        with self._lock:
            if self._dirty:
                self._save_locked()

    def _save_locked(self) -> None:
        path = self._path()
        if path is None:
            return
        self._last_save = time.time()
        self._dirty = False
        merged: Dict[str, dict] = {}
        try:
            with open(path) as fh:
                data = json.load(fh)
            if isinstance(data, dict):
                for sid, rec in data.items():
                    if isinstance(rec, dict) and sid not in self._sessions:
                        merged[str(sid)] = rec
        except (OSError, ValueError):
            pass  # no file yet / corrupt: write only what we know
        merged.update(self._sessions)
        if len(merged) > self.CAP:
            drop = sorted(merged, key=lambda s: merged[s].get(
                "last_active", 0.0))[:len(merged) - self.CAP]
            for sid in drop:
                merged.pop(sid, None)
        try:
            os.makedirs(self._dir, exist_ok=True)
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "w") as fh:
                json.dump(merged, fh)
            os.replace(tmp, path)
        except OSError:
            log.warning("session meter save failed (%s)", self._dir,
                        exc_info=True)

    def record(self, session_id: str, wall_seconds: float = 0.0,
               task_seconds: float = 0.0,
               device_blocked_seconds: float = 0.0,
               bytes_shuffled: int = 0,
               peak_host_bytes: int = 0,
               peak_device_bytes: int = 0) -> None:
        """Accumulate one finished query into the session's record."""
        sid = str(session_id or "anonymous")
        now = time.time()
        with self._lock:
            rec = self._sessions.pop(sid, None)
            if rec is None:
                rec = {"session_id": sid, "queries": 0,
                       "wall_seconds": 0.0, "task_seconds": 0.0,
                       "device_blocked_seconds": 0.0,
                       "bytes_shuffled": 0, "peak_host_bytes": 0,
                       "peak_device_bytes": 0,
                       "table_cache_hits": 0, "result_cache_hits": 0,
                       "started_at": now}
            rec["queries"] += 1
            rec["wall_seconds"] = round(
                rec["wall_seconds"] + float(wall_seconds), 4)
            rec["task_seconds"] = round(
                rec["task_seconds"] + float(task_seconds), 4)
            rec["device_blocked_seconds"] = round(
                rec["device_blocked_seconds"]
                + float(device_blocked_seconds), 4)
            rec["bytes_shuffled"] += int(bytes_shuffled)
            rec["peak_host_bytes"] = max(rec["peak_host_bytes"],
                                         int(peak_host_bytes or 0))
            rec["peak_device_bytes"] = max(rec["peak_device_bytes"],
                                           int(peak_device_bytes or 0))
            rec["last_active"] = now
            self._sessions[sid] = rec  # re-insert: LRU order
            while len(self._sessions) > self.CAP:
                self._sessions.popitem(last=False)
            self._maybe_save_locked()

    def annotate(self, session_id: str,
                 device_blocked_seconds: float = 0.0) -> None:
        """Late-arriving facts (the lane decomposition lands on the
        deferred merge worker, after the terminal record)."""
        if not device_blocked_seconds:
            return
        sid = str(session_id or "anonymous")
        with self._lock:
            rec = self._sessions.get(sid)
            if rec is None:
                return
            rec["device_blocked_seconds"] = round(
                rec["device_blocked_seconds"]
                + float(device_blocked_seconds), 4)
            rec["last_active"] = time.time()
            self._maybe_save_locked()

    def annotate_cache(self, session_id: str, table_cache_hits: int = 0,
                       result_cache_hits: int = 0) -> None:
        """Warm-path cache attribution for an already-recorded query —
        like :meth:`annotate`, this never bumps the ``queries`` count
        (a result-cache hit IS a query; its hit flag arrives
        separately)."""
        if not table_cache_hits and not result_cache_hits:
            return
        sid = str(session_id or "anonymous")
        with self._lock:
            rec = self._sessions.get(sid)
            if rec is None:
                # annotation can land before the recorder's terminal
                # record() (first query of a session): seed a zero-query
                # record, record() accumulates into it
                rec = self._sessions[sid] = {
                    "session_id": sid, "queries": 0,
                    "wall_seconds": 0.0, "task_seconds": 0.0,
                    "device_blocked_seconds": 0.0,
                    "bytes_shuffled": 0, "peak_host_bytes": 0,
                    "peak_device_bytes": 0,
                    "table_cache_hits": 0, "result_cache_hits": 0,
                    "started_at": time.time()}
            # pre-cache records loaded from disk lack the fields
            rec["table_cache_hits"] = (
                int(rec.get("table_cache_hits", 0)) + int(table_cache_hits))
            rec["result_cache_hits"] = (
                int(rec.get("result_cache_hits", 0))
                + int(result_cache_hits))
            rec["last_active"] = time.time()
            self._maybe_save_locked()

    def rows(self) -> List[dict]:
        with self._lock:
            return [dict(rec) for rec in self._sessions.values()]


_meter_lock = threading.Lock()
_meters: Dict[Optional[str], SessionMeter] = {}


def process_session_meter() -> SessionMeter:
    """The process's session meter for the current
    ``BALLISTA_QUERY_LOG_DIR`` (in-memory only when unset). Shared by
    the standalone recorder, the scheduler's terminal hook and the
    ``system.sessions`` scans of this process."""
    from .systables import query_log_dir

    d = query_log_dir()
    with _meter_lock:
        meter = _meters.get(d)
        if meter is None:
            meter = _meters[d] = SessionMeter(d)
            if d:
                # durability backstop for the save debounce
                atexit.register(meter.flush)
        return meter


def _reset_process_state_for_tests() -> None:
    with _meter_lock:
        _meters.clear()
    with _local_lock:
        _LOCAL.clear()


# ---------------------------------------------------------------------------
# Standalone parity: local query handles + the on_progress sampler
# ---------------------------------------------------------------------------

_local_lock = threading.Lock()
_LOCAL: "OrderedDict[str, LocalQueryHandle]" = OrderedDict()
_tls = threading.local()


class LocalQueryHandle:
    """One in-flight standalone collect, visible to the live surfaces
    (system.tasks / system.stages / in-flight system.queries) and
    driving the ``on_progress`` sampler. The executed plan attaches
    lazily (planning happens after the recorder starts) and is held
    weakly — a handle must never pin a plan tree."""

    def __init__(self, job_id: str, session_id: str = "",
                 plan_digest: str = ""):
        self.job_id = job_id
        self.session_id = session_id
        self.plan_digest = plan_digest
        self.t0 = time.time()
        self.status = "running"
        self._plan_ref = None
        self._input_total = 0
        self._last_fraction = 0.0
        self._last_sample: dict = {}

    def attach_plan(self, phys) -> None:
        self._plan_ref = weakref.ref(phys)
        try:
            self._input_total = plan_input_estimate(phys)
        except Exception:  # noqa: BLE001 - advisory
            self._input_total = 0

    def sample(self) -> dict:
        plan = self._plan_ref() if self._plan_ref is not None else None
        if plan is None:
            return dict(self._last_sample)
        try:
            s = sample_plan(plan, input_rows_total=self._input_total)
        except Exception:  # noqa: BLE001 - advisory
            return dict(self._last_sample)
        self._last_sample = s
        return s

    def snapshot(self) -> dict:
        """The ONE progress shape, standalone face: a single synthetic
        stage 0 with one task."""
        wall = max(time.time() - self.t0, 0.0)
        done = self.status == "completed"
        if done:
            f = 1.0
        elif self.status in ("failed", "cancelled"):
            f = self._last_fraction
        else:
            f = max(_fraction_of(self.sample()), self._last_fraction)
        self._last_fraction = f
        running = 0 if self.status != "running" else 1
        s = self._last_sample
        stage = {
            "stage_id": 0, "tasks_total": 1, "tasks_running": running,
            "tasks_completed": 1 if done else 0,
            "fraction": round(f, 4), "eta_seconds": _eta(f, wall),
            "rows_so_far": int(s.get("rows_so_far", 0)),
            "bytes_so_far": int(s.get("bytes_so_far", 0)),
        }
        return {
            "job_id": self.job_id,
            "status": self.status,
            "fraction": round(f, 4),
            "eta_seconds": _eta(f, wall),
            "wall_seconds": round(wall, 3),
            "tasks_total": 1,
            "tasks_running": running,
            "tasks_queued": 0,
            "tasks_completed": 1 if done else 0,
            "stages": [stage],
        }


def start_local_query(job_id: str, session_id: str = "",
                      plan_digest: str = "") -> LocalQueryHandle:
    """Register one standalone collect with the live surfaces. Also
    pushed onto a thread-local stack so the collect path can attach
    the executed plan without threading the handle through every
    layer."""
    h = LocalQueryHandle(job_id, session_id, plan_digest)
    with _local_lock:
        _LOCAL[job_id] = h
        while len(_LOCAL) > 64:
            _LOCAL.popitem(last=False)
    stack = getattr(_tls, "handles", None)
    if stack is None:
        stack = _tls.handles = []
    stack.append(h)
    return h


def attach_current_plan(phys) -> None:
    """Attach the executed physical plan to this thread's active
    handle (no-op outside a recorded collect — df.profile() and
    EXPLAIN drive the inner path directly)."""
    stack = getattr(_tls, "handles", None)
    if stack:
        try:
            stack[-1].attach_plan(phys)
        except Exception:  # noqa: BLE001 - advisory
            pass


def finish_local_query(handle: LocalQueryHandle, status: str) -> None:
    handle.status = status
    stack = getattr(_tls, "handles", None)
    if stack and handle in stack:
        stack.remove(handle)
    with _local_lock:
        _LOCAL.pop(handle.job_id, None)


def local_live_handles() -> List[LocalQueryHandle]:
    with _local_lock:
        return list(_LOCAL.values())


def local_stage_rows() -> List[dict]:
    """Standalone ``system.stages``: one row per in-flight collect."""
    rows = []
    for h in local_live_handles():
        snap = h.snapshot()
        for s in snap["stages"]:
            rows.append({"job_id": snap["job_id"], **s})
    return rows


def local_task_rows() -> List[dict]:
    """Standalone ``system.tasks``: one row per in-flight collect."""
    rows = []
    for h in local_live_handles():
        s = h.sample()
        rows.append({
            "job_id": h.job_id,
            "stage_id": 0,
            "partition_id": 0,
            "executor_id": "standalone",
            "operator": s.get("operator"),
            "rows_so_far": s.get("rows_so_far"),
            "bytes_so_far": s.get("bytes_so_far"),
            "elapsed_seconds": round(time.time() - h.t0, 3),
        })
    return rows


def local_live_query_records() -> List[dict]:
    """In-flight ``system.queries`` / ``/debug/queries`` rows for
    running standalone collects (status="running", live wall seconds);
    removed on completion (the terminal record replaces them)."""
    from .systables import build_query_record

    out = []
    for h in local_live_handles():
        out.append(build_query_record(
            h.job_id, "running", time.time() - h.t0,
            plan_digest=h.plan_digest or None,
            started_at=h.t0, origin="standalone",
        ))
    return out


def emit_if_changed(cb, snap: dict, last_key):
    """Deliver one progress snapshot to a caller's ``on_progress``
    callback when it meaningfully changed vs ``last_key``; returns the
    new key to carry forward. The ONE dedup + protect contract for
    both delivery paths (cluster status poll, standalone sampler):
    best-effort — a raising callback is logged, never the query's
    problem."""
    key = (snap["fraction"], snap["tasks_completed"], snap["status"])
    if key == last_key:
        return last_key
    try:
        cb(snap)
    except Exception:  # noqa: BLE001 - observability only
        log.warning("on_progress callback failed", exc_info=True)
    return key


class LocalProgressSampler:
    """Background sampler driving ``df.collect(on_progress=cb)`` on the
    standalone path: one daemon thread polls the handle's snapshot on
    the progress cadence and invokes the callback when it changes
    (callbacks run on the sampler thread; a raising callback is
    dropped, never the query). ``finish()`` emits the terminal
    snapshot (fraction exactly 1.0 on success) from the collect
    thread."""

    def __init__(self, handle: LocalQueryHandle,
                 on_progress: Callable[[dict], None],
                 interval: Optional[float] = None):
        self._handle = handle
        self._cb = on_progress
        self._interval = interval if interval is not None else \
            (progress_interval_secs() or 0.2)
        self._stop = threading.Event()
        self._last: Optional[tuple] = None
        # serializes callbacks across the sampler and collect threads:
        # the terminal snapshot must be the LAST callback even when a
        # user callback blocks past finish()'s join timeout
        self._emit_lock = threading.Lock()
        self._terminal = False
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"progress-{handle.job_id}")
        self._thread.start()

    def _emit(self, snap: dict, terminal: bool = False) -> None:
        # the terminal emit runs on the COLLECT thread: bound the wait
        # so a user callback blocked inside the sampler thread cannot
        # wedge df.collect() past query completion (the terminal
        # callback is then skipped — the callback is already stuck)
        if not self._emit_lock.acquire(timeout=2.0 if terminal else -1):
            log.warning("terminal on_progress skipped: a callback is "
                        "still blocked")
            return
        try:
            if self._terminal and not terminal:
                return  # a straggling sample must not follow the final
            if terminal:
                self._terminal = True
            self._last = emit_if_changed(self._cb, snap, self._last)
        finally:
            self._emit_lock.release()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._emit(self._handle.snapshot())
            except Exception:  # noqa: BLE001 - sampler must not die
                pass

    def finish(self, status: str) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._handle.status = status
        try:
            self._emit(self._handle.snapshot(), terminal=True)
        except Exception:  # noqa: BLE001
            pass
