"""Query profiler: one reproducible artifact per query.

The ROADMAP's execute-mass decomposition (q5 fresh-process: ~1.9s parse
+ ~0.2s H2D + ~1.8s compile retrieval + ~13s execute, of which ~5.8s
blocked on device results, ~4.6s jit trace/lower, ~2.9s host dictionary
/ numpy work) was established by ad-hoc profiling. This module makes
that decomposition a first-class output: a :class:`Profiler` session
captures, for the window of one query,

- every trace span (tracing is force-enabled into a private file for
  the session when not already on) — ingest producer threads, compile
  activity, blocking device syncs, host dictionary work, scheduler /
  executor / dataplane events;
- the ingest phase totals delta (``parse`` / ``h2d``);
- the compile governor stats delta (backend compiles, compile seconds,
  trace seconds, persistent-cache hits);
- the memory snapshot (tracked host bytes by category, device bytes,
  peaks, RSS);
- per-operator ``MetricsSet`` values off the executed physical plan,

and ``export.py`` merges them into ONE Chrome-trace/Perfetto-compatible
JSON artifact with named lane attribution. Entry points:
``DataFrame.profile()`` (standalone) and ``BALLISTA_PROFILE=<dir>``
(every standalone ``collect()`` writes an artifact into the directory).
The CLUSTER path does not use this window class: executors ship
per-task span windows with ``CompletedTask`` and the scheduler merges
them per job (``observability/distributed.py``), so the same env var /
``df.profile()`` surface works identically there.

One window per process: overlapping profilers are refused
(:class:`ProfilerBusy`; the ambient path degrades the loser to an
unprofiled run). The tracer itself stays process-global, though — if
OTHER queries run concurrently with an active window, their spans land
in the window's trace too and inflate its lanes. Profile on a quiet
process when lane precision matters; the per-record ``tid``/flow attrs
in ``traceEvents`` let a reader separate the interleaved work after
the fact.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

from . import memory as obs_memory
from . import tracing

# One profiling window per process: start/stop mutate os.environ and the
# shared tracer, so two overlapping windows would cross-write each
# other's trace files and fight over the env restore. The lock makes
# activation atomic; losers of the race run unprofiled (ambient) or
# raise (explicit df.profile()).
_active_lock = threading.Lock()
_ACTIVE = False


class ProfilerBusy(RuntimeError):
    """Another profiling window is already active in this process."""


def _try_activate() -> bool:
    global _ACTIVE
    with _active_lock:
        if _ACTIVE:
            return False
        _ACTIVE = True
        return True


def _deactivate() -> None:
    global _ACTIVE
    with _active_lock:
        _ACTIVE = False


def profile_dir() -> Optional[str]:
    """The ``BALLISTA_PROFILE`` artifact directory, or None when the
    ambient profiler is off. ``BALLISTA_PROFILE=1`` means the current
    working directory."""
    v = os.environ.get("BALLISTA_PROFILE", "")
    if not v or v.lower() in ("0", "off", "false"):
        return None
    if v.lower() in ("1", "on", "true"):
        return os.getcwd()
    return v


def plan_digest(plan, n: int = 12) -> str:
    """Stable short digest of a logical plan's pretty-printed form —
    ONE format for every surface (artifact labels, slow-query
    summaries, scheduler job digests), so a digest seen in
    ``/debug/queries`` greps straight into artifact filenames."""
    import hashlib

    return hashlib.sha1(plan.pretty().encode()).hexdigest()[:n]


class Profiler:
    """One profiling window. Usage::

        prof = Profiler(label="q5")
        prof.start()
        ... run the query ...
        session = prof.stop(plan=phys)
        path = export.write_artifact(session, out_dir)
    """

    def __init__(self, label: str = "query"):
        self.label = label
        self._own_trace = False
        self._saved_env: dict = {}
        self._trace_file: Optional[str] = None
        self._t0 = None
        self._phases0: dict = {}
        self._compile0: dict = {}
        self._trace_offset = 0

    def start(self) -> "Profiler":
        from ..compile import compile_stats
        from ..ingest import phase_totals

        if not _try_activate():
            raise ProfilerBusy("another profiling window is active")
        try:
            self._start_inner(compile_stats, phase_totals)
        except BaseException:
            # a failed setup must not leave the process looking
            # permanently "profiling" (that would silently disable
            # ambient BALLISTA_PROFILE forever)
            _deactivate()
            raise
        return self

    def _start_inner(self, compile_stats, phase_totals) -> None:
        if not tracing.trace_enabled():
            # force tracing into a private file for this window only;
            # restore the user's env on stop
            self._own_trace = True
            fd, path = tempfile.mkstemp(prefix="ballista-profile-",
                                        suffix=".jsonl")
            os.close(fd)
            self._trace_file = path
            for k in ("BALLISTA_TRACE", "BALLISTA_TRACE_FILE",
                      "BALLISTA_TRACE_TRUNCATE", "BALLISTA_TRACE_MAX_MB"):
                self._saved_env[k] = os.environ.get(k)
            os.environ["BALLISTA_TRACE"] = "1"
            os.environ["BALLISTA_TRACE_FILE"] = path
            os.environ["BALLISTA_TRACE_TRUNCATE"] = "1"
            # the user's hygiene cap is for THEIR long-lived trace file;
            # a capped private window would silently drop spans and
            # under-report every lane
            os.environ["BALLISTA_TRACE_MAX_MB"] = "0"
            tracing.reconfigure()
        else:
            self._trace_file = tracing.trace_path()
            try:
                self._trace_offset = os.path.getsize(self._trace_file)
            except OSError:
                self._trace_offset = 0
        # NOTE: the process-wide memory peaks are NOT reset here — the
        # health plane, heartbeats and bench.py report them as lifetime
        # trajectories, and an ambient profiler window clobbering them
        # would make those under-report. The artifact's memory section
        # is a snapshot taken at stop() (peaks = process lifetime).
        self._phases0 = phase_totals()
        self._compile0 = compile_stats()
        self._t0 = time.time()

    def stop(self, plan=None) -> dict:
        """End the window; returns the session dict ``export`` consumes.
        ``plan`` (the executed physical plan) supplies per-operator
        metrics when given."""
        from ..compile import compile_stats
        from ..ingest import phase_totals

        try:
            wall = time.time() - self._t0
            phases1 = phase_totals()
            compile1 = compile_stats()
            records = self._read_trace()
            if self._own_trace:
                for k, v in self._saved_env.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
                tracing.reconfigure()
                try:
                    os.unlink(self._trace_file)
                except OSError:
                    pass
        finally:
            _deactivate()

        phase_delta = {
            k: round(phases1.get(k, 0.0) - self._phases0.get(k, 0.0), 6)
            for k in set(phases1) | set(self._phases0)
        }
        compile_delta = {
            k: (round(compile1[k] - self._compile0.get(k, 0), 6)
                if isinstance(compile1[k], float)
                else compile1[k] - self._compile0.get(k, 0))
            for k in ("backend_compiles", "compile_seconds",
                      "trace_seconds", "persistent_cache_hits")
            if k in compile1
        }
        operators = None
        if plan is not None:
            try:
                from .metrics import collect_plan_metrics

                operators = collect_plan_metrics(plan)
            except Exception:  # noqa: BLE001 - artifact still useful
                operators = None
        return {
            "schema": "ballista-profile-v1",
            "label": self.label,
            "t0": self._t0,
            "wall_seconds": round(wall, 6),
            "phases": phase_delta,
            "compile": compile_delta,
            "memory": obs_memory.memory_snapshot(),
            "operators": operators,
            "records": records,
        }

    def _read_trace(self) -> list:
        """Trace records emitted during the window (other processes
        write their own files; a standalone query is single-process)."""
        if not self._trace_file:
            return []
        out = []
        try:
            with open(self._trace_file) as fh:
                fh.seek(self._trace_offset)
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    # keep records that OVERLAP the window (a span that
                    # started before .start() but ended inside still
                    # holds wall time of this query)
                    end = rec.get("ts", 0.0) + rec.get("dur", 0.0)
                    if end >= self._t0 - 1e-6:
                        out.append(rec)
        except OSError:
            return []
        return out


def profiling_active() -> bool:
    return _ACTIVE


def profile_call(fn, label: str = "query", plan_getter=None,
                 out_dir: Optional[str] = None,
                 out_path: Optional[str] = None,
                 busy_ok: bool = False):
    """Run ``fn()`` under a profiler and write the artifact. Returns
    ``(fn result, artifact path)``. ``plan_getter()`` is called after
    ``fn`` to fetch the executed physical plan (it may not exist until
    the query ran). With ``busy_ok`` a concurrent profiling window
    degrades this call to an unprofiled ``fn()`` (path None) instead of
    raising :class:`ProfilerBusy` — the ambient-BALLISTA_PROFILE path
    uses that so racing collects never corrupt each other's windows."""
    from . import export

    prof = Profiler(label=label)
    try:
        prof.start()
    except ProfilerBusy:
        if busy_ok:
            return fn(), None
        raise
    except Exception:
        if busy_ok:
            # ambient mode: ANY profiler setup failure (unwritable
            # TMPDIR, tracer trouble) degrades to an unprofiled run —
            # a broken observability knob must not abort the query
            import logging

            logging.getLogger("ballista.profiler").exception(
                "profiler setup failed; running unprofiled")
            return fn(), None
        raise
    try:
        result = fn()
    finally:
        plan = plan_getter() if plan_getter is not None else None
        session = prof.stop(plan=plan)
    path = export.write_artifact(session, out_dir=out_dir,
                                 out_path=out_path)
    return result, path
