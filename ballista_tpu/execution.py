"""Single-process plan execution helpers.

The in-process equivalent of the reference's executor collect path
(reference: rust/executor/src/collect.rs:35-121 CollectExec merges all
partitions into one stream). Used by tests, the standalone client mode,
and executors running one task.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .columnar import concat_pydicts
from .logical import LogicalPlan
from .optimizer import optimize
from .physical.base import PhysicalPlan
from .physical.planner import create_physical_plan


def plan_logical(plan: LogicalPlan) -> PhysicalPlan:
    return create_physical_plan(optimize(plan))


def collect_physical(phys: PhysicalPlan) -> Dict[str, np.ndarray]:
    """Execute all partitions and concatenate live rows on host."""
    parts: List[Dict[str, np.ndarray]] = []
    for p in range(phys.output_partitioning().num_partitions):
        for batch in phys.execute(p):
            parts.append(batch.to_pydict())
    if not parts:
        return {f.name: np.asarray([]) for f in phys.output_schema().fields}
    return concat_pydicts(parts)


def collect(plan: LogicalPlan):
    """Logical plan -> pandas DataFrame (optimize, plan, execute, gather)."""
    import pandas as pd

    return pd.DataFrame(collect_physical(plan_logical(plan)))
