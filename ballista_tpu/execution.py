"""Single-process plan execution helpers.

The in-process equivalent of the reference's executor collect path
(reference: rust/executor/src/collect.rs:35-121 CollectExec merges all
partitions into one stream). Used by tests, the standalone client mode,
and executors running one task.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from .columnar import concat_pydicts
from .datatypes import Float64 as _F64
from .errors import ExecutionError
from . import expr as ex
from .logical import (
    Aggregate,
    Filter,
    LogicalPlan,
    Projection,
    Repartition,
    Sort,
)
from .optimizer import optimize
from .physical.base import PhysicalPlan
from .physical.planner import create_physical_plan


def resolve_scalar_subqueries(plan: LogicalPlan, options=None) -> LogicalPlan:
    """Execute uncorrelated scalar subqueries and inline them as literals.

    Runs before optimization/serialization, so distributed plans never
    carry subquery nodes (the client resolves them, like the reference
    plans SQL client-side — reference: rust/client/src/context.rs:131-144).
    """

    def subquery_value(sq: ex.ScalarSubquery) -> ex.Literal:
        sub = sq.plan
        if sub is None:
            raise ExecutionError(
                "unplanned scalar subquery (correlated scalar subqueries "
                "are only supported in WHERE comparisons)"
            )
        out = collect_physical(plan_logical(sub, options))
        f = sub.schema().fields[0]
        col = out[f.name]
        if len(col) == 0:
            return ex.Literal(None, f.dtype)  # SQL: empty scalar -> NULL
        if len(col) > 1:
            raise ExecutionError(
                f"scalar subquery returned {len(col)} rows"
            )
        v = col[0]
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return ex.Literal(None, f.dtype)
        if f.dtype.kind in ("decimal", "float32", "float64"):
            return ex.Literal(float(v), _F64)
        if f.dtype.kind == "date32":
            days = int(np.asarray(v).astype("datetime64[D]").astype(np.int32))
            return ex.Literal(days, f.dtype)
        if f.dtype.kind == "utf8":
            return ex.Literal(str(v), f.dtype)
        return ex.Literal(int(v), f.dtype)

    def fix(e: ex.Expr) -> ex.Expr:
        if isinstance(e, ex.ScalarSubquery):
            return subquery_value(e)
        for attr in ("expr", "left", "right", "base", "otherwise"):
            if hasattr(e, attr) and isinstance(getattr(e, attr), ex.Expr):
                setattr(e, attr, fix(getattr(e, attr)))
        if hasattr(e, "args"):
            e.args = [fix(a) for a in e.args]
        if hasattr(e, "list"):
            e.list = [fix(a) for a in e.list]
        if hasattr(e, "branches"):
            e.branches = [(fix(w), fix(t)) for w, t in e.branches]
        return e

    def walk(p: LogicalPlan) -> LogicalPlan:
        if isinstance(p, Filter):
            p.predicate = fix(p.predicate)
        elif isinstance(p, Projection):
            p.exprs = [fix(e) for e in p.exprs]
        elif isinstance(p, Aggregate):
            p.group_exprs = [fix(e) for e in p.group_exprs]
            p.agg_exprs = [fix(e) for e in p.agg_exprs]
        elif isinstance(p, Sort):
            p.sort_exprs = [fix(e) for e in p.sort_exprs]
        elif isinstance(p, Repartition) and p.hash_exprs:
            p.hash_exprs = [fix(e) for e in p.hash_exprs]
        for c in p.children():
            walk(c)
        return p

    return walk(plan)



def plan_logical(plan: LogicalPlan, options=None) -> PhysicalPlan:
    from .logical import Explain

    if isinstance(plan, Explain):
        # render before AND after optimization so EXPLAIN VERBOSE can show
        # what the optimizer did; the rows execute as a normal leaf node
        # (distributed: the text rides the standard shuffle/fetch path)
        from .physical.explain import make_explain_analyze, render_explain

        inner = resolve_scalar_subqueries(plan.input, options)
        unopt = inner.pretty()
        opt = optimize(inner)
        phys = create_physical_plan(opt, options)
        if plan.analyze:
            # EXPLAIN ANALYZE: execute the plan and annotate it with live
            # metrics; the node is a leaf, so distributed runs ship the
            # whole analyzed plan as one task (observability docs)
            return make_explain_analyze(
                phys, plan.verbose, opt.pretty(),
                getattr(options, "adaptive_settings", None))
        return render_explain(opt, phys, plan.verbose,
                              unoptimized_text=unopt,
                              cost_notes=getattr(options, "cost_notes",
                                                 None))
    plan = resolve_scalar_subqueries(plan, options)
    return create_physical_plan(optimize(plan), options)


def collect_physical(phys: PhysicalPlan) -> Dict[str, np.ndarray]:
    """Execute all partitions and concatenate live rows on host.
    Partitions run concurrently on the ingest pool (batch order is
    preserved — see ingest.iter_partitions); serial when the pipeline
    is gated off."""
    from .ingest import iter_partitions
    from .lifecycle import check_cancel

    parts: List[Dict[str, np.ndarray]] = []
    for batch in iter_partitions(
            phys, range(phys.output_partitioning().num_partitions)):
        # cooperative cancellation: a fired token (ctx.cancel, the
        # slow-query killer) stops the collect at a batch boundary
        check_cancel()
        parts.append(batch.to_pydict())
    if not parts:
        return {f.name: np.asarray([]) for f in phys.output_schema().fields}
    return concat_pydicts(parts)


def collect_physical_cached(phys: PhysicalPlan,
                            settings=None) -> Dict[str, np.ndarray]:
    """:func:`collect_physical` behind the plan-fingerprint result
    cache (cache/results.py). The library-level surface for callers
    without a BallistaContext (the client collect path hooks the cache
    itself, earlier, to also skip prewarm/priming on a hit). Plans with
    unsignable leaves execute normally every time."""
    from .cache import results as _results

    if not _results.result_cache_enabled(settings):
        return collect_physical(phys)
    key = _results.plan_key(phys, settings)
    cache = _results.process_result_cache()
    data = cache.lookup(key)
    if data is not None:
        return data
    data = collect_physical(phys)
    cache.fill(key, data)
    return data


def collect(plan: LogicalPlan, options=None):
    """Logical plan -> pandas DataFrame (optimize, plan, execute, gather)."""
    import pandas as pd

    from .physical.fusion import maybe_fuse

    return pd.DataFrame(
        collect_physical(maybe_fuse(plan_logical(plan, options))))
