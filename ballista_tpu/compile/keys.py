"""Stable, hashable compile-cache keys.

A governed key must capture everything a traced function reads from
*Python* state (operator mode, expressions, schemas, static capacities).
Whatever the function reads from its *traced arguments* — array shapes,
dtypes, pytree structure, the dictionaries riding in batch aux-data — is
re-specialized by jax's own trace cache and must NOT be in the key, or
sharing across operator instances (the whole point of the governor)
breaks.

``fingerprint`` turns expression trees and schemas into hashable tuples
by value: two operator instances built from the same logical plan (e.g.
before and after an adaptive re-plan) produce equal fingerprints and so
share one compiled entry.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def fingerprint(obj: Any):
    """Hashable value-signature of plan configuration objects.

    Covers the engine's expression AST generically (class name + public
    attributes, recursively), frozen datatypes (already hashable by
    value), and plain containers. Unknown objects fall back to
    ``(classname, repr)`` — safe for key purposes as long as their repr
    reflects their trace-relevant state."""
    if obj is None or isinstance(obj, (str, int, float, bool, bytes)):
        return obj
    if isinstance(obj, (tuple, list)):
        return tuple(fingerprint(x) for x in obj)
    if isinstance(obj, (set, frozenset)):
        return ("set",) + tuple(sorted(fingerprint(x) for x in obj))
    if isinstance(obj, dict):
        return tuple(sorted((str(k), fingerprint(v))
                            for k, v in obj.items()))
    if isinstance(obj, np.generic):
        # np.generic is host-resident by construction, never a device sync
        # ballista: ignore[sync-span]
        return obj.item()
    if isinstance(obj, np.ndarray):
        return ("ndarray", obj.shape, str(obj.dtype), obj.tobytes())
    mod = type(obj).__module__ or ""
    if mod.endswith(".datatypes"):
        return obj  # DataType/Field/Schema: frozen + hashable by value
    if mod.startswith("ballista_tpu"):
        d = getattr(obj, "__dict__", None)
        if d is not None:
            return (type(obj).__name__,) + tuple(
                sorted((k, fingerprint(v)) for k, v in d.items()
                       if not k.startswith("_"))
            )
    return (type(obj).__name__, repr(obj))
