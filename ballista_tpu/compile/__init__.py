"""Compile governor: kernel compilation as a managed, observable resource.

Three parts (see docs/compile_cache.md):

- :mod:`buckets`  — shape canonicalization: batch capacities quantize
  onto a geometric row-count ladder (``BALLISTA_SHAPE_BUCKETS*`` knobs)
  so uneven partitions hit a handful of compiled signatures;
- :mod:`governor` — the single process-wide jit cache replacing the
  per-instance/module ad-hoc dicts (adaptive re-plans now reuse every
  trace), with compile counts/seconds/cache hits flowing into operator
  metrics, EXPLAIN ANALYZE and ``BALLISTA_TRACE`` spans;
- :mod:`prewarm`  — optional AOT compilation of scan-side pipeline
  chains concurrent with parse/H2D (``BALLISTA_PREWARM=1``).

``dev/check_jit_sites.py`` (tier-1-run lint) keeps ``jax.jit`` call
sites from regrowing outside this package.
"""

from .buckets import (  # noqa: F401
    bucket_capacity,
    bucket_ladder,
    buckets_enabled,
    reconfigure,
)
from .governor import (  # noqa: F401
    MESH_NS_CAP,
    CompileGovernor,
    GovernedFunction,
    compile_stats,
    governed,
    governor,
    reset_compile_stats,
)
from .keys import fingerprint  # noqa: F401
from .prewarm import maybe_prewarm, prewarm_enabled  # noqa: F401
