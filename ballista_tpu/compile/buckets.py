"""Shape canonicalization: the row-count bucket ladder.

Every distinct batch capacity is a fresh XLA trace + compile, so the
engine quantizes capacities onto a small geometric ladder instead of
tracking exact row counts. A 6M-row scan and its 8 unevenly-sized
shuffle partitions then hit a handful of canonical signatures, and the
persistent compilation cache (keyed by HLO hash) gets a real chance to
hit across batches, runs, and fresh processes — the same batch-bucketing
technique static-shape inference stacks use for serving.

The ladder is ``floor * growth^k`` with both knobs power-of-two (XLA
tilings stay happy):

- ``BALLISTA_SHAPE_BUCKETS``         on/off (default on)
- ``BALLISTA_SHAPE_BUCKETS_FLOOR``   smallest rung (default 1024)
- ``BALLISTA_SHAPE_BUCKETS_GROWTH``  geometric step (default 2)

Correctness rides the engine's existing mask invariants: every batch
carries a ``selection`` live-row mask and a ``num_rows`` live count, and
padding rows are dead by construction (``ColumnBatch.from_numpy`` marks
rows past the logical count unselected), so a bucket-padded batch is
row-identical to an exactly-sized one for every operator.

With buckets off, ``bucket_capacity`` degrades to the exact power-of-two
rounding (``round_capacity``) the engine always used.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

DEFAULT_FLOOR = 1024
DEFAULT_GROWTH = 2

_cfg: Optional[Tuple[bool, int, int]] = None


def next_pow2(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= n (>= minimum). Local copy of
    columnar.round_capacity so this module has no engine imports."""
    cap = minimum
    while cap < n:
        cap <<= 1
    return cap


def _read_config() -> Tuple[bool, int, int]:
    enabled = os.environ.get("BALLISTA_SHAPE_BUCKETS", "on").lower() \
        not in ("0", "off", "false")
    try:
        floor = int(os.environ.get("BALLISTA_SHAPE_BUCKETS_FLOOR",
                                   str(DEFAULT_FLOOR)))
    except ValueError:
        floor = DEFAULT_FLOOR
    try:
        growth = int(os.environ.get("BALLISTA_SHAPE_BUCKETS_GROWTH",
                                    str(DEFAULT_GROWTH)))
    except ValueError:
        growth = DEFAULT_GROWTH
    # both knobs snap to powers of two so every rung is a power of two
    floor = next_pow2(max(floor, 8))
    growth = next_pow2(max(growth, 2), minimum=2)
    return enabled, floor, growth


def _config() -> Tuple[bool, int, int]:
    global _cfg
    if _cfg is None:
        _cfg = _read_config()
    return _cfg


def reconfigure() -> None:
    """Re-read the BALLISTA_SHAPE_BUCKETS* env (tests flip it)."""
    global _cfg
    _cfg = None


def buckets_enabled() -> bool:
    return _config()[0]


def bucket_capacity(n: int, minimum: int = 8) -> int:
    """Canonical capacity for ``n`` rows: the smallest ladder rung that
    holds them (never below ``minimum``). The batch-entry replacement
    for ``round_capacity`` — scans, shuffle reads, repartition outputs
    and compaction targets all quantize through here, so downstream jit
    caches see ladder rungs, not per-partition row counts."""
    enabled, floor, growth = _config()
    if not enabled:
        return next_pow2(n, minimum)
    cap = max(floor, next_pow2(max(minimum, 8)))
    while cap < n:
        cap *= growth
    return cap


def bucket_ladder(max_rows: int, minimum: int = 8) -> List[int]:
    """The ladder rungs covering [1, max_rows] — the bound on distinct
    capacities (and so on per-signature compiles) any input of up to
    ``max_rows`` rows can produce."""
    rungs: List[int] = []
    cap = bucket_capacity(1, minimum)
    while True:
        rungs.append(cap)
        if cap >= max_rows:
            return rungs
        cap = bucket_capacity(cap + 1, minimum)
