"""Fused-stage AOT export/load: skip re-tracing in fresh processes.

The XLA persistent compilation cache (PR 3) absorbs the *backend
compile* of a warm-disk cold start, but every fresh process still pays
jaxpr tracing + lowering for each program — the ~4.6s GIL-bound
``compile_trace_lower`` lane the PR-5 profiler pinned on cold q5. With
whole-stage fusion the programs are few and big, which makes them worth
serializing whole: when ``BALLISTA_FUSION_AOT_DIR`` is set, AOT-eligible
governed entries (``aot=True`` — every operator program routed through
``PhysicalPlan.governed_jit``: the fused ``agg.*`` stage programs plus
the join/sort/repartition/compact kernels whose first calls make up the
rest of the cold compile lane) export their compiled StableHLO via
``jax.export`` after the first real call, and a fresh process
*deserializes and runs* the artifact instead of re-tracing.

Correctness model:

- Traced programs bake Python-visible state into the HLO: the governed
  KEY fingerprints operator config (exprs/schemas/modes), and the
  artifact additionally fingerprints the *call*: every leaf's
  shape/dtype, the batch's schema, validity presence, and — critically
  — each dictionary's CONTENT (string comparisons and hash tables lower
  dictionary values into constants). Different data → different
  fingerprint → no artifact hit; never a wrong answer.
- Outputs are rebuilt from a structural proto saved with the artifact
  (schema + per-column dtype/validity/dictionary values). Dictionary
  objects are materialized ONCE per loaded artifact so identity-keyed
  downstream caches see stable objects.
- Everything is best-effort: any failure disables AOT for that entry
  and falls back to the normal governed jit path.

Artifacts are invalidated by name: the filename hashes the governed
key, the call fingerprint, the jax version and the backend platform.
Stale files are simply never hit; `BALLISTA_FUSION_AOT_DIR` can be
wiped at any time.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import threading
from typing import Any, Iterator, List, Optional, Tuple

import numpy as np

log = logging.getLogger("ballista.compile.aot")

_MISS = object()  # sentinel: no artifact for this call

# One background worker drains all export jobs sequentially: exports
# re-trace + backend-compile whole programs, so an uncapped
# thread-per-artifact would compete with the measured query for every
# core. The queue is bounded; overflow drops the export (a later
# process simply re-tries).
_EXPORT_QUEUE_CAP = 64
# per-GC bound on same-revision artifacts kept on disk (oldest pruned)
_DIR_CAP = 512
# per-entry bound on exported call fingerprints: an entry whose
# fingerprints embed per-dataset dictionary content would otherwise
# export (re-trace + compile, on the worker) once per distinct dataset
# forever — past this many, later variants just run the jit path
_ENTRY_EXPORT_CAP = 8
_export_queue: list = []
_export_lock = threading.Lock()
_export_worker: Optional[threading.Thread] = None


def _enqueue_export(job) -> None:
    global _export_worker
    with _export_lock:
        if len(_export_queue) >= _EXPORT_QUEUE_CAP:
            return
        _export_queue.append(job)
        if _export_worker is None or not _export_worker.is_alive():
            _export_worker = threading.Thread(
                target=_drain_exports, name="ballista-aot-export",
                daemon=True)
            _export_worker.start()


def _drain_exports() -> None:
    global _export_worker
    from .governor import _tls

    # exports duplicate compiles the query already did (or will do):
    # keep them out of the process-wide compile stats bench.py reports
    _tls.suppress_stats = True
    _gc_stale_artifacts()
    while True:
        with _export_lock:
            if not _export_queue:
                # clear the slot BEFORE returning (still under the
                # lock): an enqueuer racing our exit must see either a
                # non-empty queue (we drain it) or no live worker (it
                # spawns one) — never a dying worker it trusts
                _export_worker = None
                return
            job = _export_queue.pop(0)
        try:
            job()
        except Exception:  # noqa: BLE001 - export is best-effort
            log.exception("AOT export job failed")


_GC_DONE = False


def _gc_stale_artifacts() -> None:
    """Unlink artifacts exported by OTHER code revisions (their
    -src<fp> filename component can never match again): without this,
    every source edit would orphan a full program set in a directory
    bench.py populates by default. Once per process, best-effort."""
    global _GC_DONE
    if _GC_DONE:
        return
    _GC_DONE = True
    d = aot_dir()
    if d is None or not os.path.isdir(d):
        return
    tag = f"-src{_code_fingerprint()}.aot"
    try:
        current = []
        for f in os.listdir(d):
            if not f.endswith(".aot"):
                continue
            p = os.path.join(d, f)
            if f.endswith(tag):
                current.append(p)
                continue
            try:
                os.unlink(p)
            except OSError:
                pass
        # same-revision artifacts are keyed on data content too (call
        # fingerprints embed dictionary values), so changing datasets
        # mint files that may never hit again: bound the directory by
        # count, oldest first
        if len(current) > _DIR_CAP:
            current.sort(key=lambda p: os.path.getmtime(p))
            for p in current[:-_DIR_CAP]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
    except OSError:
        pass


def aot_dir() -> Optional[str]:
    d = os.environ.get("BALLISTA_FUSION_AOT_DIR", "")
    return d or None


def _platform() -> str:
    import jax

    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 - backend not ready
        return "unknown"


_CODE_FP: Optional[str] = None


def _code_fingerprint() -> str:
    """Content hash of the engine's own Python sources, computed once
    per process. Artifacts bake KERNEL CODE, not just operator config —
    a bugfix to e.g. kernels/aggregate.py must invalidate every
    artifact its old self produced, and the governed key only
    fingerprints config. Riding in the filename makes stale-after-
    upgrade artifacts inert instead of silently serving old programs."""
    global _CODE_FP
    if _CODE_FP is None:
        h = hashlib.sha1()
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for root, dirs, files in os.walk(pkg):
            dirs[:] = sorted(d for d in dirs if d != "__pycache__")
            for f in sorted(files):
                if f.endswith(".py"):
                    p = os.path.join(root, f)
                    h.update(os.path.relpath(p, pkg).encode())
                    with open(p, "rb") as fh:
                        h.update(fh.read())
        _CODE_FP = h.hexdigest()[:12]
    return _CODE_FP


# ---------------------------------------------------------------------------
# call fingerprinting (stable across processes)
# ---------------------------------------------------------------------------


class _AotUnsupported(Exception):
    """Args/outputs outside the shapes this module serializes."""


def _args_fingerprint(args: tuple) -> str:
    from ..columnar import ColumnBatch

    import jax

    def walk(obj) -> tuple:
        if obj is None:
            return ("none",)
        if isinstance(obj, (tuple, list)):
            return ("seq",) + tuple(walk(x) for x in obj)
        if isinstance(obj, dict):
            return ("dict",) + tuple(
                (str(k), walk(obj[k])) for k in sorted(obj))
        if isinstance(obj, ColumnBatch):
            # dictionary identity = registry epoch (a vectorized content
            # fingerprint, cached per instance): O(1) at call time, and
            # a registry APPEND mints a new epoch for new versions while
            # batches still carrying older versions keep their keys — so
            # dictionary churn no longer re-keys (or re-hashes, via the
            # old per-value Python loop) exported programs
            from ..columnar_registry import fingerprint as _dict_fp

            return ("batch", repr(obj.schema), tuple(
                (repr(c.dtype), c.validity is not None,
                 _dict_fp(c.dictionary)
                 if c.dictionary is not None else None,
                 tuple(c.values.shape), str(c.values.dtype))
                for c in obj.columns))
        if hasattr(obj, "shape") and hasattr(obj, "dtype"):
            return ("arr", tuple(obj.shape), str(obj.dtype))
        # other registered pytree nodes (e.g. the join kernel's
        # BuildTable dataclass): hash the treedef repr + leaf avals.
        # If a node's treedef repr is not process-stable (identity
        # reprs), the fingerprint never matches across processes and
        # AOT silently never hits — degraded, never wrong.
        leaves, td = jax.tree_util.tree_flatten(obj)
        if not leaves and repr(td).find("object at 0x") < 0:
            return ("node", repr(td))
        if leaves and all(hasattr(l, "shape") for l in leaves) \
                and repr(td).find("object at 0x") < 0:
            return ("node", repr(td)) + tuple(walk(l) for l in leaves)
        raise _AotUnsupported(type(obj).__name__)

    return hashlib.sha1(repr(walk(args)).encode()).hexdigest()


# ---------------------------------------------------------------------------
# output protos: structural description + leaf consumption
# ---------------------------------------------------------------------------


def _encode_out(obj) -> tuple:
    """Abstract output -> picklable structural proto. Dictionaries are
    stored by VALUE (plain lists) plus their registry stamp, so loading
    resolves the interned in-process instance (identity shared with the
    scans — downstream identity-keyed caches and unify no-ops keep
    working across an AOT load) and only builds a fresh ``Dictionary``
    when the stamp misses."""
    from ..columnar import ColumnBatch
    from ..columnar_registry import REGISTRY

    if obj is None:
        return ("none",)
    if isinstance(obj, ColumnBatch):
        return ("batch", obj.schema, tuple(
            (c.dtype, c.validity is not None,
             None if c.dictionary is None
             else (list(c.dictionary.values),
                   REGISTRY.stamp_of(c.dictionary)))
            for c in obj.columns))
    if isinstance(obj, (tuple, list)):
        return ("seq", isinstance(obj, tuple),
                tuple(_encode_out(x) for x in obj))
    if isinstance(obj, dict):
        # jax flattens dicts in sorted-key order; decode mirrors it
        return ("map", tuple(sorted(obj)),
                tuple(_encode_out(obj[k]) for k in sorted(obj)))
    if hasattr(obj, "shape"):
        return ("leaf",)
    import dataclasses

    if dataclasses.is_dataclass(obj):
        # registered-dataclass pytree nodes (kernels.join.BuildTable):
        # jax flattens data fields in declaration order; decode rebuilds
        # by importing the class and calling it positionally
        cls = type(obj)
        return ("dc", f"{cls.__module__}:{cls.__qualname__}",
                tuple(_encode_out(getattr(obj, f.name))
                      for f in dataclasses.fields(obj)))
    raise _AotUnsupported(type(obj).__name__)


def _materialize_dicts(proto: tuple) -> tuple:
    """Proto -> proto with Dictionary objects resolved ONCE: a registry
    stamp (or matching content epoch) yields the live interned
    instance; otherwise the values are adopted so every artifact (and
    every later load) shares one identity per content."""
    from ..columnar_registry import REGISTRY

    kind = proto[0]
    if kind == "batch":
        metas = tuple(
            (dt, hv,
             REGISTRY.adopt(dv[1], np.asarray(dv[0], dtype=object))
             if dv is not None else None)
            for dt, hv, dv in proto[2])
        return ("batch", proto[1], metas)
    if kind == "seq":
        return ("seq", proto[1],
                tuple(_materialize_dicts(x) for x in proto[2]))
    if kind == "map":
        return ("map", proto[1],
                tuple(_materialize_dicts(x) for x in proto[2]))
    if kind == "dc":
        return ("dc", proto[1],
                tuple(_materialize_dicts(x) for x in proto[2]))
    return proto


def _decode_out(proto: tuple, leaves: Iterator):
    """Rebuild the output pytree, consuming ``leaves`` in the same
    order ``jax.tree_util.tree_flatten`` produced them (ColumnBatch
    flattening: per column values[, validity], then selection,
    num_rows — see columnar._flatten_batch)."""
    from ..columnar import Column, ColumnBatch

    kind = proto[0]
    if kind == "batch":
        schema, metas = proto[1], proto[2]
        cols: List[Column] = []
        for dt, has_v, d in metas:
            values = next(leaves)
            validity = next(leaves) if has_v else None
            cols.append(Column(values, dt, validity, d))
        selection = next(leaves)
        num_rows = next(leaves)
        return ColumnBatch(schema, cols, selection, num_rows)
    if kind == "seq":
        as_tuple, items = proto[1], proto[2]
        seq = [_decode_out(x, leaves) for x in items]
        return tuple(seq) if as_tuple else seq
    if kind == "map":
        keys, items = proto[1], proto[2]
        return {k: _decode_out(x, leaves) for k, x in zip(keys, items)}
    if kind == "none":
        return None
    if kind == "dc":
        import importlib

        mod, _, qual = proto[1].partition(":")
        cls = importlib.import_module(mod)
        for part in qual.split("."):
            cls = getattr(cls, part)
        return cls(*(_decode_out(x, leaves) for x in proto[2]))
    return next(leaves)


# ---------------------------------------------------------------------------
# the per-entry dispatcher
# ---------------------------------------------------------------------------


class AotEntry:
    """AOT state for one governed entry: per-call-fingerprint loaded
    artifacts, pending exports, and a disabled latch on any failure."""

    __slots__ = ("key", "key_hash", "loaded", "exporting", "disabled",
                 "lock")

    def __init__(self, key: tuple):
        self.key = key
        self.key_hash = hashlib.sha1(repr(key).encode()).hexdigest()[:16]
        self.loaded: dict = {}     # call fp -> callable | None (no file)
        self.exporting: set = set()
        self.disabled = False
        self.lock = threading.Lock()

    def _path(self, fp: str) -> str:
        import jax

        name = (f"{self.key_hash}-{fp[:16]}-{_platform()}"
                f"-jax{jax.__version__}-src{_code_fingerprint()}.aot")
        return os.path.join(aot_dir(), name)

    def call(self, gf, args: tuple):
        """Serve ``args`` from a loaded artifact, or return ``_MISS``
        (and maybe schedule a background export) for the normal path."""
        if self.disabled or aot_dir() is None:
            return _MISS
        try:
            fp = _args_fingerprint(args)
        except _AotUnsupported:
            self.disabled = True
            return _MISS
        fn = self.loaded.get(fp, _MISS)
        if fn is _MISS:
            with self.lock:
                # one load per fingerprint: concurrent partition
                # executions must share ONE materialized artifact (its
                # output Dictionary identities are the per-artifact
                # constants downstream identity-keyed caches rely on)
                fn = self.loaded.get(fp, _MISS)
                if fn is _MISS:
                    fn = self._load(fp)
        if fn is not None:
            import jax

            try:
                flat, _ = jax.tree_util.tree_flatten(args)
                return fn(flat)
            except Exception as e:  # noqa: BLE001 - stale/alien artifact
                # deserialization succeeded but the CALL failed (e.g. an
                # artifact from a different jaxlib build with the same
                # jax version tag): disable the entry and fall back to
                # the normal jit path — a cache dir must never be able
                # to fail a query
                log.warning("AOT artifact call failed for %r (%s); "
                            "disabling AOT for this entry",
                            self.key[:1], e)
                self.disabled = True
                return _MISS
        # no artifact: run the normal path; export once in the background
        with self.lock:
            want_export = (fp not in self.exporting
                           and len(self.exporting) < _ENTRY_EXPORT_CAP)
            if want_export:
                self.exporting.add(fp)
        if want_export:
            self._export_async(gf, args, fp)
        return _MISS

    def _load(self, fp: str):
        path = self._path(fp)
        fn = None
        try:
            if os.path.exists(path):
                with open(path, "rb") as fh:
                    blob = pickle.load(fh)
                from jax import export as jexport

                exported = jexport.deserialize(blob["exported"])
                proto = _materialize_dicts(blob["out_proto"])

                def run(flat, _e=exported, _p=proto):
                    out_flat = _e.call(*flat)
                    return _decode_out(_p, iter(out_flat))

                fn = run
                from ..observability import trace_event
                from .governor import _STATS

                _STATS["aot_loads"] += 1
                trace_event("compile.aot", action="load",
                            key=repr(self.key)[:160], path=path)
        except Exception as e:  # noqa: BLE001 - fall back, stay correct
            log.warning("AOT load failed for %r (%s); falling back to "
                        "jit", self.key[:1], e)
            fn = None
        self.loaded[fp] = fn
        return fn

    def _export_async(self, gf, args: tuple, fp: str) -> None:
        """Queue serialization of this entry's program for ``args`` on
        the shared export worker (re-traces once off the hot path; the
        artifact pays for itself on every later process)."""
        import jax

        try:
            wrapped = gf.fn.__wrapped__
        except AttributeError:
            self.disabled = True
            return
        leaves, in_tree = jax.tree_util.tree_flatten(args)
        avals = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in leaves]

        def work():
            try:
                out_abs = jax.eval_shape(wrapped,
                                         *jax.tree_util.tree_unflatten(
                                             in_tree, avals))
                proto = _encode_out(out_abs)

                def flat_fn(*flat):
                    out = wrapped(*jax.tree_util.tree_unflatten(in_tree,
                                                                flat))
                    return jax.tree_util.tree_flatten(out)[0]

                from jax import export as jexport

                exported = jexport.export(jax.jit(flat_fn))(*avals)
                blob = pickle.dumps({"exported": exported.serialize(),
                                     "out_proto": proto})
                path = self._path(fp)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "wb") as fh:
                    fh.write(blob)
                os.replace(tmp, path)
                from ..observability import trace_event
                from .governor import _STATS

                _STATS["aot_exports"] += 1
                trace_event("compile.aot", action="export",
                            key=repr(self.key)[:160], path=path)
            except Exception as e:  # noqa: BLE001 - export best-effort
                log.warning("AOT export failed for %r (%s)",
                            self.key[:1], e)
                self.disabled = True

        _enqueue_export(work)


def make_entry(key: tuple) -> Optional[AotEntry]:
    """AotEntry for a governed key, or None when AOT is off."""
    if aot_dir() is None:
        return None
    return AotEntry(key)
