"""Unified process-wide compile cache + compile observability.

Before this subsystem, every operator kept its own ad-hoc jit dict
(``self._jit_cache`` / ``self._jit_probe`` / module-level ``*_JITS``
maps), so adaptive re-planning — which rebuilds operator instances —
threw away every trace, and nobody could say how much of a query was
compile time. The governor replaces them all:

- **One cache.** ``governed(key, build)`` returns the process-wide
  compiled callable for ``key``; the first caller's ``build()`` supplies
  the python function and the governor owns the single ``jax.jit`` call
  in the codebase (``dev/check_jit_sites.py`` lints that this stays
  true). Keys start with a namespace string and must capture everything
  the trace reads from Python state (operator signatures — see
  ``keys.py``); anything read from *traced arguments* is re-specialized
  by jax itself, so it never belongs in the key.
- **Observability.** A ``jax.monitoring`` listener attributes backend
  compiles (count + seconds) and persistent-cache hits to the governed
  call that triggered them: per-operator ``compile_count`` /
  ``elapsed_compile`` land on the caller's MetricsSet (so EXPLAIN
  ANALYZE shows them), ``BALLISTA_TRACE`` gets a ``compile.jit`` span,
  and :func:`compile_stats` exposes the process-wide totals (bench.py
  emits them every run).
- **Bounded namespaces.** Mesh-path entries key on pytree structures
  that pin per-query ``Dictionary`` objects; their namespaces carry an
  LRU cap exactly like the bounded dicts they replaced.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional

__all__ = [
    "MESH_NS_CAP",
    "GovernedFunction",
    "CompileGovernor",
    "governed",
    "governor",
    "compile_stats",
    "reset_compile_stats",
]

_PERF = time.perf_counter

# LRU bound for mesh-path namespaces (mesh.compact / mesh.chain /
# mesh.agg_spmd / mesh.join_spmd / mesh.replicate / mesh.run_spmd):
# their keys hold meshes and pytree structures whose aux-data pins
# identity-hashed per-query Dictionary objects, so they stay much
# tighter than the generic BALLISTA_JIT_CACHE_ENTRIES bound.
MESH_NS_CAP = 32

# process-wide totals (plain ints/floats under the GIL — same benign-race
# policy as observability.metrics counters)
_STATS: Dict[str, Any] = {
    "backend_compiles": 0,      # actual XLA backend compilations
    "compile_seconds": 0.0,     # time inside those compilations
    "trace_seconds": 0.0,       # jaxpr tracing time (re-traces included)
    "persistent_cache_hits": 0,  # disk-cache hits that skipped a compile
    "governed_calls": 0,        # calls through governed functions
    "entry_hits": 0,            # governed-key lookups that found an entry
    "entries_built": 0,         # governed-key lookups that built one
    "prewarm_compiles": 0,      # compiles triggered by the prewarm pass
    "entry_trace_evictions": 0,  # within-entry jax trace-cache clears
    "aot_loads": 0,             # fused-stage programs deserialized from
                                # BALLISTA_FUSION_AOT_DIR (no re-trace)
    "aot_exports": 0,           # fused-stage programs serialized to it
}

_tls = threading.local()


class _Frame:
    """Per-governed-call attribution frame (thread-local stack)."""

    __slots__ = ("compiles", "compile_secs", "pcache_hits")

    def __init__(self):
        self.compiles = 0
        self.compile_secs = 0.0
        self.pcache_hits = 0


_listener_lock = threading.Lock()
_listener_registered = False
# False once registration failed: compile accounting then falls back to
# first-call wall-clock per entry (the pre-governor approximation)
_monitoring_ok = True


def _ensure_listener() -> None:
    global _listener_registered, _monitoring_ok
    if _listener_registered:
        return
    with _listener_lock:
        if _listener_registered:
            return

        def on_duration(name: str, secs: float, **kw) -> None:
            if getattr(_tls, "suppress_stats", False):
                return  # AOT export worker: duplicate compiles
            if name == "/jax/core/compile/backend_compile_duration":
                _STATS["backend_compiles"] += 1
                _STATS["compile_seconds"] += secs
                f = getattr(_tls, "frame", None)
                if f is not None:
                    f.compiles += 1
                    f.compile_secs += secs
            elif name == "/jax/core/compile/jaxpr_trace_duration":
                _STATS["trace_seconds"] += secs

        def on_event(name: str, **kw) -> None:
            if getattr(_tls, "suppress_stats", False):
                return
            if name == "/jax/compilation_cache/cache_hits":
                _STATS["persistent_cache_hits"] += 1
                f = getattr(_tls, "frame", None)
                if f is not None:
                    f.pcache_hits += 1

        try:
            # the registration calls sit INSIDE the guard: a jax where
            # monitoring imports but lacks/renamed the register_*
            # functions must degrade to fallback mode, not crash every
            # governed call
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(on_duration)
            monitoring.register_event_listener(on_event)
        except Exception:  # noqa: BLE001 - no monitoring: fallback mode
            import warnings

            _monitoring_ok = False
            warnings.warn(
                "jax.monitoring unavailable: compile counts fall back to "
                "first-call wall-clock per governed entry",
                RuntimeWarning, stacklevel=3)
        _listener_registered = True


class GovernedFunction:
    """One governed compile-cache entry: a ``jax.jit`` wrapper plus
    per-entry compile accounting. Shared across operator instances with
    the same signature — jax's own trace cache (keyed on treedef/avals)
    handles shape and dictionary variation within the entry."""

    __slots__ = ("key", "fn", "calls", "compiles", "compile_seconds",
                 "pcache_hits", "aot")

    def __init__(self, key: tuple, fn: Callable):
        self.key = key
        self.fn = fn
        self.calls = 0
        self.compiles = 0
        self.compile_seconds = 0.0
        self.pcache_hits = 0
        # fused-stage AOT state (compile/aot.py), or None: set at entry
        # creation when the caller opted in AND BALLISTA_FUSION_AOT_DIR
        # is configured
        self.aot = None

    def __call__(self, *args, **kwargs):
        return self.call_with(None, *args, **kwargs)

    # Within-entry trace growth bound: jax's jit cache inside one entry
    # specializes per treedef, and treedefs carry identity-hashed
    # per-query Dictionary objects — a stable-keyed entry re-run over
    # refreshed data would otherwise accumulate one executable (and pin
    # one run's string tables) per run, forever. Checked every
    # _TRACE_CHECK_EVERY calls; clearing drops in-memory traces only
    # (the persistent disk cache still holds the compilations).
    _TRACE_CHECK_EVERY = 64

    @staticmethod
    def _traces_per_entry() -> int:
        try:
            return int(os.environ.get("BALLISTA_JIT_TRACES_PER_ENTRY",
                                      "128"))
        except ValueError:
            return 128

    def _maybe_trim_traces(self) -> None:
        if self.calls % self._TRACE_CHECK_EVERY:
            return
        bound = self._traces_per_entry()
        if bound <= 0:
            return
        try:
            if self.fn._cache_size() > bound:
                self.fn._clear_cache()
                _STATS["entry_trace_evictions"] += 1
        except Exception:  # noqa: BLE001 - private jax API drifted
            pass

    def call_with(self, metrics, *args, **kwargs):
        """Invoke, attributing any compile this call triggers to
        ``metrics`` (an observability MetricsSet, or None)."""
        _STATS["governed_calls"] += 1
        self.calls += 1
        if self.aot is not None and not kwargs:
            # serve the whole program from a deserialized artifact when
            # one matches this call's content fingerprint — no trace or
            # lower; the exported module's one-time backend compile (or
            # disk-cache retrieval) still happens inside the call and is
            # attributed through the same frame machinery, so EXPLAIN
            # ANALYZE and the profiler's compile lane stay honest. Any
            # AOT failure falls through to the normal jit path.
            from .aot import _MISS

            prev = getattr(_tls, "frame", None)
            frame = _Frame()
            _tls.frame = frame
            t0 = _PERF()
            try:
                out = self.aot.call(self, args)
            finally:
                _tls.frame = prev
            if out is not _MISS:
                if frame.compiles or frame.pcache_hits:
                    self._record(frame, _PERF() - t0, metrics,
                                 aot=True)
                return out
        self._maybe_trim_traces()
        prev = getattr(_tls, "frame", None)
        frame = _Frame()
        _tls.frame = frame
        t0 = _PERF()
        try:
            return self.fn(*args, **kwargs)
        finally:
            _tls.frame = prev
            if not _monitoring_ok and self.calls == 1:
                # no monitoring events on this jax: approximate with the
                # entry's first call (includes that call's execution,
                # like the old PipelineOp measurement did)
                frame.compiles = 1
                frame.compile_secs = _PERF() - t0
                _STATS["backend_compiles"] += 1
                _STATS["compile_seconds"] += frame.compile_secs
            # a pure disk-cache hit compiles nothing but still traced,
            # lowered and deserialized — record it too, or the warm-disk
            # cold start (the scenario this subsystem optimizes) shows
            # zero compile activity in EXPLAIN ANALYZE
            if frame.compiles or frame.pcache_hits:
                self._record(frame, _PERF() - t0, metrics)

    def _record(self, frame: _Frame, call_secs: float, metrics,
                aot: bool = False) -> None:
        self.compiles += frame.compiles
        self.compile_seconds += frame.compile_secs
        self.pcache_hits += frame.pcache_hits
        if metrics is not None:
            # elapsed_compile is the whole first call (upper bound: it
            # includes the first batch's execution, but compile dominates
            # by orders of magnitude on a persistent-cache miss). An
            # AOT-loaded program never traces, so only the measured
            # backend compile/retrieval counts for it.
            if frame.compiles:
                metrics.add_counter("compile_count", frame.compiles)
            metrics.add_time("elapsed_compile",
                             frame.compile_secs if aot else call_secs)
            if frame.pcache_hits:
                metrics.add_counter("persistent_cache_hits",
                                    frame.pcache_hits)
        from ..observability.tracing import trace_event

        # compile.aot records let the profiler's compile_trace_lower
        # lane count only the real compile/retrieval seconds for loaded
        # programs (their first-call execution is execution, not
        # trace/lower)
        trace_event("compile.aot" if aot else "compile.jit",
                    key=_render_key(self.key),
                    compiles=frame.compiles,
                    compile_seconds=round(frame.compile_secs, 6),
                    persistent_cache_hits=frame.pcache_hits,
                    call_seconds=round(call_secs, 6))

    def warm(self, *abstract_args, **abstract_kwargs) -> bool:
        """AOT-compile for the given (abstract) arguments — the prewarm
        pass uses this to populate the in-process and persistent caches
        without executing anything. Returns True when the lowering
        compiled cleanly."""
        prev = getattr(_tls, "frame", None)
        frame = _Frame()
        _tls.frame = frame
        try:
            self.fn.lower(*abstract_args, **abstract_kwargs).compile()
        except Exception:  # noqa: BLE001 - prewarm is best-effort
            return False
        finally:
            _tls.frame = prev
            if frame.compiles or frame.pcache_hits:
                _STATS["prewarm_compiles"] += frame.compiles
                self.compiles += frame.compiles
                self.compile_seconds += frame.compile_secs
                self.pcache_hits += frame.pcache_hits
        return True


class _BoundGoverned:
    """A governed function bound to one operator's MetricsSet."""

    __slots__ = ("gf", "metrics")

    def __init__(self, gf: GovernedFunction, metrics):
        self.gf = gf
        self.metrics = metrics

    def __call__(self, *args, **kwargs):
        return self.gf.call_with(self.metrics, *args, **kwargs)

    def warm(self, *args, **kwargs) -> bool:
        return self.gf.warm(*args, **kwargs)


def _render_key(key: tuple) -> str:
    try:
        return repr(key)[:200]
    except Exception:  # noqa: BLE001 - unreprable key component
        return str(key[0]) if key else "?"


def _default_ns_cap() -> int:
    """Default per-namespace LRU bound. Governed entries outlive
    operator instances (that's the point), so a long-lived server
    answering thousands of DISTINCT query shapes would otherwise pin
    executables — and, through treedef keys, per-query dictionaries —
    forever. 1024 is far above any single workload's entry count (the
    whole TPC-H suite builds a few hundred); raise or lower with
    BALLISTA_JIT_CACHE_ENTRIES."""
    try:
        return int(os.environ.get("BALLISTA_JIT_CACHE_ENTRIES", "1024"))
    except ValueError:
        return 1024


class CompileGovernor:
    """Process-wide registry of governed compile entries, grouped by the
    key's leading namespace string (per-namespace LRU caps)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spaces: Dict[str, OrderedDict] = {}
        self._caps: Dict[str, int] = {}

    def get(self, key: tuple, build: Callable[[], Callable], *,
            metrics=None, cap: Optional[int] = None,
            jit_kwargs: Optional[dict] = None, aot: bool = False):
        """The governed function for ``key`` (built via ``build()`` and
        jitted on first use). ``cap`` bounds the key's namespace (LRU).
        With ``metrics``, returns a bound wrapper that attributes
        compiles to that MetricsSet. ``aot=True`` opts the entry into
        fused-stage program serialization (compile/aot.py) when
        ``BALLISTA_FUSION_AOT_DIR`` is configured."""
        _ensure_listener()
        ns = key[0] if key else "default"
        with self._lock:
            space = self._spaces.get(ns)
            if space is None:
                space = self._spaces[ns] = OrderedDict()
            if cap is not None:
                self._caps[ns] = cap
            gf = space.get(key)
            if gf is not None:
                space.move_to_end(key)
                _STATS["entry_hits"] += 1
        if gf is not None and aot and gf.aot is None and not jit_kwargs:
            # the entry may predate BALLISTA_FUSION_AOT_DIR being set
            # (env is read at attach time); attach lazily so it still
            # exports/loads
            from .aot import make_entry

            gf.aot = make_entry(key)
        if gf is None:
            # build OUTSIDE the lock: build() may itself request governed
            # entries (e.g. a mesh SPMD program wrapping an aggregate's
            # grouped kernel), which would deadlock a held non-reentrant
            # lock. Racing builders are possible and cheap (jit wrapper
            # creation traces nothing); the first insert wins.
            import jax

            gf = GovernedFunction(key, jax.jit(build(),
                                               **(jit_kwargs or {})))
            if aot and not jit_kwargs:
                from .aot import make_entry

                gf.aot = make_entry(key)
            with self._lock:
                # re-fetch: clear() may have swapped the namespace dict
                # while we were building — inserting into the captured
                # (orphaned) dict would silently lose the entry
                space = self._spaces.setdefault(ns, OrderedDict())
                existing = space.get(key)
                if existing is not None:
                    gf = existing
                    space.move_to_end(key)
                    _STATS["entry_hits"] += 1
                else:
                    ns_cap = self._caps.get(ns, _default_ns_cap())
                    if ns_cap > 0:
                        while len(space) >= ns_cap:
                            space.popitem(last=False)
                    space[key] = gf
                    _STATS["entries_built"] += 1
        if metrics is None:
            return gf
        return _BoundGoverned(gf, metrics)

    def entries(self) -> int:
        with self._lock:
            return sum(len(s) for s in self._spaces.values())

    def entry_rows(self) -> list:
        """Per-entry accounting rows for ``system.compile``: signature,
        call/compile counts, elapsed compile seconds, persistent-cache
        hits, AOT loads. Snapshot under the lock; rendering outside."""
        with self._lock:
            snap = [(ns, gf) for ns, space in self._spaces.items()
                    for gf in space.values()]
        out = []
        for ns, gf in snap:
            aot_loads = 0
            if gf.aot is not None:
                # list() first: a concurrent query may be inserting a
                # freshly-loaded artifact under the entry lock, which
                # this read does not take
                aot_loads = sum(1 for v in list(gf.aot.loaded.values())
                                if v is not None)
            out.append({
                "namespace": ns,
                "signature": _render_key(gf.key),
                "calls": gf.calls,
                "compiles": gf.compiles,
                "compile_seconds": round(gf.compile_seconds, 6),
                "persistent_cache_hits": gf.pcache_hits,
                "aot_loads": aot_loads,
            })
        return out

    def namespace_sizes(self) -> Dict[str, int]:
        with self._lock:
            return {ns: len(s) for ns, s in self._spaces.items()}

    def clear(self, namespace: Optional[str] = None) -> None:
        """Drop entries (tests / memory pressure). Compiled executables
        are released; the persistent disk cache still holds them."""
        with self._lock:
            if namespace is None:
                self._spaces.clear()
            else:
                self._spaces.pop(namespace, None)


_GOVERNOR = CompileGovernor()


def governor() -> CompileGovernor:
    return _GOVERNOR


def governed(key: tuple, build: Callable[[], Callable], *, metrics=None,
             cap: Optional[int] = None,
             jit_kwargs: Optional[dict] = None, aot: bool = False):
    """Module-level shorthand for ``governor().get(...)``."""
    return _GOVERNOR.get(key, build, metrics=metrics, cap=cap,
                         jit_kwargs=jit_kwargs, aot=aot)


def compile_stats() -> Dict[str, Any]:
    """Snapshot of process-wide compile accounting."""
    _ensure_listener()
    out = dict(_STATS)
    out["entries"] = _GOVERNOR.entries()
    out["monitoring_available"] = _monitoring_ok
    return out


def reset_compile_stats() -> None:
    """Zero the process-wide counters (tests; entries stay cached)."""
    for k, v in list(_STATS.items()):
        _STATS[k] = 0.0 if isinstance(v, float) else 0
