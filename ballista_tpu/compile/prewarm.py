"""Optional prewarm pass: start compiling while the scan parses.

The cold path serializes parse -> host-to-device upload -> first compile
(BENCH_r05: parse 1.43s + h2d 1.08s sit entirely before the first XLA
compile). With the bucket ladder, the capacity a scan will emit is
predictable from its estimated row count BEFORE any byte is parsed — so
a background thread can AOT-compile the scan-side fused pipeline chains
at the predicted rung concurrently with parse/H2D.

Best-effort by design: utf8 columns get placeholder dictionaries, so a
chain whose trace bakes dictionary content (string-literal comparisons,
hash repartitioning) lowers to different HLO and the prewarm compile is
wasted — but never wrong, because the real call re-traces through the
same governed entry. Chains over numeric/date predicates (the common
TPC-H shape) produce identical HLO, and the persistent compilation cache
turns the real call's compile into a fast disk hit even though the
in-memory trace cache misses on the placeholder treedef.

Gated by ``BALLISTA_PREWARM`` (default off — an extra thread compiling
speculatively is the wrong default for test suites and tiny queries).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Tuple

from .buckets import bucket_capacity


def prewarm_enabled() -> bool:
    return os.environ.get("BALLISTA_PREWARM", "").lower() in (
        "1", "on", "true")


def abstract_batch(schema, cap: int):
    """ColumnBatch pytree of ``jax.ShapeDtypeStruct`` leaves — enough
    for ``jit.lower`` without any real data. utf8 columns carry empty
    placeholder dictionaries; no validity (scans attach validity only
    when the file actually has NULLs)."""
    import jax
    import numpy as np

    from ..columnar import Column, ColumnBatch, Dictionary

    cols = []
    for f in schema.fields:
        dt = f.dtype.device_dtype()
        shape = (cap, f.dtype.length) if f.dtype.kind == "list" else (cap,)
        cols.append(Column(
            jax.ShapeDtypeStruct(shape, dt), f.dtype, None,
            Dictionary([]) if f.dtype.kind == "utf8" else None,
        ))
    return ColumnBatch(
        schema, cols,
        jax.ShapeDtypeStruct((cap,), np.bool_),
        jax.ShapeDtypeStruct((), np.int32),
    )


def _scan_estimate(source) -> "Optional[Tuple[int, int]]":
    """(predicted per-partition emit capacity, estimated per-partition
    rows) of a table source, or None when it cannot be estimated —
    estimation may probe file metadata, so callers needing both figures
    share one call. Mirrors the quantization the sources apply at emit
    time (io/text.py / io/parquet.py)."""
    est = None
    try:
        est = source.estimated_rows()
    except Exception:  # noqa: BLE001 - estimation is best-effort
        return None
    if not est:
        return None
    nparts = max(source.num_partitions(), 1)
    per_part = max(est // nparts, 1)
    cap = bucket_capacity(per_part)
    # unwrap caching decorators: the emit cap lives on the inner scanner
    inner = source
    while not hasattr(inner, "_capacity") and hasattr(inner, "inner"):
        inner = inner.inner
    limit = getattr(inner, "_capacity", None)
    if isinstance(limit, int) and limit > 0:
        cap = min(cap, limit)
    return cap, per_part


def _scan_capacity_hint(source) -> Optional[int]:
    hint = _scan_estimate(source)
    return hint[0] if hint is not None else None


def _fused_capacity_hint(source) -> Optional[int]:
    """Predicted capacity of a fused stage's CONCATENATED scan input.
    A chunked scan emits full chunks at the scanner's capacity limit
    plus one remainder rung; the fused stage concats them (exact sum —
    see base.concat_batches). Best-effort like everything here."""
    hint = _scan_estimate(source)
    if hint is None:
        return None
    per_part, rows = hint
    if rows <= per_part:
        return per_part
    chunks, rem = divmod(rows, per_part)
    return chunks * per_part + (bucket_capacity(rem) if rem else 0)


def collect_targets(phys) -> List[Tuple[object, object]]:
    """(governed fn, abstract input batch) for every program whose
    first compile currently waits for parse + H2D to finish: fused
    aggregate stages rooted on a table scan (the whole-stage-fusion
    shape — prewarm and fusion share one key space), plus any bare
    pipeline chain still rooted on a scan (e.g. join build sides)."""
    from ..physical.base import PipelineOp
    from ..physical.fusion import FusedDistinctCountExec, FusedStageExec
    from ..physical.operators import ScanExec

    targets: List[Tuple[object, object]] = []
    seen = set()

    def scan_batch(source: ScanExec, fused: bool):
        cap = (_fused_capacity_hint(source.source) if fused
               else _scan_capacity_hint(source.source))
        if cap is None:
            return None
        try:
            return abstract_batch(source.output_schema(), cap)
        except Exception:  # noqa: BLE001 - exotic schema
            return None

    def walk(node, parent_is_pipeline: bool) -> None:
        if isinstance(node, (FusedStageExec, FusedDistinctCountExec)) \
                and id(node) not in seen:
            seen.add(id(node))
            if isinstance(node.source, ScanExec):
                batch = scan_batch(node.source, fused=True)
                if batch is not None:
                    if isinstance(node, FusedDistinctCountExec):
                        fn = node._get_fn(node.group_capacity)
                    elif node.group_exprs:
                        fn = node._get_grouped_fn(node.group_capacity,
                                                  batch.capacity)
                    else:
                        fn = node._get_scalar_fn()
                    targets.append((fn, batch))
            for c in node.children():
                walk(c, False)
            return
        is_pipe = isinstance(node, PipelineOp)
        if is_pipe and not parent_is_pipeline and id(node) not in seen:
            seen.add(id(node))
            chain, source = node._pipeline_chain()
            if isinstance(source, ScanExec):
                batch = scan_batch(source, fused=False)
                if batch is not None:
                    targets.append((node._fused_governed(), batch))
        for c in node.children():
            walk(c, is_pipe)

    walk(phys, False)
    return targets


def maybe_prewarm(phys) -> Optional[threading.Thread]:
    """Kick off background compilation of ``phys``'s scan-side pipeline
    chains (once per plan instance). Returns the thread, or None when
    disabled / nothing to warm. Fire-and-forget: compilation is pure, a
    racing foreground compile of the same program is just wasted work,
    never wrong."""
    if not prewarm_enabled() or getattr(phys, "_prewarmed", False):
        return None
    try:
        phys._prewarmed = True
    except AttributeError:  # exotic root without a __dict__
        return None
    try:
        targets = collect_targets(phys)
    except Exception:  # noqa: BLE001 - prewarm must never break a query
        return None
    if not targets:
        return None

    def run() -> None:
        for fn, batch in targets:
            fn.warm(batch)

    t = threading.Thread(target=run, name="ballista-prewarm", daemon=True)
    t.start()
    return t
