"""Logical plan: the relational algebra IR.

Mirrors the reference wire contract's logical plan surface (reference:
rust/core/proto/ballista.proto:164-179 ``LogicalPlanNode`` with variants
TableScan/Projection/Filter/Aggregate/Join/Limit/Sort/Repartition/
EmptyRelation/CreateExternalTable/Explain) re-designed as Python dataclasses
whose schemas are computed eagerly for binder/optimizer use.

``LogicalPlanBuilder`` provides the fluent construction API the reference
exposes through its DataFrame verbs (reference: rust/client/src/context.rs:
241-314).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional, Sequence, Tuple

from .datatypes import Field, Int64, Schema
from .errors import PlanError, SchemaError
from . import expr as ex


class LogicalPlan:
    """Base class for logical plan nodes."""

    def schema(self) -> Schema:
        raise NotImplementedError

    def children(self) -> List["LogicalPlan"]:
        return []

    def display(self) -> str:
        raise NotImplementedError

    def pretty(self, indent: int = 0) -> str:
        out = "  " * indent + self.display() + "\n"
        for c in self.children():
            out += c.pretty(indent + 1)
        return out


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------


class TableSource:
    """Provider interface for scannable tables (io layer implements it)."""

    def __deepcopy__(self, memo):
        # deep-copying a plan (e.g. inlining a registered view) must
        # SHARE sources, not clone their data/caches
        return self

    def table_schema(self) -> Schema:
        raise NotImplementedError

    def num_partitions(self) -> int:
        raise NotImplementedError

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        """Yield ColumnBatches for one partition."""
        raise NotImplementedError

    def source_descriptor(self) -> dict:
        """Serializable description {kind, path, ...} for plan serde."""
        raise NotImplementedError

    def estimated_rows(self) -> Optional[int]:
        """Cheap row-count estimate (file sizes / metadata); None=unknown."""
        return None

    def content_signature(self) -> Optional[tuple]:
        """Identity of the data this source serves, re-stat'd at call
        time (file sizes + mtimes). The result-cache key ingredient:
        None (the default) marks the source unsignable, making any plan
        over it uncacheable — memtables and system tables stay live."""
        return None

    def residency_key(self, partition: int,
                      projection=None) -> Optional[tuple]:
        """Device-residency cache key for one partition scan; None =
        this source never routes through the residency layer."""
        return None

    def is_resident(self, partition: int, projection=None) -> bool:
        """Whether this partition's scan output is device-resident
        right now (prefetch routing: no parse/H2D left to overlap)."""
        key = self.residency_key(partition, projection)
        if key is None:
            return False
        from .cache.residency import process_table_cache

        return process_table_cache().contains(key)

    def scan_cache_outcome(self, partition: int) -> Optional[str]:
        """Device-residency outcome of this partition's most recent
        scan (``hit``/``filled``/``miss``), for EXPLAIN ANALYZE; None
        when the source doesn't route through the residency layer."""
        outcomes = getattr(self, "_scan_outcomes", None)
        return outcomes.get(partition) if outcomes else None

    def _note_scan_outcome(self, partition: int):
        """Sink for ``cache.residency.serve_or_fill``: records the
        outcome per partition (benign last-writer-wins race, display
        only)."""

        def sink(outcome: str) -> None:
            outcomes = getattr(self, "_scan_outcomes", None)
            if outcomes is None:
                outcomes = self._scan_outcomes = {}
            outcomes[partition] = outcome

        return sink


@dataclass
class TableScan(LogicalPlan):
    table_name: str
    source: TableSource
    projection: Optional[Tuple[str, ...]] = None

    def schema(self) -> Schema:
        s = self.source.table_schema()
        if self.projection is not None:
            return s.project(self.projection)
        return s

    def display(self) -> str:
        p = f" projection={list(self.projection)}" if self.projection else ""
        return f"TableScan: {self.table_name}{p}"


@dataclass
class EmptyRelation(LogicalPlan):
    produce_one_row: bool = False

    def schema(self) -> Schema:
        return Schema([])

    def display(self) -> str:
        return "EmptyRelation"


# ---------------------------------------------------------------------------
# Unary nodes
# ---------------------------------------------------------------------------


@dataclass
class Projection(LogicalPlan):
    exprs: List[ex.Expr]
    input: LogicalPlan

    def schema(self) -> Schema:
        ins = self.input.schema()
        return Schema([e.to_field(ins) for e in self.exprs])

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        return f"Projection: {', '.join(e.name() for e in self.exprs)}"


@dataclass
class Filter(LogicalPlan):
    predicate: ex.Expr
    input: LogicalPlan

    def schema(self) -> Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        return f"Filter: {self.predicate.name()}"


@dataclass
class Aggregate(LogicalPlan):
    group_exprs: List[ex.Expr]
    agg_exprs: List[ex.Expr]  # AggregateExpr possibly wrapped in Alias
    input: LogicalPlan

    def schema(self) -> Schema:
        ins = self.input.schema()
        fields = [e.to_field(ins) for e in self.group_exprs]
        fields += [e.to_field(ins) for e in self.agg_exprs]
        return Schema(fields)

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        g = ", ".join(e.name() for e in self.group_exprs)
        a = ", ".join(e.name() for e in self.agg_exprs)
        return f"Aggregate: groupBy=[{g}], aggr=[{a}]"


@dataclass
class Sort(LogicalPlan):
    sort_exprs: List[ex.SortExpr]
    input: LogicalPlan

    def schema(self) -> Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        return f"Sort: {', '.join(e.name() for e in self.sort_exprs)}"


@dataclass
class Limit(LogicalPlan):
    n: int
    input: LogicalPlan

    def schema(self) -> Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        return f"Limit: {self.n}"


@dataclass
class Repartition(LogicalPlan):
    """Round-robin or hash repartition (reference: ballista.proto:219-230)."""

    input: LogicalPlan
    num_partitions: int
    hash_exprs: Optional[List[ex.Expr]] = None  # None = round-robin

    def schema(self) -> Schema:
        return self.input.schema()

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        kind = (
            f"hash[{', '.join(e.name() for e in self.hash_exprs)}]"
            if self.hash_exprs
            else "round-robin"
        )
        return f"Repartition: {kind} into {self.num_partitions}"


# ---------------------------------------------------------------------------
# Join
# ---------------------------------------------------------------------------

JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti")


@dataclass
class Join(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    on: List[Tuple[str, str]]  # (left_col, right_col)
    how: str = "inner"
    # SQL NOT IN lowering: anti join where any NULL build key empties the
    # result and NULL probe keys are excluded
    null_aware: bool = False

    def __post_init__(self):
        if self.how not in JOIN_TYPES:
            raise PlanError(f"unknown join type {self.how}")

    def schema(self) -> Schema:
        ls, rs = self.left.schema(), self.right.schema()
        if self.how in ("semi", "anti"):
            return ls
        # drop duplicate right-side join columns that share a name
        lf = list(ls.fields)
        seen = {f.name for f in lf}
        rf = [f for f in rs.fields if f.name not in seen]
        return Schema(lf + rf)

    def children(self) -> List[LogicalPlan]:
        return [self.left, self.right]

    def display(self) -> str:
        on = ", ".join(f"{l}={r}" for l, r in self.on)
        return f"Join: how={self.how} on=[{on}]"


# ---------------------------------------------------------------------------
# Explain
# ---------------------------------------------------------------------------

# (plan_type, plan) rows, matching the surface the reference's users see
# through DataFusion's EXPLAIN output table. Single source of truth: the
# physical ExplainExec imports this.
def _explain_schema() -> Schema:
    from .datatypes import Utf8

    return Schema([Field("plan_type", Utf8, False),
                   Field("plan", Utf8, False)])


EXPLAIN_SCHEMA = _explain_schema()


@dataclass
class Explain(LogicalPlan):
    input: LogicalPlan
    verbose: bool = False
    # EXPLAIN ANALYZE: execute the input and annotate the rendered
    # physical plan with live operator metrics
    analyze: bool = False

    def schema(self) -> Schema:
        return EXPLAIN_SCHEMA

    def children(self) -> List[LogicalPlan]:
        return [self.input]

    def display(self) -> str:
        return ("Explain" + (" analyze" if self.analyze else "")
                + (" verbose" if self.verbose else ""))


# ---------------------------------------------------------------------------
# Builder (fluent API used by DataFrame + SQL planner)
# ---------------------------------------------------------------------------


class LogicalPlanBuilder:
    def __init__(self, plan: LogicalPlan):
        self.plan = plan

    @staticmethod
    def scan(table_name: str, source: TableSource) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(TableScan(table_name, source))

    @staticmethod
    def empty() -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(EmptyRelation())

    def project(self, exprs: Sequence[ex.Expr]) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(Projection(list(exprs), self.plan))

    def filter(self, predicate: ex.Expr) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(Filter(predicate, self.plan))

    def aggregate(
        self, group_exprs: Sequence[ex.Expr], agg_exprs: Sequence[ex.Expr]
    ) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(
            Aggregate(list(group_exprs), list(agg_exprs), self.plan)
        )

    def sort(self, sort_exprs: Sequence[ex.SortExpr]) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(Sort(list(sort_exprs), self.plan))

    def limit(self, n: int) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(Limit(n, self.plan))

    def repartition(
        self, num_partitions: int, hash_exprs: Optional[Sequence[ex.Expr]] = None
    ) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(
            Repartition(
                self.plan,
                num_partitions,
                list(hash_exprs) if hash_exprs else None,
            )
        )

    def join(
        self,
        right: "LogicalPlanBuilder",
        on: Sequence[Tuple[str, str]],
        how: str = "inner",
    ) -> "LogicalPlanBuilder":
        return LogicalPlanBuilder(Join(self.plan, right.plan, list(on), how))

    def build(self) -> LogicalPlan:
        return self.plan
