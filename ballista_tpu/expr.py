"""Logical expression AST.

Covers the reference wire contract's expression surface (reference:
rust/core/proto/ballista.proto:14-45 ``LogicalExprNode`` with 16 variants,
:80-114 scalar functions, :121-127 aggregate functions MIN/MAX/SUM/AVG/COUNT)
plus the operator-overload ergonomics of its Python bindings (reference:
python/src/expression.rs:1-304).

Expressions are pure ASTs; evaluation against a ColumnBatch happens in
``kernels.expr_eval`` inside a jit trace, and type inference happens here via
``to_field``.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field as dc_field
from typing import Any, List, Optional, Sequence, Tuple, Union

from .datatypes import (
    Boolean,
    DataType,
    Date32,
    Decimal,
    Field,
    Float32,
    Float64,
    Int32,
    Int64,
    Schema,
    Utf8,
    common_numeric_type,
)
from .errors import PlanError, SchemaError

# ---------------------------------------------------------------------------
# Base
# ---------------------------------------------------------------------------


class Expr:
    """Base logical expression."""

    # -- naming / typing ----------------------------------------------------

    def name(self) -> str:
        raise NotImplementedError(type(self).__name__)

    def to_field(self, schema: Schema) -> Field:
        raise NotImplementedError(type(self).__name__)

    def children(self) -> List["Expr"]:
        return []

    # -- fluent builders (DataFrame API) ------------------------------------

    def alias(self, name: str) -> "Expr":
        return Alias(self, name)

    def cast(self, dtype: DataType) -> "Expr":
        return Cast(self, dtype)

    def asc(self, nulls_first: bool = False) -> "SortExpr":
        return SortExpr(self, True, nulls_first)

    def desc(self, nulls_first: bool = False) -> "SortExpr":
        return SortExpr(self, False, nulls_first)

    def is_null(self) -> "Expr":
        return IsNull(self)

    def is_not_null(self) -> "Expr":
        return IsNotNull(self)

    def between(self, low, high) -> "Expr":
        return (self >= low) & (self <= high)

    def isin(self, values: Sequence) -> "Expr":
        return InList(self, [_wrap(v) for v in values], negated=False)

    # -- operator overloads --------------------------------------------------

    def __add__(self, other):
        return BinaryExpr(self, "+", _wrap(other))

    def __radd__(self, other):
        return BinaryExpr(_wrap(other), "+", self)

    def __sub__(self, other):
        return BinaryExpr(self, "-", _wrap(other))

    def __rsub__(self, other):
        return BinaryExpr(_wrap(other), "-", self)

    def __mul__(self, other):
        return BinaryExpr(self, "*", _wrap(other))

    def __rmul__(self, other):
        return BinaryExpr(_wrap(other), "*", self)

    def __truediv__(self, other):
        return BinaryExpr(self, "/", _wrap(other))

    def __rtruediv__(self, other):
        return BinaryExpr(_wrap(other), "/", self)

    def __mod__(self, other):
        return BinaryExpr(self, "%", _wrap(other))

    def __eq__(self, other):  # type: ignore[override]
        return BinaryExpr(self, "=", _wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return BinaryExpr(self, "!=", _wrap(other))

    def __lt__(self, other):
        return BinaryExpr(self, "<", _wrap(other))

    def __le__(self, other):
        return BinaryExpr(self, "<=", _wrap(other))

    def __gt__(self, other):
        return BinaryExpr(self, ">", _wrap(other))

    def __ge__(self, other):
        return BinaryExpr(self, ">=", _wrap(other))

    def __and__(self, other):
        return BinaryExpr(self, "and", _wrap(other))

    def __or__(self, other):
        return BinaryExpr(self, "or", _wrap(other))

    def __invert__(self):
        return Not(self)

    # Identity hash: __eq__ is DSL sugar (returns a BinaryExpr), so Exprs
    # must never rely on structural set/dict semantics — planners key on
    # .name() strings instead.
    __hash__ = object.__hash__

    def __repr__(self) -> str:
        return self.name()

    def __bool__(self):
        raise PlanError(
            "cannot coerce Expr to bool — use & | ~ instead of and/or/not"
        )


def _wrap(v) -> Expr:
    if isinstance(v, Expr):
        return v
    return Literal.infer(v)


# ---------------------------------------------------------------------------
# Leaf expressions
# ---------------------------------------------------------------------------


@dataclass(repr=False, eq=False)
class ColumnRef(Expr):
    """Reference to an input column, optionally qualified (table.column)."""

    column: str
    relation: Optional[str] = None

    def name(self) -> str:
        return self.column

    def qualified(self) -> str:
        return f"{self.relation}.{self.column}" if self.relation else self.column

    def to_field(self, schema: Schema) -> Field:
        return schema.field(self.column)


@dataclass(repr=False, eq=False)
class Literal(Expr):
    """Typed literal. ``value`` is the logical Python value."""

    value: Any
    dtype: DataType

    @staticmethod
    def infer(v) -> "Literal":
        if isinstance(v, bool):
            return Literal(v, Boolean)
        if isinstance(v, int):
            return Literal(v, Int64)
        if isinstance(v, float):
            return Literal(v, Float64)
        if isinstance(v, str):
            return Literal(v, Utf8)
        if isinstance(v, _dt.date):
            return Literal((v - _dt.date(1970, 1, 1)).days, Date32)
        if v is None:
            return Literal(None, Int64)
        raise PlanError(f"cannot infer literal type for {v!r}")

    def name(self) -> str:
        return repr(self.value) if not isinstance(self.value, str) else self.value

    def to_field(self, schema: Schema) -> Field:
        return Field(self.name(), self.dtype, self.value is None)


def parse_date_literal(s: str) -> int:
    """'YYYY-MM-DD' -> days since epoch."""
    d = _dt.date.fromisoformat(s.strip())
    return (d - _dt.date(1970, 1, 1)).days


# ---------------------------------------------------------------------------
# Compound expressions
# ---------------------------------------------------------------------------


@dataclass(repr=False, eq=False)
class Alias(Expr):
    expr: Expr
    alias_name: str

    def name(self) -> str:
        return self.alias_name

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        inner = self.expr.to_field(schema)
        return Field(self.alias_name, inner.dtype, inner.nullable)


ARITH_OPS = ("+", "-", "*", "/", "%")
CMP_OPS = ("=", "!=", "<", "<=", ">", ">=")
BOOL_OPS = ("and", "or")


@dataclass(repr=False, eq=False)
class BinaryExpr(Expr):
    left: Expr
    op: str
    right: Expr

    def name(self) -> str:
        return f"{self.left.name()} {self.op.upper()} {self.right.name()}"

    def children(self) -> List[Expr]:
        return [self.left, self.right]

    def to_field(self, schema: Schema) -> Field:
        lf = self.left.to_field(schema)
        rf = self.right.to_field(schema)
        nullable = lf.nullable or rf.nullable
        if self.op in BOOL_OPS:
            if lf.dtype != Boolean or rf.dtype != Boolean:
                raise SchemaError(f"{self.op} requires booleans, got {lf} / {rf}")
            return Field(self.name(), Boolean, nullable)
        if self.op in CMP_OPS:
            _ = _coerced_binary_type(lf.dtype, rf.dtype, self)
            return Field(self.name(), Boolean, nullable)
        if self.op in ARITH_OPS:
            out = _arith_result_type(lf.dtype, rf.dtype, self.op)
            return Field(self.name(), out, nullable)
        raise PlanError(f"unknown binary op {self.op}")


def _coerced_binary_type(l: DataType, r: DataType, ctx: Expr) -> DataType:
    """Common comparison type; utf8 comparisons require utf8 on both sides
    (literals adapt to dictionary codes at evaluation time)."""
    if l.is_string or r.is_string:
        if l.kind == "date32" or r.kind == "date32":
            return Date32  # string date literal vs date column
        if l.is_string and r.is_string:
            return Utf8
        raise SchemaError(f"cannot compare {l!r} with {r!r} in {ctx.name()}")
    if l == Boolean and r == Boolean:
        return Boolean
    return common_numeric_type(l, r)


def _arith_result_type(l: DataType, r: DataType, op: str) -> DataType:
    if l.kind == "date32" or r.kind == "date32":
        if op in ("+", "-"):
            # date +/- int days -> date; date - date -> int
            if l.kind == "date32" and r.kind == "date32":
                return Int32
            return Date32
        raise SchemaError(f"op {op} invalid for dates")
    if l.kind == "decimal" or r.kind == "decimal":
        ls = l.scale if l.kind == "decimal" else 0
        rs = r.scale if r.kind == "decimal" else 0
        if op in ("+", "-"):
            if l.is_floating or r.is_floating:
                return Float64
            return Decimal(max(ls, rs))
        if op == "*":
            if l.is_floating or r.is_floating:
                return Float64
            return Decimal(ls + rs)
        if op == "/":
            return Float64
        if op == "%":
            raise SchemaError("modulo on decimal not supported")
    if op == "/":
        if l.is_integer and r.is_integer:
            return common_numeric_type(l, r)
        return Float64
    return common_numeric_type(l, r)


@dataclass(repr=False, eq=False)
class Not(Expr):
    expr: Expr

    def name(self) -> str:
        return f"NOT {self.expr.name()}"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        f = self.expr.to_field(schema)
        return Field(self.name(), Boolean, f.nullable)


@dataclass(repr=False, eq=False)
class IsNull(Expr):
    expr: Expr

    def name(self) -> str:
        return f"{self.expr.name()} IS NULL"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        return Field(self.name(), Boolean, False)


@dataclass(repr=False, eq=False)
class IsNotNull(Expr):
    expr: Expr

    def name(self) -> str:
        return f"{self.expr.name()} IS NOT NULL"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        return Field(self.name(), Boolean, False)


@dataclass(repr=False, eq=False)
class InList(Expr):
    expr: Expr
    list: List[Expr]
    negated: bool = False

    def name(self) -> str:
        n = "NOT IN" if self.negated else "IN"
        return f"{self.expr.name()} {n} ({', '.join(e.name() for e in self.list)})"

    def children(self) -> List[Expr]:
        return [self.expr] + list(self.list)

    def to_field(self, schema: Schema) -> Field:
        f = self.expr.to_field(schema)
        return Field(self.name(), Boolean, f.nullable)


@dataclass(repr=False, eq=False)
class Cast(Expr):
    expr: Expr
    dtype: DataType

    def name(self) -> str:
        return f"CAST({self.expr.name()} AS {self.dtype!r})"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        f = self.expr.to_field(schema)
        return Field(self.name(), self.dtype, f.nullable)


@dataclass(repr=False, eq=False)
class Case(Expr):
    """CASE [expr] WHEN v THEN r ... [ELSE d] END."""

    base: Optional[Expr]
    branches: List[Tuple[Expr, Expr]]
    otherwise: Optional[Expr]

    def name(self) -> str:
        parts = ["CASE"]
        if self.base is not None:
            parts.append(self.base.name())
        for w, t in self.branches:
            parts.append(f"WHEN {w.name()} THEN {t.name()}")
        if self.otherwise is not None:
            parts.append(f"ELSE {self.otherwise.name()}")
        parts.append("END")
        return " ".join(parts)

    def children(self) -> List[Expr]:
        out = [self.base] if self.base is not None else []
        for w, t in self.branches:
            out += [w, t]
        if self.otherwise is not None:
            out.append(self.otherwise)
        return out

    def to_field(self, schema: Schema) -> Field:
        t = self.branches[0][1].to_field(schema)
        return Field(self.name(), t.dtype, True)


@dataclass(repr=False, eq=False)
class Like(Expr):
    expr: Expr
    pattern: str
    negated: bool = False

    def name(self) -> str:
        n = "NOT LIKE" if self.negated else "LIKE"
        return f"{self.expr.name()} {n} {self.pattern!r}"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        f = self.expr.to_field(schema)
        return Field(self.name(), Boolean, f.nullable)


# ---------------------------------------------------------------------------
# Subquery expressions (resolved/decorrelated by the SQL planner; a
# ScalarSubquery that survives to execution is inlined to a Literal by
# execution.resolve_subqueries)
# ---------------------------------------------------------------------------


@dataclass(repr=False, eq=False)
class ScalarSubquery(Expr):
    """(SELECT single_value ...) used as a scalar."""

    plan: object  # LogicalPlan (late-bound by the SQL planner)
    query: object = None  # parser AST before planning

    def name(self) -> str:
        return "(<scalar subquery>)"

    def to_field(self, schema: Schema) -> Field:
        sub_schema = self.plan.schema()
        f = sub_schema.fields[0]
        return Field(self.name(), f.dtype, True)


@dataclass(repr=False, eq=False)
class Exists(Expr):
    """EXISTS (SELECT ...); planner decorrelates into a semi/anti join."""

    query: object  # parser Query AST
    negated: bool = False

    def name(self) -> str:
        return ("NOT " if self.negated else "") + "EXISTS(<subquery>)"

    def to_field(self, schema: Schema) -> Field:
        return Field(self.name(), Boolean, False)


@dataclass(repr=False, eq=False)
class InSubquery(Expr):
    """expr [NOT] IN (SELECT col ...); planner turns into semi/anti join."""

    expr: Expr
    query: object  # parser Query AST
    negated: bool = False

    def name(self) -> str:
        n = "NOT IN" if self.negated else "IN"
        return f"{self.expr.name()} {n} (<subquery>)"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        return Field(self.name(), Boolean, True)


# ---------------------------------------------------------------------------
# Scalar functions
# ---------------------------------------------------------------------------

# name -> (arity, result type rule). Rule: "same" | "float" | "bool" | "int"
# | "utf8" | callable(schema, args)->DataType
SCALAR_FUNCTIONS = {
    "abs": (1, "same"),
    "sqrt": (1, "float"),
    "exp": (1, "float"),
    "ln": (1, "float"),
    "log": (1, "float"),
    "log2": (1, "float"),
    "log10": (1, "float"),
    "floor": (1, "float"),
    "ceil": (1, "float"),
    "round": (1, "float"),
    "trunc": (1, "float"),
    "signum": (1, "same"),
    "sin": (1, "float"),
    "cos": (1, "float"),
    "tan": (1, "float"),
    "asin": (1, "float"),
    "acos": (1, "float"),
    "atan": (1, "float"),
    "upper": (1, "utf8"),
    "lower": (1, "utf8"),
    "trim": (1, "utf8"),
    "ltrim": (1, "utf8"),
    "rtrim": (1, "utf8"),
    "length": (1, "int"),
    "character_length": (1, "int"),
    "octet_length": (1, "int"),
    "md5": (1, "utf8"),
    "sha224": (1, "utf8"),
    "sha256": (1, "utf8"),
    "sha384": (1, "utf8"),
    "sha512": (1, "utf8"),
    "date_trunc": (2, "arg1"),  # date_trunc('month', d) -> d's type
    "to_timestamp": (1, "timestamp"),
    "substr": (3, "utf8"),
    "concat": (-1, "utf8"),
    "date_part": (2, "int"),
    "extract_year": (1, "int"),
    "extract_month": (1, "int"),
    "extract_day": (1, "int"),
    "nullif": (2, "same"),
    "coalesce": (-1, "same"),
    # ARRAY constructor (reference: rust/core/proto/ballista.proto:105) —
    # numeric/temporal elements, coerced to a common type
    "array": (-1, "array"),
}


@dataclass(repr=False, eq=False)
class ScalarFunction(Expr):
    fn: str
    args: List[Expr]

    def name(self) -> str:
        return f"{self.fn}({', '.join(a.name() for a in self.args)})"

    def children(self) -> List[Expr]:
        return list(self.args)

    def to_field(self, schema: Schema) -> Field:
        if self.fn not in SCALAR_FUNCTIONS:
            raise PlanError(f"unknown scalar function {self.fn}")
        arity, rule = SCALAR_FUNCTIONS[self.fn]
        if arity >= 0 and len(self.args) != arity:
            raise PlanError(f"{self.fn} expects {arity} args, got {len(self.args)}")
        nullable = any(a.to_field(schema).nullable for a in self.args)
        if rule == "same":
            return Field(self.name(), self.args[0].to_field(schema).dtype, nullable)
        if rule == "float":
            return Field(self.name(), Float64, nullable)
        if rule == "int":
            return Field(self.name(), Int32, nullable)
        if rule == "bool":
            return Field(self.name(), Boolean, nullable)
        if rule == "utf8":
            return Field(self.name(), Utf8, nullable)
        if rule == "arg1":
            return Field(self.name(), self.args[1].to_field(schema).dtype, nullable)
        if rule == "timestamp":
            from .datatypes import TimestampNs

            return Field(self.name(), TimestampNs, nullable)
        if rule == "array":
            from .datatypes import FixedSizeList

            if not self.args:
                raise PlanError("array() requires at least one argument")
            dts = [a.to_field(schema).dtype for a in self.args]
            if any(d.kind in ("utf8", "list") for d in dts):
                raise PlanError("array() supports numeric/temporal elements")
            elem = dts[0]
            for d in dts[1:]:
                elem = d if d == elem else common_numeric_type(elem, d)
            return Field(self.name(), FixedSizeList(elem, len(self.args)),
                         nullable)
        raise PlanError(f"bad rule for {self.fn}")


# ---------------------------------------------------------------------------
# Aggregate expressions (the reference's 5: MIN/MAX/SUM/AVG/COUNT)
# ---------------------------------------------------------------------------

AGG_FUNCTIONS = ("sum", "avg", "min", "max", "count", "count_distinct")


@dataclass(repr=False, eq=False)
class AggregateExpr(Expr):
    fn: str  # one of AGG_FUNCTIONS
    expr: Expr  # inner expression (Literal(1) for COUNT(*))
    is_star: bool = False

    def name(self) -> str:
        if self.fn == "count" and self.is_star:
            return "COUNT(*)"
        if self.fn == "count_distinct":
            return f"COUNT(DISTINCT {self.expr.name()})"
        return f"{self.fn.upper()}({self.expr.name()})"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        if self.fn in ("count", "count_distinct"):
            return Field(self.name(), Int64, False)
        inner = self.expr.to_field(schema)
        if self.fn == "avg":
            # exact-ish fixed-point average for int/decimal inputs: TPU has
            # no fast f64, so sum stays int64 and avg is scaled to 6 dp
            if inner.dtype.is_integer or inner.dtype.kind == "decimal":
                return Field(self.name(), Decimal(6), True)
            return Field(self.name(), Float64, True)
        if self.fn == "sum":
            dt = inner.dtype
            if dt.is_integer:
                dt = Int64
            return Field(self.name(), dt, True)
        # min/max keep input type
        return Field(self.name(), inner.dtype, True)


# ---------------------------------------------------------------------------
# Sort key
# ---------------------------------------------------------------------------


@dataclass(repr=False, eq=False)
class SortExpr(Expr):
    expr: Expr
    ascending: bool = True
    nulls_first: bool = False

    def name(self) -> str:
        d = "ASC" if self.ascending else "DESC"
        return f"{self.expr.name()} {d}"

    def children(self) -> List[Expr]:
        return [self.expr]

    def to_field(self, schema: Schema) -> Field:
        return self.expr.to_field(schema)


# ---------------------------------------------------------------------------
# Public constructors (mirrors reference python functions module,
# reference: python/src/functions.rs:1-171)
# ---------------------------------------------------------------------------


def col(name: str) -> ColumnRef:
    if "." in name:
        rel, c = name.split(".", 1)
        return ColumnRef(c, rel)
    return ColumnRef(name)


def lit(v) -> Literal:
    return Literal.infer(v)


def date_lit(s: str) -> Literal:
    return Literal(parse_date_literal(s), Date32)


def sum_(e: Expr) -> AggregateExpr:
    return AggregateExpr("sum", e)


def avg(e: Expr) -> AggregateExpr:
    return AggregateExpr("avg", e)


def min_(e: Expr) -> AggregateExpr:
    return AggregateExpr("min", e)


def max_(e: Expr) -> AggregateExpr:
    return AggregateExpr("max", e)


def count(e: Optional[Expr] = None) -> AggregateExpr:
    if e is None:
        return AggregateExpr("count", Literal(1, Int64), is_star=True)
    return AggregateExpr("count", e)


def count_distinct(e: Expr) -> AggregateExpr:
    return AggregateExpr("count_distinct", e)


def case(base: Optional[Expr] = None) -> "CaseBuilder":
    return CaseBuilder(base)


class CaseBuilder:
    """Fluent CASE builder (reference: python/src/expression.rs CaseBuilder)."""

    def __init__(self, base: Optional[Expr] = None):
        self._base = base
        self._branches: List[Tuple[Expr, Expr]] = []
        self._otherwise: Optional[Expr] = None

    def when(self, cond, then) -> "CaseBuilder":
        self._branches.append((_wrap(cond), _wrap(then)))
        return self

    def otherwise(self, v) -> Case:
        self._otherwise = _wrap(v)
        return self.end()

    def end(self) -> Case:
        return Case(self._base, self._branches, self._otherwise)


# -- tree utilities ---------------------------------------------------------


def walk(e: Expr):
    yield e
    for c in e.children():
        if c is not None:
            yield from walk(c)


def referenced_columns(e: Expr) -> List[str]:
    out = []
    for node in walk(e):
        if isinstance(node, ColumnRef) and node.column not in out:
            out.append(node.column)
    return out


def strip_alias(e: Expr) -> Expr:
    while isinstance(e, Alias):
        e = e.expr
    return e
