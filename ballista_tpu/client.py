"""Client API: BallistaContext + DataFrame.

Mirrors the reference's client crate surface (reference:
rust/client/src/context.rs:75-144 ``BallistaContext`` with remote/
read_csv/read_parquet/register_*/sql; :149-315 ``BallistaDataFrame`` verbs
select/filter/aggregate/sort/limit/repartition/collect) and its Python
bindings (reference: python/src/context.rs, python/src/dataframe.rs).

Two modes:
- ``standalone()``: plans and executes in-process (single host, one device);
- ``remote(host, port)``: submits plans to a scheduler over gRPC and fetches
  results from executors (distributed layer).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple, Union

from .datatypes import Schema, dtype_from_name, schema as make_schema
from .errors import BallistaError, PlanError
from . import expr as ex
from .io import CsvSource, MemTableSource, ParquetSource, TblSource
from .logical import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    LogicalPlanBuilder,
    Projection,
    Repartition,
    Sort,
    TableScan,
    TableSource,
)
from .sql.parser import CreateExternalTable, Query, parse_sql
from .sql.planner import CatalogTable, SqlPlanner


def _default_pk(schema: Schema) -> Optional[str]:
    """TPC-H-style convention: a first column named *key is the primary key."""
    names = schema.names()
    if names and names[0].endswith("key"):
        return names[0]
    return None


class BallistaContext:
    """Entry point: table registration + SQL/DataFrame construction."""

    def __init__(self, mode: str = "standalone", host: str = "localhost",
                 port: int = 50050, settings: Optional[Dict[str, str]] = None):
        self.mode = mode
        self.host = host
        self.port = port
        self.settings = dict(settings or {})
        # per-session resource metering (observability/progress.py):
        # every query this context runs is accounted to one session id.
        # It travels with the submitted settings so the scheduler's
        # terminal hook can meter cluster jobs; a caller-supplied
        # "session.id" setting wins (shared-session pools).
        import uuid

        self.session_id = self.settings.setdefault(
            "session.id", uuid.uuid4().hex[:12])
        self._catalog: Dict[str, CatalogTable] = {}
        # SQL plan cache: repeated identical queries reuse the planned (and,
        # in standalone mode, compiled) DataFrame; invalidated on any
        # catalog change
        self._plan_cache: Dict[str, "DataFrame"] = {}
        # per-stage operator metrics of the last executed query
        # (observability subsystem); None until a query completes or when
        # metrics are disabled (BALLISTA_METRICS=0). Standalone queries
        # stash the executed plan and snapshot LAZILY at read time —
        # harvesting inside collect() would put a device_get + plan walk
        # on every query's critical path (the < 5% overhead gate)
        self._last_query_metrics = None
        self._last_query_phys = None
        # job id of the last remote query: the handle df.profile() and
        # /debug/profile/<job_id> take on the cluster path
        self._last_job_id = None
        # latency ledger (observability/ledger.py): standalone collects
        # stash their assembled ledger; remote collects stash only the
        # client envelope (wall + client-side stamps) and
        # last_query_ledger() merges it with the scheduler's
        # system.latency rows LAZILY — no RPC on the collect path
        self._last_query_ledger = None
        self._last_ledger_env = None
        # query lifecycle (lifecycle.py / docs/robustness.md): cancel
        # tokens of in-flight standalone collects and the live job-id
        # sinks of in-flight remote collects — what ctx.cancel() fires
        # from another thread
        self._lifecycle_lock = threading.Lock()
        self._active_tokens: List = []
        self._active_job_sinks: List[list] = []

    # -- constructors -------------------------------------------------------

    @staticmethod
    def standalone(**settings) -> "BallistaContext":
        return BallistaContext("standalone", settings=settings or None)

    @staticmethod
    def remote(host: str, port: int = 50050, **settings) -> "BallistaContext":
        return BallistaContext("remote", host, port, settings or None)

    # -- registration (reference: context.rs:110-129) -----------------------

    def register_source(self, name: str, source: TableSource,
                        primary_key: Optional[str] = None,
                        cached: bool = False) -> None:
        if cached:
            from .io import CacheSource

            source = CacheSource(source)
        pk = primary_key or _default_pk(source.table_schema())
        self._catalog[name] = CatalogTable(name, source, pk)
        self._plan_cache.clear()

    def register_tbl(self, name: str, path: str, schema: Schema,
                     primary_key: Optional[str] = None, cached: bool = False,
                     **kw) -> None:
        self.register_source(name, TblSource(path, schema, **kw), primary_key,
                             cached=cached)

    def register_csv(self, name: str, path: str, schema: Schema,
                     has_header: bool = True,
                     primary_key: Optional[str] = None, cached: bool = False,
                     **kw) -> None:
        self.register_source(
            name, CsvSource(path, schema, has_header=has_header, **kw),
            primary_key, cached=cached,
        )

    def register_parquet(self, name: str, path: str,
                         schema: Optional[Schema] = None,
                         primary_key: Optional[str] = None,
                         cached: bool = False, **kw) -> None:
        self.register_source(name, ParquetSource(path, schema, **kw),
                             primary_key, cached=cached)

    def register_memtable(self, name: str, schema: Schema, data: Dict,
                          num_partitions: int = 1,
                          primary_key: Optional[str] = None) -> None:
        self.register_source(
            name, MemTableSource.from_pydict(schema, data, num_partitions),
            primary_key,
        )

    def register_table(self, name: str, df: "DataFrame") -> None:
        """Register a DataFrame as a named table (view semantics): SQL
        referencing ``name`` inlines the frame's logical plan, exactly
        the role the reference's DFTableAdapter plays for registered
        frames (reference: rust/core/src/datasource.rs:28-66;
        rust/client/src/context.rs:131-144 registers DataFrames before
        planning SQL)."""
        # df.plan plans raw-SQL (server-planned) frames on demand and
        # raises PlanError for true DDL frames that carry no plan.
        # Copy it: executing the original frame mutates its plan in
        # place (scalar subqueries resolve to literals) and the view
        # must not inherit those baked values. Sources are shared
        # (TableSource.__deepcopy__).
        import copy

        self._catalog[name] = CatalogTable(name, None, None,
                                           plan=copy.deepcopy(df.plan))
        self._plan_cache.clear()

    def deregister_table(self, name: str) -> None:
        self._catalog.pop(name, None)
        self._plan_cache.clear()

    def tables(self) -> List[str]:
        return sorted(self._catalog)

    # -- reads (reference: context.rs:88-108) -------------------------------

    def read_tbl(self, path: str, schema: Schema, **kw) -> "DataFrame":
        src = TblSource(path, schema, **kw)
        return DataFrame(self, TableScan("tbl:" + path, src))

    def read_csv(self, path: str, schema: Schema, has_header: bool = True,
                 **kw) -> "DataFrame":
        src = CsvSource(path, schema, has_header=has_header, **kw)
        return DataFrame(self, TableScan("csv:" + path, src))

    def read_parquet(self, path: str, schema: Optional[Schema] = None,
                     **kw) -> "DataFrame":
        src = ParquetSource(path, schema, **kw)
        return DataFrame(self, TableScan("parquet:" + path, src))

    def _system_source(self, name: str):
        """Scan source for a ``system.*`` table: the current process's
        snapshot in standalone mode; in remote mode rows are fetched
        from the SCHEDULER at scan/ship time so they reflect cluster
        state (observability/systables.py)."""
        from .observability.systables import SystemTableSource

        if self.mode == "remote":
            host, port = self.host, self.port

            def fetch():
                from .distributed.client import fetch_system_table

                return fetch_system_table(host, port, name)

            return SystemTableSource(name, fetcher=fetch)
        return SystemTableSource(name)

    def table(self, name: str) -> "DataFrame":
        if name not in self._catalog:
            from .observability.systables import is_system_table

            if is_system_table(name):
                return DataFrame(
                    self, TableScan(name, self._system_source(name)))
            raise PlanError(f"unknown table {name!r}")
        t = self._catalog[name]
        if t.plan is not None:  # registered DataFrame view: inline a
            # copy — execution mutates plans in place and the catalog's
            # must stay pristine (sources are shared, not cloned)
            import copy

            return DataFrame(self, copy.deepcopy(t.plan))
        return DataFrame(self, TableScan(t.name, t.source))

    # -- SQL ----------------------------------------------------------------

    def sql(self, query: str) -> "DataFrame":
        cached = self._plan_cache.get(query)
        if cached is not None:
            return cached
        if (self.mode == "remote"
                and self.settings.get("plan.server") in ("on", "true", "1")
                and not _is_ddl(query)):
            # raw-SQL submission: the scheduler plans against the catalog
            # shipped with the query (no client-side planning at collect
            # time; DDL still registers in the client catalog below)
            return DataFrame(self, None, raw_sql=query)
        stmt = parse_sql(query)
        if isinstance(stmt, CreateExternalTable):
            sch = make_schema(*[(n, t) for n, t in stmt.columns])
            if stmt.stored_as in ("CSV",):
                self.register_csv(stmt.name, stmt.location, sch,
                                  has_header=stmt.has_header)
            elif stmt.stored_as in ("TBL",):
                self.register_tbl(stmt.name, stmt.location, sch)
            elif stmt.stored_as in ("PARQUET",):
                self.register_parquet(stmt.name, stmt.location, sch)
            else:
                raise PlanError(f"STORED AS {stmt.stored_as} unsupported")
            return DataFrame(self, None)
        planner = SqlPlanner(self._catalog,
                             system_provider=self._system_source)
        df = DataFrame(self, planner.plan(stmt))
        # plans over system.* tables are NOT cached: a cached plan reuses
        # its physical operator instances, whose materializations (a
        # JoinExec build side, RepartitionExec parts) would freeze the
        # telemetry snapshot of the FIRST collect — re-issuing the SQL
        # must see fresh rows (observability/systables.py)
        if not _scans_system_table(df._plan):
            self._plan_cache[query] = df
        return df

    # -- execution ----------------------------------------------------------

    @contextmanager
    def _track_lifecycle(self, obj, registry: list):
        """Register an in-flight query's cancel handle (a CancelToken
        or a live remote job-id sink) for the duration of the collect,
        so a concurrent ``ctx.cancel()`` can reach it."""
        with self._lifecycle_lock:
            registry.append(obj)
        try:
            yield obj
        finally:
            with self._lifecycle_lock:
                try:
                    registry.remove(obj)
                except ValueError:
                    pass

    def cancel(self, reason: str = "client") -> int:
        """Cooperatively cancel this context's in-flight queries (call
        from another thread). Standalone collects stop at their next
        batch boundary and raise :class:`errors.QueryCancelled`; remote
        collects get a best-effort ``CancelJob`` for every job this
        context currently has in flight. Returns how many queries/jobs
        this call cancelled. Queries land as terminal ``cancelled`` in
        ``system.queries`` with the given reason."""
        with self._lifecycle_lock:
            tokens = list(self._active_tokens)
            sinks = list(self._active_job_sinks)
            job_ids = [jid for sink in sinks
                       for jid in list(sink) if isinstance(jid, str)]
        n = 0
        for t in tokens:
            n += bool(t.cancel(reason))
        if self.mode == "remote" and sinks:
            import logging

            from .distributed.client import CancelRequested, cancel_job

            for jid in job_ids:
                try:
                    n += bool(cancel_job(self.host, self.port, jid,
                                         reason))
                except Exception:  # noqa: BLE001 - best-effort
                    logging.getLogger("ballista.lifecycle").warning(
                        "CancelJob(%s) failed", jid, exc_info=True)
            # a collect sleeping between admission-retry attempts has
            # no live job to CancelJob: the sentinel stops its loop
            # before it resubmits the query the user just cancelled
            for sink in sinks:
                sink.append(CancelRequested(reason))
        return n

    def _collect(self, plan: LogicalPlan, on_progress=None):
        if self.mode == "standalone":
            out, _ = self._standalone_collect(plan,
                                              on_progress=on_progress)
            return out
        import time as _time

        from .distributed.client import remote_collect
        from .observability import ledger as _ledger

        sink: list = []
        jsink: list = []
        _ledger.begin_collect()
        t0 = _time.perf_counter()
        # jsink receives the job id at SUBMIT time, so a concurrent
        # ctx.cancel() can CancelJob the job while this thread waits
        with self._track_lifecycle(jsink, self._active_job_sinks):
            out = remote_collect(self.host, self.port, plan, self.settings,
                                 metrics_out=sink, job_id_out=jsink,
                                 on_progress=on_progress)
        self._last_query_metrics = sink[0] if sink else None
        self._last_query_phys = None
        self._last_job_id = jsink[0] if jsink else None
        self._last_query_ledger = None
        self._last_ledger_env = {"wall": _time.perf_counter() - t0,
                                 "stamps": _ledger.take_collect()}
        return out

    def job_progress(self, job_id: Optional[str] = None):
        """Live progress snapshot of a job (the ONE progress shape —
        see docs/observability.md): per-stage completion fractions,
        rate-based ETA, task counts. ``job_id`` defaults to this
        context's most recent remote job. Remote contexts ask the
        scheduler (extended GetJobStatus); standalone contexts report
        their in-flight collects. Returns None when nothing is known
        about the job."""
        if self.mode == "remote":
            jid = job_id
            if not jid:
                # prefer a currently in-flight job (another thread's
                # collect registered its id at SUBMIT time — the same
                # channel ctx.cancel() uses) over the last finished one
                with self._lifecycle_lock:
                    inflight = [j for sink in self._active_job_sinks
                                for j in list(sink)
                                if isinstance(j, str)]
                jid = (inflight[-1] if inflight else None) \
                    or self._last_job_id
            if not jid:
                return None
            from .distributed.client import fetch_job_progress

            return fetch_job_progress(self.host, self.port, jid)
        from .observability import progress as obs_progress

        handles = obs_progress.local_live_handles()
        if job_id is not None:
            handles = [h for h in handles if h.job_id == job_id]
        return handles[-1].snapshot() if handles else None

    def _standalone_collect(self, plan: LogicalPlan, phys=None,
                            on_progress=None):
        """Shared standalone execute-and-wrap: plan (unless the caller
        passes a cached physical plan), execute, record metrics.
        Returns ``(frame, phys)`` so DataFrame.collect can keep its
        plan cache. Under ``BALLISTA_PROFILE=<dir>`` every collect
        writes a Chrome-trace profile artifact into the directory.
        Every collect's terminal summary (status, wall seconds, output
        rows, flight-recorder lanes, artifact path) lands in the shared
        system-tables snapshot + the durable query-history log
        (observability/systables.py) — the standalone face of the
        scheduler's terminal-transition hook. ``on_progress`` (live
        progress plane) receives snapshots of the ONE progress shape
        from a sampler thread over the executing plan's MetricsSet —
        parity with the cluster path's GetJobStatus-driven callbacks."""
        from .observability.systables import StandaloneQueryRecorder

        rec = StandaloneQueryRecorder(plan, session_id=self.session_id)
        sampler = None
        if on_progress is not None:
            from .observability.progress import LocalProgressSampler

            sampler = LocalProgressSampler(rec.handle, on_progress)
        try:
            out, phys2 = self._standalone_collect_routed(plan, phys, rec)
        except Exception as e:  # noqa: BLE001 - record, then propagate
            from .errors import QueryCancelled

            if sampler is not None:
                sampler.finish("cancelled" if isinstance(e, QueryCancelled)
                               else "failed")
            rec.finish("failed", error=e)
            self._last_query_ledger = rec.ledger
            self._last_ledger_env = None
            raise
        if sampler is not None:
            # terminal callback BEFORE the recorder tears the handle
            # down: the final snapshot reports fraction exactly 1.0
            sampler.finish("completed")
        rec.finish("completed", result=out, phys=phys2)
        self._last_query_ledger = rec.ledger
        self._last_ledger_env = None
        return out, phys2

    def _standalone_collect_routed(self, plan: LogicalPlan, phys, rec):
        from .observability import profiler as obs_profiler

        out_dir = obs_profiler.profile_dir()
        if out_dir is not None and not obs_profiler.profiling_active():
            # label artifacts by a plan digest so a bench loop's files
            # are distinguishable per query shape
            try:
                profile_label = "query-" + obs_profiler.plan_digest(plan)
            except Exception:  # noqa: BLE001 - label is cosmetic
                profile_label = "query"
            box = {}

            def run():
                box["r"] = self._standalone_collect_inner(plan, phys)

            import logging

            plog = logging.getLogger("ballista.profiler")
            try:
                _, path = obs_profiler.profile_call(
                    run, label=profile_label,
                    plan_getter=lambda: box.get("r", (None, None))[1],
                    out_dir=out_dir, busy_ok=True,
                )
            except Exception:
                if "r" not in box:
                    raise  # the QUERY failed: propagate as usual
                # the query succeeded and only the artifact write/stop
                # failed (e.g. unwritable BALLISTA_PROFILE path): a
                # misconfigured observability knob must not cost the
                # caller their result
                plog.exception("profile artifact write failed; "
                               "returning the query result anyway")
                path = None
            if path is not None:
                plog.info("profile artifact written: %s", path)
                rec.artifact_path = path
            return box["r"]
        # unprofiled run: the always-on flight recorder still lets a
        # query that crosses BALLISTA_SLOW_QUERY_SECS dump a RETROACTIVE
        # merged artifact after the fact (no-op when the knob is unset)
        from .observability.distributed import watch_slow_query

        def slow_label():
            return "query-" + obs_profiler.plan_digest(plan)

        slow_sink: list = []
        try:
            with watch_slow_query(slow_label, artifact_out=slow_sink):
                return self._standalone_collect_inner(plan, phys)
        finally:
            if slow_sink:
                rec.artifact_path = slow_sink[0]

    def _standalone_collect_inner(self, plan: LogicalPlan, phys=None):
        from .lifecycle import CancelToken, bind_token, slow_query_killer

        # one cancel token per collect: ctx.cancel() fires it from
        # another thread, the slow-query killer fires it on timeout,
        # and every batch boundary under the bind checks it
        token = CancelToken()
        with self._track_lifecycle(token, self._active_tokens), \
                bind_token(token), slow_query_killer(token):
            return self._standalone_collect_governed(plan, phys)

    def _standalone_collect_governed(self, plan: LogicalPlan, phys=None):
        import pandas as pd

        from .execution import collect_physical, plan_logical
        from .observability.metrics import (metrics_enabled,
                                            reset_plan_metrics)
        from .observability.ledger import ledger_phase
        from .physical.planner import PlannerOptions

        with ledger_phase("planning"):
            if phys is None:
                phys = plan_logical(
                    plan, PlannerOptions.from_settings(self.settings))
            # whole-stage fusion (physical/fusion.py): merge each
            # pipeline stage into one governed XLA program. Before
            # prewarm (which targets fused-stage signatures) and before
            # the adaptive pass (fused stages survive re-planning via
            # with_new_children).
            from .physical.fusion import maybe_fuse

            phys = maybe_fuse(phys)
        # plan-fingerprint result cache (cache/results.py, opt-in): a
        # repeat of the same fused plan over unchanged files with the
        # same settings returns the stored pydict without executing.
        # Keyed AFTER fusion so the fingerprint covers the real
        # programs; EXPLAIN trees execute nothing worth caching and
        # ANALYZE must re-measure, so both bypass.
        from .cache import results as _results
        from .physical.explain import ExplainAnalyzeExec, ExplainExec

        rc_key = None
        if (_results.result_cache_enabled(self.settings)
                and not isinstance(phys, (ExplainAnalyzeExec, ExplainExec))):
            rc_key = _results.plan_key(phys, self.settings)
            cached = _results.process_result_cache().lookup(rc_key)
            if cached is not None:
                self._annotate_cache_hits(result_hit=True)
                with ledger_phase("host_decode"):
                    out = pd.DataFrame(cached)
                return out, phys
        if metrics_enabled():
            # cached plans re-execute: last_query_metrics() must report
            # THIS query, not the lifetime accumulation — and the reset
            # drains pending device row-count scalars, which would
            # otherwise grow unboundedly when metrics are never read
            reset_plan_metrics(phys)
        # optional (BALLISTA_PREWARM=1): AOT-compile scan-side pipeline
        # chains in the background, overlapping XLA compile with the
        # scan's parse + host-to-device upload. Must start BEFORE the
        # adaptive pass: standalone adaptive eagerly materializes
        # repartition inputs (parse + upload + chain compiles) on this
        # thread, which is exactly the work prewarm wants to overlap.
        # The chains prewarm targets are scan-rooted and unchanged by
        # the adaptive rewrites.
        from .compile import maybe_prewarm

        maybe_prewarm(phys)
        # Parallel ingest (ballista_tpu/ingest): start parse+H2D for
        # every leaf scan NOW, so independent tables overlap each other
        # and the adaptive pass's eager repartition materialization
        # below consumes already-running streams. Scan INSTANCES
        # survive the adaptive rewrite (with_new_children keeps
        # leaves), so primed handles are consumed by the re-planned
        # tree; anything a rewrite or early exit leaves behind is
        # cancelled, never leaked.
        from .ingest import cancel_plan, prime_plan

        prime_plan(phys)
        try:
            phys = self._apply_adaptive(phys)
            # live progress plane: expose the FINAL (post-adaptive)
            # tree to this thread's active query handle — the
            # on_progress sampler and system.tasks/system.stages read
            # it weakly (no-op for unrecorded inner collects: EXPLAIN,
            # df.profile()). After the adaptive pass so the weak ref
            # survives: a rewritten root replaces the planned one.
            from .observability import progress as obs_progress

            obs_progress.attach_current_plan(phys)
            data = collect_physical(phys)
            with ledger_phase("host_decode"):
                out = pd.DataFrame(data)
        finally:
            cancel_plan(phys)
        self._record_plan_metrics(phys)
        if rc_key is not None:
            _results.process_result_cache().fill(rc_key, data)
        self._annotate_cache_hits(phys)
        return out, phys

    def _annotate_cache_hits(self, phys=None, result_hit=False) -> None:
        """Per-session warm-path attribution (system.sessions): sum the
        plan's ScanExec table_cache_hits counters for THIS collect
        (reset_plan_metrics zeroed them at entry) and/or flag a
        result-cache hit. Never bumps the meter's query count."""
        from .observability.progress import process_session_meter

        hits = 0
        if phys is not None:
            def walk(node):
                nonlocal hits
                m = getattr(node, "_metrics", None)
                if m is not None:
                    hits += int(m._counters.get("table_cache_hits", 0) or 0)
                for c in node.children():
                    walk(c)

            walk(phys)
        if hits or result_hit:
            process_session_meter().annotate_cache(
                self.settings.get("session.id"), hits,
                1 if result_hit else 0)

    def _apply_adaptive(self, phys):
        """Standalone adaptive execution: rewrite the planned tree from
        observed pipeline-breaker histograms (adaptive/standalone.py).
        Runs once per plan — cached DataFrames keep the adapted tree —
        and leaves EXPLAIN [ANALYZE] leaves alone (ANALYZE applies the
        rules itself, inside its measured window)."""
        if getattr(phys, "_adaptive_applied", False):
            return phys
        from .adaptive import AdaptiveConfig
        from .adaptive.standalone import apply_adaptive_rules
        from .physical.explain import ExplainAnalyzeExec, ExplainExec

        if not isinstance(phys, (ExplainAnalyzeExec, ExplainExec)):
            conf = AdaptiveConfig.from_settings(self.settings)
            if conf.enabled:
                phys = apply_adaptive_rules(phys, conf)
                # re-fuse subtrees the rewrite restructured (e.g. a
                # demoted join's probe chain). Value-keyed signatures
                # mean re-fused stages hit the existing governed
                # entries — zero new compiles. Probe-chain fusion is
                # skipped: a demoted join must keep the compiled probe
                # programs it already has.
                from .physical.fusion import fuse_plan, fusion_enabled

                if fusion_enabled():
                    phys = fuse_plan(phys, fuse_joins=False)
                    try:
                        # the re-fused root is cached: without the
                        # marker the NEXT collect would re-run the full
                        # pass (fuse_joins=True) and fuse the demoted
                        # join's probe chain after all
                        phys._fusion_applied = True
                    except AttributeError:
                        pass
        phys._adaptive_applied = True
        return phys

    def _record_plan_metrics(self, phys) -> None:
        from .observability.metrics import metrics_enabled

        self._last_query_metrics = None
        self._last_query_phys = phys if metrics_enabled() else None

    def last_query_metrics(self):
        """Per-stage/operator metric breakdown of the most recent query
        this context executed (:class:`observability.QueryMetrics`), or
        None before any query / under ``BALLISTA_METRICS=0``. Standalone
        queries report a single synthetic stage 0; distributed queries
        report the scheduler's per-stage aggregation over completed
        tasks."""
        if self._last_query_metrics is None and \
                self._last_query_phys is not None:
            from .observability.metrics import snapshot_plan_metrics

            self._last_query_metrics = snapshot_plan_metrics(
                self._last_query_phys)
        return self._last_query_metrics

    def last_query_ledger(self):
        """The per-query latency ledger of the most recent query this
        context ran (docs/observability.md): the fixed phase schema
        (``observability.ledger.LEDGER_PHASES``) plus wall seconds and
        the unattributed remainder, or None before any query / under
        ``BALLISTA_LEDGER=0``. Standalone queries stash the assembled
        ledger at terminal time; remote queries fetch the scheduler's
        ``system.latency`` rows for the job LAZILY here and merge them
        with the client envelope (end-to-end wall, result transfer,
        host decode) — nothing on the collect hot path."""
        if self._last_query_ledger is None and self.mode == "remote" \
                and self._last_job_id and self._last_ledger_env:
            self._last_query_ledger = self._fetch_remote_ledger()
        return self._last_query_ledger

    def _fetch_remote_ledger(self):
        import time as _time

        from .observability import ledger as _ledger

        env = self._last_ledger_env
        job_id = self._last_job_id
        # completion is published before the scheduler's terminal hook
        # records the job ledger (results never wait on observability)
        # — briefly retry until the job's rows appear
        rows = []
        deadline = _time.time() + 5.0
        while True:
            try:
                from .distributed.client import fetch_system_table

                rows = [r for r in fetch_system_table(
                            self.host, self.port, "system.latency")
                        if r.get("job_id") == job_id]
            except Exception:  # noqa: BLE001 - ledger is advisory
                rows = []
            if rows or _time.time() > deadline:
                break
            _time.sleep(0.1)
        phases = {}
        status = "completed"
        for r in rows:
            phase = r.get("phase")
            if phase and phase != "unattributed":
                try:
                    phases[phase] = float(r.get("seconds") or 0.0)
                except (TypeError, ValueError):
                    continue
            status = r.get("status") or status
        # the client envelope: end-to-end wall + client-side stamps
        # (result_transfer, host_decode) the scheduler never sees
        for k, v in (env.get("stamps") or {}).items():
            phases[k] = phases.get(k, 0.0) + float(v)
        return _ledger.build_ledger(job_id, env["wall"], origin="client",
                                    status=status, phases=phases)


def _is_ddl(query: str) -> bool:
    return query.lstrip().lower().startswith("create")


def _scans_system_table(plan: Optional[LogicalPlan]) -> bool:
    from .observability.systables import SystemTableSource

    if plan is None:
        return False
    if isinstance(plan, TableScan) and \
            isinstance(plan.source, SystemTableSource):
        return True
    return any(_scans_system_table(c) for c in plan.children())


class DataFrame:
    """Lazy relational frame over a logical plan (reference:
    BallistaDataFrame, rust/client/src/context.rs:149-315)."""

    def __init__(self, ctx: BallistaContext, plan: Optional[LogicalPlan],
                 raw_sql: Optional[str] = None):
        self.ctx = ctx
        self._plan = plan
        # server-side planning: no local logical plan, the SQL text is
        # submitted with the client catalog and planned by the scheduler
        self._raw_sql = raw_sql
        # standalone mode caches the physical plan across collect() calls so
        # operator jit caches (and table caches) are reused
        self._phys = None

    # -- plan access --------------------------------------------------------

    @property
    def plan(self) -> LogicalPlan:
        if self._plan is None and self._raw_sql is not None:
            # server-planned frame used through the DataFrame API (schema,
            # verbs, count...): plan locally on demand; collect() still
            # takes the raw-SQL path
            planner = SqlPlanner(self.ctx._catalog,
                                 system_provider=self.ctx._system_source)
            self._plan = planner.plan(parse_sql(self._raw_sql))
        if self._plan is None:
            raise PlanError("this DataFrame carries no plan (DDL result)")
        return self._plan

    def schema(self) -> Schema:
        return self.plan.schema()

    def explain(self) -> str:
        from .optimizer import optimize

        return (
            "== Logical plan ==\n" + self.plan.pretty()
            + "== Optimized ==\n" + optimize(self.plan).pretty()
        )

    def explain_analyze(self) -> str:
        """Execute the frame's plan and return the physical plan text
        annotated with live operator metrics — the DataFrame face of SQL
        ``EXPLAIN ANALYZE`` (works in standalone and remote mode; the
        remote plan ships as one task, see physical/explain.py)."""
        from .logical import Explain

        out = self._with(Explain(self.plan, analyze=True)).collect()
        rows = dict(zip(out["plan_type"], out["plan"]))
        return rows.get("plan_with_metrics", "")

    def logical_plan(self) -> LogicalPlan:
        return self.plan

    # -- verbs --------------------------------------------------------------

    def _with(self, plan: LogicalPlan) -> "DataFrame":
        return DataFrame(self.ctx, plan)

    def select(self, *exprs: Union[ex.Expr, str]) -> "DataFrame":
        es = [ex.col(e) if isinstance(e, str) else e for e in exprs]
        return self._with(Projection(list(es), self.plan))

    def select_columns(self, *names: str) -> "DataFrame":
        return self.select(*names)

    def filter(self, predicate: ex.Expr) -> "DataFrame":
        return self._with(Filter(predicate, self.plan))

    where = filter

    def aggregate(self, group_by: Sequence[ex.Expr],
                  aggs: Sequence[ex.Expr]) -> "DataFrame":
        return self._with(Aggregate(list(group_by), list(aggs), self.plan))

    def sort(self, *sort_exprs: ex.Expr) -> "DataFrame":
        ses = [
            e if isinstance(e, ex.SortExpr) else ex.SortExpr(e)
            for e in sort_exprs
        ]
        return self._with(Sort(ses, self.plan))

    def limit(self, n: int) -> "DataFrame":
        return self._with(Limit(n, self.plan))

    def join(self, right: "DataFrame", on: Sequence[Tuple[str, str]],
             how: str = "inner") -> "DataFrame":
        return self._with(Join(self.plan, right.plan, list(on), how))

    def repartition(self, num_partitions: int,
                    hash_exprs: Optional[Sequence[ex.Expr]] = None) -> "DataFrame":
        return self._with(
            Repartition(self.plan, num_partitions,
                        list(hash_exprs) if hash_exprs else None)
        )

    # -- execution ----------------------------------------------------------

    def collect(self, on_progress=None):
        """Execute and return a pandas DataFrame.

        ``on_progress`` (live progress plane): a callable receiving
        progress snapshots — the ONE shape both paths share (job_id,
        fraction, eta_seconds, task counts, per-stage rows; see
        docs/observability.md). On the cluster path snapshots come from
        the scheduler's live job model via the status poll; standalone,
        a sampler thread over the executing plan's MetricsSet reports
        the same shape. Callbacks run on a background/polling thread
        and are best-effort: a raising callback is logged, never the
        query's problem. The final callback reports fraction 1.0."""
        if self._raw_sql is not None:
            import time as _time

            from .distributed.client import remote_sql_collect
            from .observability import ledger as _ledger

            sink: list = []
            jsink: list = []
            _ledger.begin_collect()
            t0 = _time.perf_counter()
            with self.ctx._track_lifecycle(jsink,
                                           self.ctx._active_job_sinks):
                out = remote_sql_collect(
                    self.ctx.host, self.ctx.port, self._raw_sql,
                    self.ctx._catalog, self.ctx.settings, metrics_out=sink,
                    job_id_out=jsink, on_progress=on_progress,
                )
            self.ctx._last_query_metrics = sink[0] if sink else None
            self.ctx._last_query_phys = None
            self.ctx._last_job_id = jsink[0] if jsink else None
            self.ctx._last_query_ledger = None
            self.ctx._last_ledger_env = {
                "wall": _time.perf_counter() - t0,
                "stamps": _ledger.take_collect(),
            }
            return out
        if self.ctx.mode == "standalone":
            out, self._phys = self.ctx._standalone_collect(
                self.plan, phys=self._phys, on_progress=on_progress)
            return out
        return self.ctx._collect(self.plan, on_progress=on_progress)

    def to_pandas(self):
        return self.collect()

    def cancel(self, reason: str = "client") -> int:
        """Cancel the context's in-flight queries (this frame's collect
        included) — see :meth:`BallistaContext.cancel`."""
        return self.ctx.cancel(reason)

    def profile(self, path: Optional[str] = None,
                label: Optional[str] = None) -> str:
        """Execute the frame under the query profiler and write ONE
        Chrome-trace/Perfetto-compatible artifact (trace spans + ingest
        phases + compile attribution + per-operator metrics + named
        wall-time lanes). Returns the artifact path.

        On the cluster path the query runs normally and the SCHEDULER
        builds the merged artifact — its own spans plus every
        executor's per-task profile window, with per-process tracks,
        task flow arrows and cluster-aggregated lanes — which this call
        fetches over the GetJobProfile RPC and writes locally."""
        if self.ctx.mode != "standalone":
            return self._profile_remote(path, label)
        from .observability import profiler as obs_profiler

        box = {}

        def run():
            out, phys = self.ctx._standalone_collect_inner(
                self.plan, phys=self._phys)
            self._phys = phys
            box["phys"] = phys
            return out

        _, artifact = obs_profiler.profile_call(
            run, label=label or "query",
            plan_getter=lambda: box.get("phys"),
            out_path=path,
            out_dir=obs_profiler.profile_dir(),
        )
        return artifact

    def _profile_remote(self, path: Optional[str],
                        label: Optional[str]) -> str:
        """Cluster df.profile(): run the query, then pull the
        scheduler-merged artifact for its job."""
        from .distributed.client import fetch_job_profile
        from .observability import profiler as obs_profiler
        from .observability.export import write_artifact_file

        self.collect()
        job_id = self.ctx._last_job_id
        if not job_id:
            raise BallistaError(
                "no job id recorded for the profiled query")
        # the client can observe job completion BEFORE the scheduler's
        # terminal-transition hook finalizes the job's profile window
        # (completion is published first so result fetches never wait
        # on observability) — briefly retry while the artifact is still
        # marked partial, or while the scheduler holds no window at all
        # yet (a job whose executors shipped no profiles creates its
        # collector slot only at finalize)
        import time as _time

        from .distributed.client import SchedulerClient
        from .errors import ClusterError

        deadline = _time.time() + 10.0
        sched = SchedulerClient(self.ctx.host, self.ctx.port)
        try:
            while True:
                try:
                    art = fetch_job_profile(self.ctx.host, self.ctx.port,
                                            job_id, client=sched)
                except ClusterError:
                    if _time.time() > deadline:
                        raise
                    _time.sleep(0.25)
                    continue
                if not (art.get("distributed") or {}).get("partial") or \
                        _time.time() > deadline:
                    break
                _time.sleep(0.25)
        finally:
            sched.close()
        if label:
            art["label"] = label
        return write_artifact_file(art, out_dir=obs_profiler.profile_dir(),
                                   out_path=path)

    def count(self) -> int:
        agg = Aggregate([], [ex.count().alias("__n")], self.plan)
        out = self.ctx._collect(agg)
        return int(out["__n"][0])

    def show(self, n: int = 20) -> None:
        print(self.limit(n).collect().to_string())
