"""Error types for ballista-tpu.

Mirrors the error taxonomy of the reference engine's ``BallistaError`` enum
(reference: rust/core/src/error.rs:31-163) with Python-idiomatic exception
classes instead of a Rust enum.
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base error for all ballista-tpu failures."""


class NotImplementedError_(BallistaError):
    """Feature recognized but not yet supported."""


class PlanError(BallistaError):
    """Logical/physical planning failure (bad column, type mismatch, ...)."""


class SqlError(BallistaError):
    """SQL tokenizing/parsing failure."""


class SchemaError(BallistaError):
    """Schema mismatch or unknown field."""


class ExecutionError(BallistaError):
    """Runtime failure while executing a physical plan."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class IoError(BallistaError):
    """File/scan/shuffle IO failure."""


class ClusterError(BallistaError):
    """Scheduler/executor control-plane failure. Carries the job id
    when one is known (e.g. a client-side timeout), so the caller can
    inspect the job in ``system.queries`` after the fact."""

    def __init__(self, message: str, job_id: "str | None" = None):
        super().__init__(message)
        self.job_id = job_id


class AdmissionRejected(ClusterError):
    """A submission was SHED by the scheduler's admission plane (quota
    exhausted, queue full, queue-time timeout, draining cluster).
    Retryable by contract: ``retry_after_secs`` tells the client when a
    resubmission has a chance (``remote_collect`` honors it
    automatically within the job timeout). Like
    :class:`ShuffleFetchError`, the message format is a wire contract —
    queue-timeout sheds travel as a terminal failed JobStatus whose
    error string the client re-parses into this class."""

    PREFIX = "ADMISSION_SHED"

    def __init__(self, reason: str, retry_after_secs: float = 1.0,
                 detail: str = "", job_id: "str | None" = None):
        self.reason = reason
        self.retry_after_secs = max(float(retry_after_secs), 0.0)
        msg = (f"{self.PREFIX} reason={reason} "
               f"retry_after={self.retry_after_secs:.3f}")
        if detail:
            msg += f": {detail}"
        super().__init__(msg, job_id=job_id)

    @classmethod
    def parse(cls, message: str):
        """Returns ``(reason, retry_after_secs)`` or None. The tag is
        located anywhere in the message (reporters may prefix it)."""
        idx = (message or "").find(cls.PREFIX)
        if idx < 0:
            return None
        body = message[idx + len(cls.PREFIX):].split(":", 1)[0]
        try:
            fields = dict(kv.split("=", 1) for kv in body.split())
            return (fields.get("reason", "unknown"),
                    float(fields.get("retry_after", 1.0)))
        except (KeyError, ValueError):
            return None


class QueryCancelled(BallistaError):
    """A query was cooperatively cancelled (client CancelJob, server
    deadline, slow-query kill, or executor drain). Terminal but NOT a
    failure: surfaces record status ``cancelled`` with the reason."""

    def __init__(self, reason: str = "client",
                 job_id: "str | None" = None):
        self.reason = reason
        self.job_id = job_id
        suffix = f" [job {job_id}]" if job_id else ""
        super().__init__(f"query cancelled ({reason}){suffix}")


class FaultInjected(IoError):
    """Raised by an armed fault point (testing/faults.py). Subclasses
    IoError so injected task failures look transient to the scheduler's
    recovery (``FaultInjected:`` is in TRANSIENT_ERRORS) and exercise
    the retry-budget machinery exactly like a real IO hiccup."""


class ShuffleFetchError(IoError):
    """A consumer could not fetch a producer stage's shuffle output
    (producer executor dead or its data lost). Carries enough structure
    in the message for the scheduler to re-queue the lost producer
    partitions — the string format is the wire contract, since task
    failures travel as plain error strings (TaskStatus.failed.error).
    """

    PREFIX = "SHUFFLE_FETCH_FAILED"

    def __init__(self, stage_id: int, partition_ids, executor_id: str,
                 cause: str):
        self.stage_id = stage_id
        self.partition_ids = sorted(set(partition_ids))
        self.executor_id = executor_id
        parts = ",".join(str(p) for p in self.partition_ids)
        super().__init__(
            f"{self.PREFIX} stage={stage_id} partitions={parts} "
            f"executor={executor_id}: {cause}"
        )

    @classmethod
    def parse(cls, message: str):
        """Returns (stage_id, [partition_ids], executor_id) or None. The
        tag is located anywhere in the message (reporters may prefix the
        exception class name)."""
        idx = (message or "").find(cls.PREFIX)
        if idx < 0:
            return None
        message = message[idx:]
        try:
            fields = dict(
                kv.split("=", 1)
                for kv in message[len(cls.PREFIX):].split(":", 1)[0].split()
            )
            parts = [int(p) for p in fields["partitions"].split(",") if p]
            return int(fields["stage"]), parts, fields.get("executor", "")
        except (KeyError, ValueError):
            return None
