"""Error types for ballista-tpu.

Mirrors the error taxonomy of the reference engine's ``BallistaError`` enum
(reference: rust/core/src/error.rs:31-163) with Python-idiomatic exception
classes instead of a Rust enum.
"""

from __future__ import annotations


class BallistaError(Exception):
    """Base error for all ballista-tpu failures."""


class NotImplementedError_(BallistaError):
    """Feature recognized but not yet supported."""


class PlanError(BallistaError):
    """Logical/physical planning failure (bad column, type mismatch, ...)."""


class SqlError(BallistaError):
    """SQL tokenizing/parsing failure."""


class SchemaError(BallistaError):
    """Schema mismatch or unknown field."""


class ExecutionError(BallistaError):
    """Runtime failure while executing a physical plan."""


class SerdeError(BallistaError):
    """Plan (de)serialization failure."""


class IoError(BallistaError):
    """File/scan/shuffle IO failure."""


class ClusterError(BallistaError):
    """Scheduler/executor control-plane failure."""
