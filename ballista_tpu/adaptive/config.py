"""AQE knobs: the ``adaptive.*`` section of the Ballista configuration.

Configuration travels as the same string-keyed ``settings`` map every
other knob uses (client ``BallistaContext.standalone(**settings)`` /
``remote(...)``; it rides ``ExecuteQueryParams.settings`` to the
scheduler, so cluster re-planning honours the submitting client's
values). Resolution order per key:

    settings["adaptive.X"]  >  env BALLISTA_ADAPTIVE_X  >  default

Keys (documented in README "Configuration" and docs/adaptive.md):

- ``adaptive.enabled``                    master switch (default on)
- ``adaptive.target_partition_bytes``     coalescing target (64 MiB)
- ``adaptive.broadcast_threshold_bytes``  join demotion threshold (32 MiB)
- ``adaptive.skew_factor``                skew = factor x median (4.0)
- ``adaptive.coalesce`` / ``adaptive.broadcast`` / ``adaptive.skew``
                                          per-rule gates (default on)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

_TRUE = ("1", "on", "true", "yes", "")
_FALSE = ("0", "off", "false", "no", "none")


def _as_bool(raw: str, key: str, default: bool) -> bool:
    v = str(raw).strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    import logging

    logging.getLogger("ballista.adaptive").warning(
        "unrecognized %s value %r; keeping %s", key, raw,
        "on" if default else "off")
    return default


@dataclass(frozen=True)
class AdaptiveConfig:
    enabled: bool = True
    # merge adjacent shuffle partitions up to roughly this many bytes per
    # reader task (Spark's spark.sql.adaptive.advisoryPartitionSizeInBytes
    # plays the same role)
    target_partition_bytes: int = 64 * 1024 * 1024
    # a completed build side under this many bytes demotes a planned
    # shuffle-hash join to a broadcast join
    broadcast_threshold_bytes: int = 32 * 1024 * 1024
    # a partition is skewed when bytes > skew_factor x median(bytes) AND
    # > target_partition_bytes (both guards, like Spark's skewedPartition
    # Factor + ThresholdInBytes pair)
    skew_factor: float = 4.0
    coalesce: bool = True
    broadcast: bool = True
    skew: bool = True

    @staticmethod
    def from_settings(settings: Optional[Dict[str, str]] = None,
                      env: Optional[Dict[str, str]] = None
                      ) -> "AdaptiveConfig":
        s = settings or {}
        env = os.environ if env is None else env

        def raw(key: str):
            if key in s:
                return s[key]
            return env.get("BALLISTA_" + key.upper().replace(".", "_"))

        def boolean(key: str, default: bool) -> bool:
            v = raw(key)
            return default if v is None else _as_bool(v, key, default)

        def integer(key: str, default: int) -> int:
            v = raw(key)
            if v is None:
                return default
            try:
                n = int(str(v).strip())
            except ValueError:
                raise ValueError(
                    f"config key {key!r}: expected an integer byte count, "
                    f"got {v!r}") from None
            if n <= 0:
                raise ValueError(f"config key {key!r}: must be > 0")
            return n

        def floating(key: str, default: float) -> float:
            v = raw(key)
            if v is None:
                return default
            try:
                f = float(str(v).strip())
            except ValueError:
                raise ValueError(
                    f"config key {key!r}: expected a number, got {v!r}"
                ) from None
            if f <= 1.0:
                raise ValueError(f"config key {key!r}: must be > 1")
            return f

        return AdaptiveConfig(
            enabled=boolean("adaptive.enabled", True),
            target_partition_bytes=integer(
                "adaptive.target_partition_bytes", 64 * 1024 * 1024),
            broadcast_threshold_bytes=integer(
                "adaptive.broadcast_threshold_bytes", 32 * 1024 * 1024),
            skew_factor=floating("adaptive.skew_factor", 4.0),
            coalesce=boolean("adaptive.coalesce", True),
            broadcast=boolean("adaptive.broadcast", True),
            skew=boolean("adaptive.skew", True),
        )

    @property
    def coalesce_enabled(self) -> bool:
        return self.enabled and self.coalesce

    @property
    def broadcast_enabled(self) -> bool:
        return self.enabled and self.broadcast

    @property
    def skew_enabled(self) -> bool:
        return self.enabled and self.skew
