"""Standalone (single-process) adaptive execution.

The cluster replanner acts at stage boundaries; in standalone mode the
pipeline breakers play that role: a ``RepartitionExec`` materializes its
whole input before any consumer partition runs, which is exactly the
moment real per-partition sizes exist and nothing downstream has
started. ``apply_adaptive_rules`` walks a planned physical tree
bottom-up, materializes each repartition it can act on, and rewrites:

- **join demotion**: a co-partitioned ``JoinExec`` whose observed build
  side lands under ``broadcast_threshold_bytes`` becomes a merged
  (broadcast-build) join and the probe side's repartition is DROPPED —
  the probe subtree streams straight into the join;
- **coalescing / skew**: otherwise both sides' observed per-bucket
  histograms drive the same ``plan_shuffle_reads`` layout the cluster
  uses; readers are wrapped in :class:`AdaptiveShuffleReadExec` (source
  fragments play the role shuffle producers play in the cluster);
- a repartition outside any join (shuffled aggregation, user
  ``.repartition()``) gets coalescing only.

Sizes are estimated as rows x schema row width — row counts are already
on host after ``_materialize_parts`` (no extra device syncs).
"""

from __future__ import annotations

import logging
from typing import Iterator, List

from ..physical.base import Partitioning, PhysicalPlan
from ..observability import trace_event
from .config import AdaptiveConfig
from .rules import (
    describe_layout,
    layout_has_splits,
    plan_shuffle_reads,
    should_broadcast,
)

log = logging.getLogger("ballista.adaptive")


def _row_bytes(schema) -> int:
    # fixed-size-list columns hold ``length`` elements per row (same
    # accounting as JoinExec's deferred-sync window)
    return max(
        sum(
            f.dtype.device_dtype().itemsize
            * (getattr(f.dtype, "length", 0) or 1)
            for f in schema.fields
        ),
        1,
    )


class AdaptiveShuffleReadExec(PhysicalPlan):
    """Reads a materialized ``RepartitionExec`` through an adaptive
    layout (see adaptive/rules.py): output partition i yields the
    buckets/fragment-ranges ``layout[i]`` selects. The single-process
    analogue of the cluster's range-driven ``ShuffleReaderExec``."""

    def __init__(self, repart, layout, note: str):
        self.repart = repart
        self.layout = [[tuple(r) for r in ranges] for ranges in layout]
        self.note = note

    def output_schema(self):
        return self.repart.output_schema()

    def output_partitioning(self) -> Partitioning:
        base = self.repart.output_partitioning()
        n = len(self.layout)
        # unions of whole hash buckets keep the hash property; fragment
        # splits break it
        if base.kind == "hash" and not layout_has_splits(self.layout):
            return Partitioning("hash", n, base.hash_columns)
        return Partitioning("unknown", n)

    def children(self) -> List[PhysicalPlan]:
        return [self.repart]

    def with_new_children(self, children):
        return AdaptiveShuffleReadExec(children[0], self.layout, self.note)

    def execute(self, partition: int) -> Iterator["object"]:
        for olo, ohi, flo, fhi in self.layout[partition]:
            for q in range(olo, ohi):
                if fhi == 0:
                    yield from self.repart.execute(q)
                else:
                    yield from self.repart.execute_fragments(q, flo, fhi)

    def display(self) -> str:
        return f"AdaptiveShuffleReadExec [adaptive: {self.note}]"


def apply_adaptive_rules(phys: PhysicalPlan,
                         conf: AdaptiveConfig) -> PhysicalPlan:
    """Rewrite a planned standalone physical tree using observed
    repartition histograms. Materializes the repartitions it touches
    (work their consumers would do anyway — the ``_parts`` cache is
    shared with execution). Identity when no rule fires."""
    if not conf.enabled:
        return phys
    return _transform(phys, conf)


def _transform(node: PhysicalPlan, conf: AdaptiveConfig) -> PhysicalPlan:
    from ..physical.join import JoinExec
    from ..physical.operators import RepartitionExec

    if (isinstance(node, JoinExec) and node.partitioned
            and isinstance(node.build, RepartitionExec)
            and isinstance(node.probe, RepartitionExec)):
        # adapt below the shuffle boundary first (deeper joins decide
        # before this one's materialization freezes them)
        build = node.build.with_new_children(
            [_transform(node.build.child, conf)])
        probe = node.probe.with_new_children(
            [_transform(node.probe.child, conf)])
        join = node.with_new_children([build, probe])
        return _adapt_partitioned_join(join, conf)
    kids = node.children()
    if kids:
        new_kids = [_transform(c, conf) for c in kids]
        if not all(a is b for a, b in zip(kids, new_kids)):
            node = node.with_new_children(new_kids)
    if isinstance(node, RepartitionExec):
        return _adapt_lone_repartition(node, conf)
    return node


def _observed_bytes(repart):
    rb = _row_bytes(repart.output_schema())
    totals, per_frag = repart.observed_partition_rows()
    return ([r * rb for r in totals],
            [[r * rb for r in row] for row in per_frag])


def _adapt_partitioned_join(join, conf: AdaptiveConfig):
    from ..physical.join import JoinExec

    build_bytes, _ = _observed_bytes(join.build)
    if should_broadcast(sum(build_bytes), conf):
        total = sum(build_bytes)
        note = (f"broadcast build ({total / 1e6:.2f} MB < "
                f"{conf.broadcast_threshold_bytes / 1e6:.0f} MB threshold)")
        trace_event("adaptive.standalone", rule="broadcast",
                    decision=note, build_bytes=total)
        log.info("adaptive (standalone): %s", note)
        # the probe's repartition is dropped entirely: its child streams
        # into the merged join untouched; the build keeps its (already
        # materialized) repartition and is concatenated across buckets
        return JoinExec(join.build, join.probe.child, join.on, join.how,
                        null_aware=join.null_aware, partitioned=False,
                        adaptive_note=note)
    if not (conf.coalesce_enabled or conf.skew_enabled):
        return join
    probe_bytes, probe_frag = _observed_bytes(join.probe)
    combined = [b + p for b, p in zip(build_bytes, probe_bytes)]
    # coalesce on combined bytes (what a reader task holds), but detect
    # skew on probe mass only — split sub-tasks re-read the whole build
    # bucket, so build-heavy buckets must not split
    layout = plan_shuffle_reads(combined, conf, producer_bytes=probe_frag,
                                allow_skew=True, skew_bytes=probe_bytes)
    if layout is None:
        return join
    build_layout = [[(olo, ohi, 0, 0) for (olo, ohi, _, _) in ranges]
                    for ranges in layout]
    note = describe_layout(join.build.num_partitions, layout)
    trace_event("adaptive.standalone", rule="coalesce+skew", decision=note,
                buckets_before=join.build.num_partitions,
                buckets_after=len(layout))
    log.info("adaptive (standalone): %s", note)
    return join.with_new_children([
        AdaptiveShuffleReadExec(join.build, build_layout, note),
        AdaptiveShuffleReadExec(join.probe, layout, note),
    ])


def _adapt_lone_repartition(repart, conf: AdaptiveConfig):
    """A repartition outside a co-partitioned join (shuffled
    aggregation, explicit ``.repartition()``): whole-bucket coalescing
    only — sub-bucket splits would break downstream grouping."""
    if not conf.coalesce_enabled:
        return repart
    bytes_q, _ = _observed_bytes(repart)
    layout = plan_shuffle_reads(bytes_q, conf, allow_skew=False)
    if layout is None:
        return repart
    note = describe_layout(repart.num_partitions, layout)
    trace_event("adaptive.standalone", rule="coalesce", decision=note,
                buckets_before=repart.num_partitions,
                buckets_after=len(layout))
    log.info("adaptive (standalone): %s", note)
    return AdaptiveShuffleReadExec(repart, layout, note)
