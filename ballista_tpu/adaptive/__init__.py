"""Adaptive query execution (AQE): re-plan not-yet-scheduled stages from
observed runtime metrics instead of static estimates.

The reference engine (like pre-3.0 Spark) fixes partition counts and join
strategies at plan time (reference: docs/architecture.md:9-18 — stages are
carved out of a static physical plan before a single row is read). This
subsystem closes the loop the observability layer opened: each completed
stage reports real per-partition shuffle byte histograms, and the
scheduler rewrites the stages that have not started yet. Three rules, each
independently gateable (see :class:`AdaptiveConfig`):

- **shuffle partition coalescing** — merge adjacent small hash-shuffle
  partitions so each reader task sees ~``target_partition_bytes``;
- **join strategy demotion** — when the build side of a planned
  shuffle-hash join lands under ``broadcast_threshold_bytes``, broadcast
  it and drop the probe side's shuffle repartition;
- **skew splitting** — split a shuffle partition whose bytes exceed
  ``skew_factor`` x the median into producer-subrange sub-tasks.

Cluster path: ``replanner`` hooks stage completion in the scheduler
state machine. Standalone path: ``standalone`` applies the same rules
between pipeline breakers inside one process.
"""

from .config import AdaptiveConfig  # noqa: F401
from .rules import (  # noqa: F401
    describe_layout,
    layout_is_identity,
    plan_shuffle_reads,
    should_broadcast,
)
