"""Cluster-side adaptive re-planning.

Installed by the scheduler service as ``SchedulerState.replan_hook`` and
invoked (under the state lock) whenever a stage completes — the moment
real metrics for that stage exist and its dependents' plans are still
just rows in the state store. Two entry windows:

- a dependent whose inputs are now ALL complete (``ready``): coalesce
  its shuffle reads to ``target_partition_bytes`` and/or split skewed
  partitions, shrinking or reshaping its task list before the first
  task is enqueued;
- a dependent still waiting on other inputs (``blocked``): if the
  completed input is the build side of a planned co-partitioned join
  and it came in under ``broadcast_threshold_bytes``, demote the join
  to a broadcast build and strip the probe side's (not yet started)
  shuffle repartition.

Every rewrite goes through ``SchedulerState.update_stage_plan``, which
bumps the stage version; task definitions carry the version and status
reports echo it, so an executor that raced a re-plan reports into a
dropped bucket instead of corrupting the new plan's bookkeeping.

All decisions are best-effort: any structural condition not recognized
(multi-stage readers, mesh-fused stages, already-started tasks) leaves
the static plan untouched, which is always correct.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..observability import trace_event
from .config import AdaptiveConfig
from .rules import describe_layout, plan_shuffle_reads, should_broadcast

log = logging.getLogger("ballista.adaptive")


def replan_on_stage_complete(state, job_id: str, completed_sid: int,
                             ready_sids: List[int],
                             blocked_sids: List[int]) -> None:
    """SchedulerState.replan_hook entry point."""
    conf = AdaptiveConfig.from_settings(state.get_job_settings(job_id))
    if not conf.enabled:
        return
    for sid in ready_sids:
        try:
            _replan_ready_stage(state, job_id, sid, conf)
        except Exception:  # noqa: BLE001 - static plan is the fallback
            log.exception("adaptive coalesce/skew re-plan failed for "
                          "%s/%d; keeping static plan", job_id, sid)
    if conf.broadcast_enabled:
        for sid in blocked_sids:
            try:
                _maybe_demote_join(state, job_id, sid, completed_sid, conf)
            except Exception:  # noqa: BLE001 - static plan is the fallback
                log.exception("adaptive join demotion failed for %s/%d; "
                              "keeping static plan", job_id, sid)


# -- plan (de)serialization helpers ------------------------------------------


def _load_plan(plan_bytes: bytes):
    from ..proto import ballista_pb2 as pb
    from .. import serde

    node = pb.PhysicalPlanNode()
    node.ParseFromString(plan_bytes)
    return serde.physical_from_proto(node)


def _dump_plan(plan) -> bytes:
    from .. import serde

    return serde.physical_to_proto(plan).SerializeToString()


def _walk(plan):
    yield plan
    for c in plan.children():
        yield from _walk(c)


def _replace_node(plan, old, new):
    if plan is old:
        return new
    kids = plan.children()
    if not kids:
        return plan
    new_kids = [_replace_node(c, old, new) for c in kids]
    if all(a is b for a, b in zip(kids, new_kids)):
        return plan
    return plan.with_new_children(new_kids)


# -- rule 1 + 3: partition coalescing and skew splitting ---------------------


def _replan_ready_stage(state, job_id: str, sid: int,
                        conf: AdaptiveConfig) -> None:
    """Rewrite an about-to-be-enqueued stage's shuffle reads from the
    observed per-partition byte histogram of its (now complete)
    producers."""
    from ..physical.join import JoinExec
    from ..physical.shuffle import UnresolvedShuffleExec

    if not (conf.coalesce_enabled or conf.skew_enabled):
        return
    row = state.get_stage_plan(job_id, sid)
    if row.mesh_devices or row.version > 0:
        return
    if state.stage_started(job_id, sid):
        return
    plan = _load_plan(row.plan_bytes)
    hash_nodes = []  # (UnresolvedShuffleExec, dep_sid, dep StagePlan)
    for nd in (n for n in _walk(plan)
               if isinstance(n, UnresolvedShuffleExec)):
        if len(nd.query_stage_ids) != 1:
            return  # multi-stage reader: shape not understood, bail
        dep = nd.query_stage_ids[0]
        dep_row = state.get_stage_plan(job_id, dep)
        if dep_row.shuffle_spec is not None:
            hash_nodes.append((nd, dep, dep_row))
    if not hash_nodes:
        return
    outs = {r.shuffle_spec[1] for _, _, r in hash_nodes}
    if len(outs) != 1:
        return  # mixed fan-outs cannot share one grouping
    n_out = outs.pop()

    # placement: skew splitting is only sound where sub-reads of one
    # bucket are row-wise unionable — the probe side of a single
    # co-partitioned join whose two inputs are exactly our hash deps.
    # Everything else gets coalescing only (whole buckets preserved).
    joins = [n for n in _walk(plan)
             if isinstance(n, JoinExec) and n.partitioned]
    probe_dep: Optional[int] = None
    if len(joins) > 1:
        return
    if joins:
        j = joins[0]
        b, p = j.build, j.probe
        if not (isinstance(b, UnresolvedShuffleExec)
                and isinstance(p, UnresolvedShuffleExec)):
            return
        if len(hash_nodes) != 2 or {b.query_stage_ids[0],
                                    p.query_stage_ids[0]} != \
                {dep for _, dep, _ in hash_nodes}:
            return
        probe_dep = p.query_stage_ids[0]
    elif any(isinstance(n, JoinExec) for n in _walk(plan)):
        # a merged (or already-demoted) join over a hash shuffle: its
        # build reader spans every bucket anyway — nothing to gain
        return

    hists = {}
    for _, dep, _ in hash_nodes:
        h = state.shuffle_partition_histogram(job_id, dep)
        if h is None:
            return  # producers predate the histogram field, or racing
        hists[dep] = h
    combined = [sum(hists[dep][0][q] for dep in hists)
                for q in range(n_out)]
    layout = plan_shuffle_reads(
        combined, conf,
        producer_bytes=hists[probe_dep][1] if probe_dep is not None
        else None,
        allow_skew=probe_dep is not None,
        # skew must be detected on PROBE mass only: each split sub-task
        # re-reads the whole build bucket, so build-heavy buckets gain
        # nothing from splitting and would pay the build N times over
        skew_bytes=hists[probe_dep][0] if probe_dep is not None else None,
    )
    if layout is None:
        return
    # non-probe inputs mirror the grouping with ALL producers per range:
    # a skew-split probe bucket is joined against its WHOLE build bucket
    # in every sub-task
    broadcast_ranges = [[(olo, ohi, 0, 0) for (olo, ohi, _, _) in ranges]
                        for ranges in layout]
    layouts = {}
    for nd, dep, _ in hash_nodes:
        layouts[dep] = layout if dep == probe_dep else broadcast_ranges
        nd.partition_count = len(layout)
    new_nparts = plan.output_partitioning().num_partitions
    note = describe_layout(n_out, layout)
    version = state.update_stage_plan(
        job_id, sid, plan_bytes=_dump_plan(plan),
        num_partitions=new_nparts, reader_layouts=layouts,
    )
    trace_event("adaptive.replan", job=job_id, stage=sid,
                rule="coalesce" if probe_dep is None else "coalesce+skew",
                decision=note, reads_before=n_out, reads_after=len(layout),
                tasks_before=row.num_partitions, tasks_after=new_nparts,
                version=version)
    log.info("adaptive: job %s stage %d: %s (%d -> %d tasks, v%d)",
             job_id, sid, note, row.num_partitions, new_nparts, version)


# -- rule 2: join strategy demotion ------------------------------------------


def _maybe_demote_join(state, job_id: str, consumer_sid: int,
                       completed_sid: int, conf: AdaptiveConfig) -> None:
    """The completed stage turned out to be a small build side of a
    planned shuffle-hash join: broadcast it and drop the probe side's
    (not yet started) shuffle repartition."""
    from ..physical.join import JoinExec
    from ..physical.shuffle import UnresolvedShuffleExec

    crow = state.get_stage_plan(job_id, consumer_sid)
    if crow.mesh_devices or state.stage_started(job_id, consumer_sid):
        return
    # cheap row-level pre-check before deserializing the plan (this
    # runs under the state lock for EVERY blocked dependent of every
    # completing stage): a demotable join needs the completed stage
    # shuffled AND at least two shuffled deps (build + probe)
    if state.get_stage_plan(job_id, completed_sid).shuffle_spec is None:
        return
    shuffled_deps = sum(
        1 for d in crow.deps
        if state.get_stage_plan(job_id, d).shuffle_spec is not None)
    if shuffled_deps < 2:
        return
    plan = _load_plan(crow.plan_bytes)
    target = next(
        (n for n in _walk(plan)
         if isinstance(n, JoinExec) and n.partitioned
         and isinstance(n.build, UnresolvedShuffleExec)
         and isinstance(n.probe, UnresolvedShuffleExec)
         and n.build.query_stage_ids == [completed_sid]
         and len(n.probe.query_stage_ids) == 1),
        None,
    )
    if target is None:
        return
    probe_sid = target.probe.query_stage_ids[0]
    prow = state.get_stage_plan(job_id, probe_sid)
    if prow.shuffle_spec is None or prow.mesh_devices:
        return
    if state.stage_started(job_id, probe_sid):
        return  # its hash-split output format is already in flight
    if state.stage_consumers(job_id, probe_sid) != [consumer_sid]:
        return  # someone else reads the shuffled layout
    total = state.stage_output_bytes(job_id, completed_sid)
    if total is None or not should_broadcast(total, conf):
        return

    note = (f"broadcast build ({total / 1e6:.2f} MB < "
            f"{conf.broadcast_threshold_bytes / 1e6:.0f} MB threshold)")
    demoted = JoinExec(
        target.build,
        UnresolvedShuffleExec([probe_sid],
                              target.probe.output_schema(),
                              prow.num_partitions),
        target.on, target.how, null_aware=target.null_aware,
        partitioned=False, adaptive_note=note,
    )
    new_plan = _replace_node(plan, target, demoted)
    new_nparts = new_plan.output_partitioning().num_partitions
    # The two stage rewrites below cannot be transactional (two kv
    # writes), so the consumer is made correct under EITHER probe
    # format first: its probe-side reader layout maps task p to ALL
    # n_out hash outputs of producer p — the union of a producer's
    # hash slices IS its full output. If the spec strip lands, the
    # probe writes plain per-task files and the (shuffled-only) layout
    # is simply ignored; if it doesn't (crash between the writes), the
    # probe still hash-splits and the layout reassembles each
    # producer's rows — only the split work is wasted, never rows.
    n_out = prow.shuffle_spec[1]
    probe_layout = [[(0, n_out, p, p + 1)]
                    for p in range(prow.num_partitions)]
    version = state.update_stage_plan(
        job_id, consumer_sid, plan_bytes=_dump_plan(new_plan),
        num_partitions=new_nparts,
        reader_layouts={probe_sid: probe_layout},
    )
    # probe producer stops hash-splitting: its tasks now write ONE
    # partition file each, which the demoted join streams 1:1
    state.update_stage_plan(job_id, probe_sid, shuffle_spec=None)
    trace_event("adaptive.replan", job=job_id, stage=consumer_sid,
                rule="broadcast", decision=note, build_stage=completed_sid,
                probe_stage=probe_sid, build_bytes=total,
                tasks_before=crow.num_partitions, tasks_after=new_nparts,
                version=version)
    log.info("adaptive: job %s stage %d: %s (probe stage %d unshuffled; "
             "%d -> %d tasks, v%d)", job_id, consumer_sid, note,
             probe_sid, crow.num_partitions, new_nparts, version)
