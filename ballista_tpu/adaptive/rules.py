"""Pure AQE decision functions (no engine state, unit-testable).

A *reader layout* describes how a consumer stage's tasks map onto the
shuffle files a producing stage wrote. Producers write one file per
(producer partition p, output partition q); the static layout gives each
consumer task one q read across all p. Adaptive layouts regroup those
files:

    layout: List[List[ReadRange]]     # one entry per NEW consumer task
    ReadRange = (out_lo, out_hi, prod_lo, prod_hi)

A range selects files with ``out_lo <= q < out_hi`` and
``prod_lo <= p < prod_hi``; ``prod_hi == 0`` means "all producers".
Coalescing emits one multi-``q`` range with all producers; skew splitting
emits several single-``q`` ranges with disjoint producer subranges.

Correctness invariants the rules preserve:

- every (p, q) file is read by EXACTLY one new task (union = original);
- coalesced groups are unions of whole hash buckets, so key groups stay
  co-located (safe under final aggregation and co-partitioned joins);
- skew splits carve a single bucket by producer, which is only applied
  where the consumer is row-wise unionable over that input (the join
  probe side — the replanner enforces placement, not these functions).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

ReadRange = Tuple[int, int, int, int]
Layout = List[List[ReadRange]]

ALL_PRODUCERS = (0, 0)


def _median(xs: Sequence[int]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    if n % 2:
        return float(s[mid])
    return (s[mid - 1] + s[mid]) / 2.0


def plan_shuffle_reads(
    bytes_per_partition: Sequence[int],
    conf,
    producer_bytes: Optional[Sequence[Sequence[int]]] = None,
    allow_skew: bool = True,
    skew_bytes: Optional[Sequence[int]] = None,
) -> Optional[Layout]:
    """Plan a reader layout from the observed per-``q`` byte histogram.

    ``producer_bytes[q][p]`` (when available) gives the per-producer
    breakdown used to place skew split points; without it skewed
    partitions are left whole. ``allow_skew`` lets the caller veto
    splitting when the consumer cannot union sub-reads (e.g. a final
    aggregation). ``skew_bytes`` is the histogram skew is DETECTED on
    when it differs from the one being packed: a join coalesces on
    build+probe combined bytes but must only split on probe-side mass —
    splitting a bucket whose weight sits on the (replicated) build side
    would multiply the expensive build work instead of dividing
    anything. Returns None when the static layout stands.
    """
    n = len(bytes_per_partition)
    if n == 0 or not conf.enabled:
        return None
    do_coalesce = conf.coalesce_enabled
    do_skew = conf.skew_enabled and allow_skew and producer_bytes is not None
    if not do_coalesce and not do_skew:
        return None
    target = conf.target_partition_bytes
    sb = skew_bytes if skew_bytes is not None else bytes_per_partition
    med = _median(sb)

    def is_skewed(q: int) -> bool:
        if not do_skew:
            return False
        b = sb[q]
        if b <= target or b <= conf.skew_factor * med:
            return False
        # need at least two producers with data to split anything
        contrib = [p for p, pb in enumerate(producer_bytes[q]) if pb > 0]
        return len(contrib) >= 2

    layout: Layout = []
    group_lo: Optional[int] = None
    group_bytes = 0

    def flush_group(hi: int) -> None:
        nonlocal group_lo, group_bytes
        if group_lo is not None:
            layout.append([(group_lo, hi, *ALL_PRODUCERS)])
            group_lo = None
            group_bytes = 0

    for q in range(n):
        b = bytes_per_partition[q]
        if is_skewed(q):
            flush_group(q)
            layout.extend(
                [(q, q + 1, plo, phi)]
                for plo, phi in _split_producers(producer_bytes[q], target)
            )
            continue
        if group_lo is None:
            group_lo, group_bytes = q, b
            continue
        if do_coalesce and group_bytes + b <= target:
            group_bytes += b
            continue
        flush_group(q)
        group_lo, group_bytes = q, b
    flush_group(n)

    if layout_is_identity(layout, n):
        return None
    return layout


def _split_producers(per_producer: Sequence[int],
                     target: int) -> List[Tuple[int, int]]:
    """Contiguous producer subranges each near ``target`` bytes. Always
    returns >= 2 ranges (callers only split genuinely skewed partitions)
    and covers every producer index exactly once — trailing producers
    with zero bytes ride in the last range."""
    n = len(per_producer)
    total = sum(per_producer)
    # aim for the fewest chunks that bring each under target, bounded by
    # the number of contributing producers (a file is the atomic unit)
    contributing = sum(1 for b in per_producer if b > 0)
    want = min(max(2, -(-total // target)), max(contributing, 2))
    per_chunk = total / want
    out: List[Tuple[int, int]] = []
    lo = 0
    acc = 0
    for p in range(n):
        acc += per_producer[p]
        if acc >= per_chunk and p + 1 < n and len(out) < want - 1:
            out.append((lo, p + 1))
            lo = p + 1
            acc = 0
    out.append((lo, n))
    if len(out) == 1:
        # the mass sits on the last producer so no cut was placed (e.g.
        # [1, 0, 0, 1000]): cut before the last contributing producer —
        # callers rely on >= 2 ranges, and a single all-producer range
        # would masquerade as a split (version bump, hash-partitioning
        # downgrade) while splitting nothing
        last = max(p for p, b in enumerate(per_producer) if b > 0)
        out = [(0, last), (last, n)]
    return out


def layout_is_identity(layout: Layout, n_partitions: int) -> bool:
    """True when the layout reproduces the static one-task-per-``q``,
    all-producers mapping."""
    if len(layout) != n_partitions:
        return False
    for i, ranges in enumerate(layout):
        if ranges != [(i, i + 1, *ALL_PRODUCERS)]:
            return False
    return True


def should_broadcast(total_bytes: int, conf) -> bool:
    """Join demotion gate: a fully-observed side under the threshold is
    cheap enough to hand every consumer task whole."""
    return conf.broadcast_enabled and \
        0 <= total_bytes < conf.broadcast_threshold_bytes


def layout_has_splits(layout: Layout) -> bool:
    return any(r[3] != 0 for ranges in layout for r in ranges)


def describe_layout(n_before: int, layout: Layout) -> str:
    """Human-readable decision summary for EXPLAIN ANALYZE annotations,
    trace spans, and scheduler logs: "coalesced 32->4", "split 1 skewed
    partition into 3", or both comma-joined."""
    n_after = len(layout)
    split_qs = sorted({r[0] for ranges in layout for r in ranges
                       if r[3] != 0})
    parts = []
    n_split_tasks = sum(
        1 for ranges in layout for r in ranges if r[3] != 0)
    n_plain = n_after - n_split_tasks
    n_unsplit_before = n_before - len(split_qs)
    if n_plain != n_unsplit_before or (not split_qs and n_after != n_before):
        parts.append(f"coalesced {n_unsplit_before}→{n_plain}"
                     if split_qs else f"coalesced {n_before}→{n_after}")
    if split_qs:
        qs = ",".join(str(q) for q in split_qs)
        parts.append(f"split skewed partition{'s' if len(split_qs) > 1 else ''}"
                     f" [{qs}] into {n_split_tasks} reads")
    return ", ".join(parts) if parts else "unchanged"
