"""Caching table source: materialize scanned batches once, serve from
memory/device afterwards (the Spark ``.cache()`` analogue; the reference
re-scans files every query, rust/client/src/context.rs:88-108)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..datatypes import Schema
from ..logical import TableSource


class CacheSource(TableSource):
    def __init__(self, inner: TableSource):
        self.inner = inner
        self._cache: Dict[Tuple[int, Optional[Tuple[str, ...]]], list] = {}

    def table_schema(self) -> Schema:
        return self.inner.table_schema()

    def num_partitions(self) -> int:
        return self.inner.num_partitions()

    def source_descriptor(self) -> dict:
        return self.inner.source_descriptor()

    def estimated_rows(self):
        return self.inner.estimated_rows()

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        key = (partition, tuple(projection) if projection is not None else None)
        if key not in self._cache:
            self._cache[key] = list(self.inner.scan(partition, projection))
        yield from self._cache[key]

    def invalidate(self):
        self._cache.clear()
