"""Caching table source: materialize scanned batches once, serve from
memory/device afterwards (the Spark ``.cache()`` analogue; the reference
re-scans files every query, rust/client/src/context.rs:88-108)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..datatypes import Schema
from ..ingest import KeyedLocks
from ..logical import TableSource


class CacheSource(TableSource):
    """Thread-safe: parallel ingest (and self-joins) scan the same
    (partition, projection) key concurrently, so materialization takes a
    PER-KEY lock — exactly one inner scan runs, late arrivals wait for
    it and serve from the cache (an unlocked dict would materialize the
    inner scan once per racer and interleave the insert)."""

    def __init__(self, inner: TableSource):
        import threading

        self.inner = inner
        self._cache: Dict[Tuple[int, Optional[Tuple[str, ...]]], list] = {}
        self._key_locks = KeyedLocks()
        # cache occupancy (observability/memory): guarded by its own
        # lock — concurrent materializations of DIFFERENT keys hold
        # different per-key locks, so an unguarded += could lose an
        # update and leave bytes leaked after invalidate()
        self._size_lock = threading.Lock()
        self._tracked_bytes = 0

    @staticmethod
    def _batches_nbytes(batches: list) -> int:
        total = 0
        # in-memory accounting walk over already-materialized batches
        # ballista: ignore[cancel-coverage]
        for b in batches:
            for c in getattr(b, "columns", []):
                total += int(getattr(c.values, "nbytes", 0))
                if c.validity is not None:
                    total += int(getattr(c.validity, "nbytes", 0))
        return total

    def table_schema(self) -> Schema:
        return self.inner.table_schema()

    def num_partitions(self) -> int:
        return self.inner.num_partitions()

    def source_descriptor(self) -> dict:
        return self.inner.source_descriptor()

    def content_signature(self):
        """Result-cache identity is the INNER data's identity — this
        wrapper adds replay, not different rows."""
        sig_fn = getattr(self.inner, "content_signature", None)
        return sig_fn() if sig_fn is not None else None

    def estimated_rows(self):
        return self.inner.estimated_rows()

    def is_materialized(self, partition: int,
                        projection: Optional[Sequence[str]] = None) -> bool:
        """True when this (partition, projection) is already served from
        memory — the ingest pipeline then skips its prefetch queue (no
        parse/H2D left to overlap; keeps the warm path overhead-free)."""
        key = (partition, tuple(projection) if projection is not None else None)
        return key in self._cache

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        key = (partition, tuple(projection) if projection is not None else None)
        if key not in self._cache:  # fast path: no lock once populated
            with self._key_locks.get(key):
                if key not in self._cache:
                    batches = list(self.inner.scan(partition, projection))
                    # replayed every query: a transient mark from the
                    # inner scan would let the first consumer donate
                    # (delete) buffers later replays still serve
                    for b in batches:
                        b._transient = False
                    from ..observability import memory as obs_memory

                    n = self._batches_nbytes(batches)
                    obs_memory.record_host_bytes("cache", n)
                    with self._size_lock:
                        self._tracked_bytes += n
                    self._cache[key] = batches
        yield from self._cache[key]

    def invalidate(self):
        # locks are NOT dropped: a materialization mid-flight still
        # holds one, and dropping it would let a post-invalidate scan
        # run a second concurrent inner scan against it
        self._cache.clear()
        self._release_tracked()

    def _release_tracked(self):
        from ..observability import memory as obs_memory

        with self._size_lock:
            n, self._tracked_bytes = self._tracked_bytes, 0
        obs_memory.release_host_bytes("cache", n)

    def __del__(self):
        # a CacheSource dropped without invalidate() must not leak its
        # bytes in the accounting gauges
        try:
            self._release_tracked()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
