"""Caching table source: materialize scanned batches once, serve from
memory/device afterwards (the Spark ``.cache()`` analogue; the reference
re-scans files every query, rust/client/src/context.rs:88-108)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..datatypes import Schema
from ..ingest import KeyedLocks
from ..logical import TableSource


class CacheSource(TableSource):
    """Thread-safe: parallel ingest (and self-joins) scan the same
    (partition, projection) key concurrently, so materialization takes a
    PER-KEY lock — exactly one inner scan runs, late arrivals wait for
    it and serve from the cache (an unlocked dict would materialize the
    inner scan once per racer and interleave the insert)."""

    def __init__(self, inner: TableSource):
        self.inner = inner
        self._cache: Dict[Tuple[int, Optional[Tuple[str, ...]]], list] = {}
        self._key_locks = KeyedLocks()

    def table_schema(self) -> Schema:
        return self.inner.table_schema()

    def num_partitions(self) -> int:
        return self.inner.num_partitions()

    def source_descriptor(self) -> dict:
        return self.inner.source_descriptor()

    def estimated_rows(self):
        return self.inner.estimated_rows()

    def is_materialized(self, partition: int,
                        projection: Optional[Sequence[str]] = None) -> bool:
        """True when this (partition, projection) is already served from
        memory — the ingest pipeline then skips its prefetch queue (no
        parse/H2D left to overlap; keeps the warm path overhead-free)."""
        key = (partition, tuple(projection) if projection is not None else None)
        return key in self._cache

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        key = (partition, tuple(projection) if projection is not None else None)
        if key not in self._cache:  # fast path: no lock once populated
            with self._key_locks.get(key):
                if key not in self._cache:
                    self._cache[key] = list(self.inner.scan(partition,
                                                            projection))
        yield from self._cache[key]

    def invalidate(self):
        # locks are NOT dropped: a materialization mid-flight still
        # holds one, and dropping it would let a post-invalidate scan
        # run a second concurrent inner scan against it
        self._cache.clear()
