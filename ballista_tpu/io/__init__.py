"""Table sources: memory, delimited text (.tbl/.csv), Parquet.

TPU-native replacement for the reference's scan layer (reference:
rust/core/proto/ballista.proto:334-354 CsvScan/ParquetScan nodes; client
registration at rust/client/src/context.rs:88-129). Sources produce
fixed-capacity ColumnBatches with interned per-table dictionaries so
string comparisons stay ordinal across all partitions.
"""

from .cache import CacheSource  # noqa: F401
from .memory import MemTableSource  # noqa: F401
from .text import CsvSource, TblSource  # noqa: F401
from .parquet import ParquetSource  # noqa: F401
