"""Columnar IPC persistence for shuffle partitions and result fetch.

Equivalent of the reference's Arrow-IPC shuffle materialization
(reference: rust/core/src/utils.rs:49-84 ``write_stream_to_disk`` +
executor FetchPartition serving at rust/executor/src/flight_service.rs:
193-228). Files are Arrow IPC (pyarrow); the engine's physical column
representations map to Arrow as:

- decimal(s)  -> int64 + field metadata ballista.kind=decimal/scale
- date32      -> int32 + metadata
- utf8        -> Arrow dictionary<int32, utf8> (codes survive verbatim)

Rows are COMPACTED to the live selection before writing, so shuffle files
carry no padding. Readers get physical arrays back plus per-file
dictionaries; ``unify_dictionaries`` merges multiple producers' codes into
one table-wide dictionary via searchsorted remapping (no per-row decode).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..columnar import Column, ColumnBatch, Dictionary
from ..compile import bucket_capacity
from ..datatypes import Field, Schema
from ..errors import IoError


_POOL_CHECKED = False


def _arrow():
    import pyarrow as pa

    global _POOL_CHECKED
    if not _POOL_CHECKED:
        _POOL_CHECKED = True
        # mimalloc (pyarrow's default pool) intermittently corrupts under
        # this engine's thread mix (see ballista_tpu/__init__.py). The env
        # selector set there is inert on builds without jemalloc, so
        # verify at first use and degrade to the system allocator — but
        # only when the pool choice was OURS: a user's explicit
        # ARROW_DEFAULT_MEMORY_POOL always wins.
        # ours only when the env still holds the exact value we recorded
        # at set time: the marker is inherited by child processes, where
        # a user's explicit ARROW_DEFAULT_MEMORY_POOL must win even
        # though the marker is present
        user_chose = (
            "ARROW_DEFAULT_MEMORY_POOL" in os.environ
            and os.environ["ARROW_DEFAULT_MEMORY_POOL"]
            != os.environ.get("_BALLISTA_SET_ARROW_POOL")
        )
        try:
            if (not user_chose
                    and pa.default_memory_pool().backend_name == "mimalloc"
                    and not os.environ.get("BALLISTA_ALLOW_MIMALLOC")):
                pa.set_memory_pool(pa.system_memory_pool())
        except Exception:  # noqa: BLE001 - keep whatever pool exists
            pass
    return pa


def batch_to_arrow(batch: ColumnBatch):
    """Compact a ColumnBatch to a pyarrow RecordBatch (live rows only)."""
    pa = _arrow()
    mask = np.asarray(batch.selection)
    arrays = []
    fields = []
    for f, col in zip(batch.schema.fields, batch.columns):
        vals = np.asarray(col.values)[mask]
        nulls = None
        if col.validity is not None:
            nulls = ~np.asarray(col.validity)[mask]
        meta = {b"ballista.kind": f.dtype.kind.encode(),
                b"ballista.scale": str(f.dtype.scale).encode()}
        if f.dtype.kind == "utf8":
            if col.dictionary is None:
                raise IoError(f"utf8 column {f.name} without dictionary")
            # registry stamp (entry:version:epoch): a reader in this or
            # any sibling process resolves the SAME interned instance
            # instead of re-hydrating values from the wire
            from .. import columnar_registry

            stamp = columnar_registry.REGISTRY.stamp_of(col.dictionary)
            if stamp is not None:
                meta[b"ballista.dict"] = stamp.encode()
            codes = pa.array(vals.astype(np.int32), mask=nulls)
            dict_vals = pa.array(
                [str(v) for v in col.dictionary.values], type=pa.string()
            )
            arr = pa.DictionaryArray.from_arrays(codes, dict_vals)
            fields.append(pa.field(f.name, arr.type, True, meta))
        elif f.dtype.kind == "list":
            # fixed-size list: (rows, length) physical array -> real Arrow
            # FixedSizeListArray (element kind/scale ride in metadata so
            # decimal elements decode without Arrow decimal types)
            meta[b"ballista.element_kind"] = f.dtype.element.kind.encode()
            meta[b"ballista.element_scale"] = str(
                f.dtype.element.scale).encode()
            flat = pa.array(vals.reshape(-1))
            arr = pa.FixedSizeListArray.from_arrays(
                flat, f.dtype.length, mask=nulls)
            fields.append(pa.field(f.name, arr.type, True, meta))
        else:
            arr = pa.array(vals, mask=nulls)
            fields.append(pa.field(f.name, arr.type, True, meta))
        arrays.append(arr)
    return pa.record_batch(arrays, schema=pa.schema(fields))


def write_partition(path: str, batches: List[ColumnBatch],
                    compute_column_stats: bool = True) -> Dict[str, int]:
    """Write batches to an Arrow IPC file; returns PartitionStats dict
    (reference: PartitionStats {num_rows, num_batches, num_bytes},
    ballista.proto:478-485) plus per-column selectivity stats unless
    ``compute_column_stats`` is off (the n_out-way shuffle write path
    turns it off: per-file column stats there have no consumer and a
    64-way shuffle would pay 64 stat passes per task)."""
    pa = _arrow()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rbs = [batch_to_arrow(b) for b in batches]
    if not rbs:
        raise IoError("no batches to write")
    schema = rbs[0].schema
    num_rows = 0
    # write to a tmp file in the same dir then rename: concurrent writers
    # of the same deterministic path (e.g. a speculative duplicate task)
    # can never leave a half-written file visible to a fetching consumer
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with pa.OSFile(tmp, "wb") as sink:
            with pa.ipc.new_file(sink, schema) as writer:
                for rb in rbs:
                    writer.write_batch(rb)
                    num_rows += rb.num_rows
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    out = {
        "num_rows": num_rows,
        "num_batches": len(rbs),
        "num_bytes": os.path.getsize(path),
    }
    if compute_column_stats:
        out["columns"] = _column_stats(rbs)
    return out


def _column_stats(rbs) -> List[Dict]:
    """Per-column {name, null_count, distinct_count, min, max} over the
    written record batches (reference declares ColumnStats but never
    fills it, ballista.proto:478-485; computing at write time makes the
    numbers available to the optimizer for selectivity). min/max use
    pyarrow's vectorized kernels — cheap relative to the IPC write.
    distinct_count is exact for dictionary columns (dict size), -1
    otherwise."""
    pa = _arrow()
    import pyarrow.compute as pc

    table = pa.Table.from_batches(rbs)
    out: List[Dict] = []
    for name in table.column_names:
        col = table.column(name)
        entry: Dict = {"name": name,
                       "null_count": int(col.null_count),
                       "distinct_count": -1}
        try:
            typ = col.type
            if pa.types.is_dictionary(typ):
                # stats over the decoded VALUES (string min/max +
                # exact distinct over the data actually present)
                decoded = col.cast(typ.value_type)
                entry["distinct_count"] = int(
                    pc.count_distinct(decoded).as_py())
                mm = pc.min_max(decoded)
                mn, mx = mm["min"].as_py(), mm["max"].as_py()
            else:
                mm = pc.min_max(col)
                mn, mx = mm["min"].as_py(), mm["max"].as_py()
            if mn is not None:
                entry["min"] = _norm_stat(mn)
                entry["max"] = _norm_stat(mx)
        except Exception:  # noqa: BLE001 - stats stay partial
            pass
        out.append(entry)
    return out


def _norm_stat(v):
    """Normalize a pyarrow .as_py() scalar to the physical repr the
    proto carries (dates -> epoch days)."""
    import datetime as _dt

    if isinstance(v, _dt.datetime):
        v = v.date()
    if isinstance(v, _dt.date):
        return (v - _dt.date(1970, 1, 1)).days
    return v


def decode_fixed_size_list(chunk) -> np.ndarray:
    """FixedSizeListArray chunk -> (rows, width) ndarray of flat values.

    ``.values`` spans all slots (incl. null rows), so the reshape stays
    aligned with the row axis — but it ignores a slice offset on the
    chunk (an Arrow slice adjusts offset/length only, the child stays
    whole), so slice the flat child to this chunk's window first.
    In-repo IPC files always arrive unsliced (serialization materializes
    slices); the offset handling protects direct/zero-copy producers.
    """
    width = chunk.type.list_size
    flat = chunk.values.to_numpy(zero_copy_only=False)
    off = chunk.offset
    flat = flat[off * width:(off + len(chunk)) * width]
    return flat.reshape(len(chunk), width)


def read_partition_arrays(
    path_or_buf,
) -> Tuple[List[str], Dict[str, np.ndarray], Dict[str, np.ndarray],
           Dict[str, np.ndarray], Dict[str, Tuple[str, int]]]:
    """Read an IPC file -> (names, arrays, null_masks, dictionaries, kinds).

    arrays hold PHYSICAL values (codes for utf8); dictionaries map colname ->
    np object array for utf8 columns; kinds map colname -> (kind, scale).
    """
    pa = _arrow()
    if isinstance(path_or_buf, (str, os.PathLike)):
        reader = pa.ipc.open_file(pa.memory_map(str(path_or_buf), "r"))
    else:
        reader = pa.ipc.open_file(pa.BufferReader(path_or_buf))
    table = reader.read_all().combine_chunks()
    names = table.schema.names
    arrays: Dict[str, np.ndarray] = {}
    nulls: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}
    kinds: Dict[str, Tuple[str, int]] = {}
    for i, name in enumerate(names):
        field = table.schema.field(i)
        meta = field.metadata or {}
        kind = meta.get(b"ballista.kind", b"").decode() or None
        scale = int(meta.get(b"ballista.scale", b"0") or 0)
        colarr = table.column(i)
        chunk = colarr.chunk(0) if colarr.num_chunks else colarr.combine_chunks()
        if pa.types.is_dictionary(chunk.type):
            codes = chunk.indices.to_numpy(zero_copy_only=False).astype(np.int32)
            null_mask = np.asarray(chunk.indices.is_null())
            # a registry stamp resolves to the live interned Dictionary
            # (content-verified by epoch) without touching the shipped
            # values; otherwise adopt them once per content epoch so
            # every part/read of equal content shares ONE instance
            from .. import columnar_registry as _reg

            stamp = meta.get(b"ballista.dict", b"").decode() or None
            resolved = _reg.REGISTRY.resolve(stamp)
            if resolved is None and _reg.enabled():
                resolved = _reg.REGISTRY.adopt(
                    stamp,
                    np.asarray(chunk.dictionary.to_pylist(), dtype=object))
            if resolved is not None:
                dicts[name] = resolved
            else:  # registry off: legacy raw value array
                dicts[name] = np.asarray(chunk.dictionary.to_pylist(),
                                         dtype=object)
            arrays[name] = np.where(null_mask, 0, codes).astype(np.int32)
            kinds[name] = ("utf8", 0)
        elif pa.types.is_fixed_size_list(chunk.type):
            null_mask = np.asarray(chunk.is_null())
            arrays[name] = decode_fixed_size_list(chunk)
            ekind = (meta.get(b"ballista.element_kind", b"").decode()
                     or str(chunk.type.value_type))
            escale = int(meta.get(b"ballista.element_scale", b"0") or 0)
            kinds[name] = (f"list:{ekind}", escale)
        else:
            null_mask = np.asarray(chunk.is_null())
            if pa.types.is_integer(chunk.type):
                # stay in integer domain: to_numpy on a nullable int array
                # converts to float64, corrupting scaled-decimal/int64
                # values above 2^53; fill_null copies, so only when needed
                src = chunk.fill_null(0) if null_mask.any() else chunk
                vals = src.to_numpy(zero_copy_only=False)
            else:
                vals = chunk.to_numpy(zero_copy_only=False)
                if null_mask.any():
                    vals = np.where(null_mask, 0, np.nan_to_num(vals))
            arrays[name] = vals
            kinds[name] = (kind or str(chunk.type), scale)
        nulls[name] = null_mask
    return list(names), arrays, nulls, dicts, kinds


def unify_dictionaries(
    parts: List[Tuple[np.ndarray, "Dictionary | np.ndarray"]]
) -> Tuple[Dictionary, List[np.ndarray]]:
    """[(codes, Dictionary-or-raw-values)] from several producers ->
    (shared Dictionary, remapped codes per part). Sorted union keeps
    codes ordinal. Routed through the dictionary registry: producers
    of one table resolve to ONE interned instance (no remap at all),
    version chains remap through cached integer tables, and only
    unregistered content pays a (cached) sorted union."""
    from ..observability.tracing import trace_span
    from .. import columnar_registry

    if not parts:
        return Dictionary([]), []
    with trace_span("host.dictionary", site="ipc.unify", n_parts=len(parts)):
        return columnar_registry.unify_parts(parts)


def batches_from_parts(
    schema: Schema,
    parts: List[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                      Dict[str, np.ndarray]]],
    capacity: Optional[int] = None,
) -> List[ColumnBatch]:
    """Assemble ColumnBatches from several read_partition_arrays results
    (arrays, nulls, dicts per part), unioning utf8 dictionaries."""
    import jax.numpy as jnp

    from ..observability.memory import track_host_bytes

    if not parts:
        return []
    # shuffle-read host buffers: transient, but the peak matters — the
    # memory plane attributes them separately from scan parse buffers
    shuffle_bytes = sum(
        int(getattr(a, "nbytes", 0))
        for arrays, _nulls, _dicts in parts for a in arrays.values()
    )
    with track_host_bytes("shuffle", shuffle_bytes):
        return _batches_from_parts_inner(schema, parts, capacity, jnp)


def _batches_from_parts_inner(schema, parts, capacity, jnp):
    # union dictionaries per utf8 column — split from batches_from_parts
    # only so the shuffle-byte accounting brackets the whole assembly
    union_dicts: Dict[str, Dictionary] = {}
    remaps: Dict[str, List[np.ndarray]] = {}
    for f in schema.fields:
        if f.dtype.kind == "utf8":
            pieces = [(p[0][f.name], p[2][f.name]) for p in parts]
            d, remapped = unify_dictionaries(pieces)
            union_dicts[f.name] = d
            remaps[f.name] = remapped
    out = []
    for pi, (arrays, nulls, dicts) in enumerate(parts):
        n = len(next(iter(arrays.values()))) if arrays else 0
        # shuffle-read batches enter at canonical ladder capacities:
        # unevenly-sized shuffle partitions share compiled signatures
        cap = capacity or bucket_capacity(max(n, 1))
        cols = []
        for f in schema.fields:
            if f.dtype.kind == "utf8":
                vals = remaps[f.name][pi]
            else:
                vals = arrays[f.name].astype(f.dtype.device_dtype())
            vals = vals.astype(f.dtype.device_dtype())
            # pad along the row axis only (list columns are 2-D)
            pad = np.zeros((cap - n,) + vals.shape[1:],
                           dtype=f.dtype.device_dtype())
            vals = np.concatenate([vals, pad])
            nm = nulls.get(f.name)
            validity = None
            if nm is not None and nm.any():
                v = np.ones(cap, dtype=bool)
                v[:n] = ~nm
                validity = jnp.asarray(v)
            cols.append(
                Column(jnp.asarray(vals), f.dtype, validity,
                       union_dicts.get(f.name))
            )
        sel = np.zeros(cap, dtype=bool)
        sel[:n] = True
        out.append(
            ColumnBatch(schema, cols, jnp.asarray(sel),
                        jnp.asarray(np.int32(n)))
        )
    return out
