"""Columnar IPC persistence for shuffle partitions and result fetch.

Equivalent of the reference's Arrow-IPC shuffle materialization
(reference: rust/core/src/utils.rs:49-84 ``write_stream_to_disk`` +
executor FetchPartition serving at rust/executor/src/flight_service.rs:
193-228). Files are Arrow IPC (pyarrow); the engine's physical column
representations map to Arrow as:

- decimal(s)  -> int64 + field metadata ballista.kind=decimal/scale
- date32      -> int32 + metadata
- utf8        -> Arrow dictionary<int32, utf8> (codes survive verbatim)

Rows are COMPACTED to the live selection before writing, so shuffle files
carry no padding. Readers get physical arrays back plus per-file
dictionaries; ``unify_dictionaries`` merges multiple producers' codes into
one table-wide dictionary via searchsorted remapping (no per-row decode).

Streaming layout (docs/shuffle.md): writers emit Arrow IPC **stream**
format with record batches bounded to ``BALLISTA_SHUFFLE_CHUNK_BYTES``
(:class:`PartitionWriter`), so the data plane can serve and readers can
decode partitions chunk-by-chunk without whole-partition buffering.
Readers sniff the format (``ARROW1`` magic = legacy file format) so
both layouts stay readable.
"""

from __future__ import annotations

import io as _io
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..columnar import Column, ColumnBatch, Dictionary
from ..compile import bucket_capacity
from ..datatypes import Field, Schema
from ..errors import IoError

ARROW_FILE_MAGIC = b"ARROW1"


_POOL_CHECKED = False


def _arrow():
    import pyarrow as pa

    global _POOL_CHECKED
    if not _POOL_CHECKED:
        _POOL_CHECKED = True
        # mimalloc (pyarrow's default pool) intermittently corrupts under
        # this engine's thread mix (see ballista_tpu/__init__.py). The env
        # selector set there is inert on builds without jemalloc, so
        # verify at first use and degrade to the system allocator — but
        # only when the pool choice was OURS: a user's explicit
        # ARROW_DEFAULT_MEMORY_POOL always wins.
        # ours only when the env still holds the exact value we recorded
        # at set time: the marker is inherited by child processes, where
        # a user's explicit ARROW_DEFAULT_MEMORY_POOL must win even
        # though the marker is present
        user_chose = (
            "ARROW_DEFAULT_MEMORY_POOL" in os.environ
            and os.environ["ARROW_DEFAULT_MEMORY_POOL"]
            != os.environ.get("_BALLISTA_SET_ARROW_POOL")
        )
        try:
            if (not user_chose
                    and pa.default_memory_pool().backend_name == "mimalloc"
                    and not os.environ.get("BALLISTA_ALLOW_MIMALLOC")):
                pa.set_memory_pool(pa.system_memory_pool())
        except Exception:  # noqa: BLE001 - keep whatever pool exists
            pass
    return pa


def batch_to_arrow(batch: ColumnBatch):
    """Compact a ColumnBatch to a pyarrow RecordBatch (live rows only).

    Every D2H fetch (selection, then each column's buffers) runs under
    a ``device.block`` span, so shuffle-write sync time lands in the
    profiler's ``device_blocked`` lane instead of hiding inside the
    write lane. Fetches stay per-column (not one batched hoist): at
    most ONE full-capacity host copy is live beside the masked
    outputs, the pre-span memory shape."""
    pa = _arrow()
    from ..observability.tracing import trace_span

    with trace_span("device.block", site="ipc.batch_to_arrow"):
        mask = np.asarray(batch.selection)
    arrays = []
    fields = []
    # bounded per-batch column conversion; the chunk loop in
    # write_arrow carries the cancel check
    # ballista: ignore[cancel-coverage]
    for f, col in zip(batch.schema.fields, batch.columns):
        with trace_span("device.block", site="ipc.batch_to_arrow",
                        col=f.name):
            hv = np.asarray(col.values)
            hval = (None if col.validity is None
                    else np.asarray(col.validity))
        vals = hv[mask]
        del hv
        nulls = None
        if hval is not None:
            nulls = ~hval[mask]
        del hval
        meta = {b"ballista.kind": f.dtype.kind.encode(),
                b"ballista.scale": str(f.dtype.scale).encode()}
        if f.dtype.kind == "utf8":
            if col.dictionary is None:
                raise IoError(f"utf8 column {f.name} without dictionary")
            # registry stamp (entry:version:epoch): a reader in this or
            # any sibling process resolves the SAME interned instance
            # instead of re-hydrating values from the wire
            from .. import columnar_registry

            stamp = columnar_registry.REGISTRY.stamp_of(col.dictionary)
            if stamp is not None:
                meta[b"ballista.dict"] = stamp.encode()
            codes = pa.array(vals.astype(np.int32), mask=nulls)
            dict_vals = pa.array(
                [str(v) for v in col.dictionary.values], type=pa.string()
            )
            arr = pa.DictionaryArray.from_arrays(codes, dict_vals)
            fields.append(pa.field(f.name, arr.type, True, meta))
        elif f.dtype.kind == "list":
            # fixed-size list: (rows, length) physical array -> real Arrow
            # FixedSizeListArray (element kind/scale ride in metadata so
            # decimal elements decode without Arrow decimal types)
            meta[b"ballista.element_kind"] = f.dtype.element.kind.encode()
            meta[b"ballista.element_scale"] = str(
                f.dtype.element.scale).encode()
            flat = pa.array(vals.reshape(-1))
            arr = pa.FixedSizeListArray.from_arrays(
                flat, f.dtype.length, mask=nulls)
            fields.append(pa.field(f.name, arr.type, True, meta))
        else:
            arr = pa.array(vals, mask=nulls)
            fields.append(pa.field(f.name, arr.type, True, meta))
        arrays.append(arr)
    return pa.record_batch(arrays, schema=pa.schema(fields))


def _iter_chunked(rb, chunk_bytes: int):
    """Split one Arrow record batch into row slices of at most
    ``chunk_bytes`` (estimated from the batch's mean bytes/row). Slices
    share the parent's buffers; the IPC writer truncates them to the
    slice window on write, so the file carries bounded record batches."""
    n = rb.num_rows
    if n == 0 or rb.nbytes <= chunk_bytes:
        yield rb
        return
    rows = max(int(chunk_bytes / max(rb.nbytes / n, 1e-9)), 1)
    for lo in range(0, n, rows):
        yield rb.slice(lo, min(rows, n - lo))


class _ColumnStatsAcc:
    """Incremental per-column {null_count, distinct_count, min, max}
    accumulator — the streaming replacement for the old whole-table
    stats pass (reference declares ColumnStats but never fills it,
    ballista.proto:478-485). min/max merge per record batch via
    pyarrow's vectorized kernels; distinct_count stays exact for
    dictionary columns by unioning the OBSERVED code sets (codes map
    1:1 to values within one dictionary), and degrades to -1 when a
    stream carries replacement dictionaries."""

    def __init__(self):
        self._cols: Optional[Dict[str, dict]] = None

    def update(self, rb) -> None:
        pa = _arrow()
        import pyarrow.compute as pc

        if self._cols is None:
            self._cols = {
                name: {"null": 0, "min": None, "max": None,
                       "codes": set(), "first_dict": None, "multi": False}
                for name in rb.schema.names
            }
        # bounded per-record-batch stats merge; callers' chunk loops
        # carry the cancel check
        # ballista: ignore[cancel-coverage]
        for i, name in enumerate(rb.schema.names):
            st = self._cols[name]
            col = rb.column(i)
            st["null"] += int(col.null_count)
            try:
                typ = col.type
                if pa.types.is_dictionary(typ):
                    if st["first_dict"] is None:
                        st["first_dict"] = col.dictionary
                    elif not st["multi"] and not (
                            col.dictionary is st["first_dict"]
                            or col.dictionary.equals(st["first_dict"])):
                        st["multi"] = True
                    if not st["multi"]:
                        st["codes"].update(
                            pc.unique(col.indices.drop_null()).to_pylist())
                    mm = pc.min_max(col.cast(typ.value_type))
                else:
                    st["codes"] = None
                    mm = pc.min_max(col)
                mn, mx = mm["min"].as_py(), mm["max"].as_py()
                if mn is not None:
                    mn, mx = _norm_stat(mn), _norm_stat(mx)
                    st["min"] = mn if st["min"] is None else min(st["min"], mn)
                    st["max"] = mx if st["max"] is None else max(st["max"], mx)
            except Exception:  # noqa: BLE001 - stats stay partial
                pass

    def rows(self) -> List[Dict]:
        out: List[Dict] = []
        for name, st in (self._cols or {}).items():
            entry: Dict = {"name": name, "null_count": st["null"],
                           "distinct_count": -1}
            if st["codes"] is not None and not st["multi"]:
                entry["distinct_count"] = len(st["codes"])
            if st["min"] is not None:
                entry["min"] = st["min"]
                entry["max"] = st["max"]
            out.append(entry)
        return out


class PartitionWriter:
    """Incremental Arrow-IPC STREAM writer for partition/shuffle files.

    The streaming replacement for materialize-then-write: callers push
    ColumnBatches as the plan produces them and each is converted,
    sliced to at most ``BALLISTA_SHUFFLE_CHUNK_BYTES`` record batches
    and written immediately — peak host memory is one chunk, not one
    partition. Every chunk write checks the thread's cancel token (a
    fired ``ctx.cancel()``/deadline aborts a multi-GB write mid-file)
    and charges the shuffle memory governor transiently so the
    in-flight gauge covers the write side too.

    tmp+rename semantics are preserved: concurrent writers of the same
    deterministic path (e.g. a speculative duplicate task) can never
    leave a half-written file visible to a fetching consumer. ``close``
    on a writer that saw no batches synthesizes one empty record batch
    from ``schema`` (or raises when none was given), matching the old
    empty-partition file shape."""

    def __init__(self, path: str, schema: Optional[Schema] = None,
                 chunk_bytes: Optional[int] = None,
                 compute_column_stats: bool = False):
        from ..distributed import spill as _spill

        self._pa = _arrow()
        self.path = path
        self._schema = schema
        self._chunk_bytes = chunk_bytes or _spill.shuffle_chunk_bytes()
        self._stats = _ColumnStatsAcc() if compute_column_stats else None
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        self._sink = None
        self._writer = None
        self.num_rows = 0
        self.num_batches = 0
        self.write_seconds = 0.0
        self._done = False

    def write_batch(self, batch: ColumnBatch) -> None:
        self.write_arrow(batch_to_arrow(batch))

    def write_arrow(self, rb) -> None:
        from ..distributed import spill as _spill
        from ..lifecycle import check_cancel

        gov = _spill.governor()
        for piece in _iter_chunked(rb, self._chunk_bytes):
            # chunk-level cancellation: deadlines/ctx.cancel() abort
            # inside a large partition write, not after it
            check_cancel()
            nbytes = int(piece.nbytes)
            gov.charge(nbytes)
            try:
                t0 = time.perf_counter()
                if self._writer is None:
                    self._sink = self._pa.OSFile(self._tmp, "wb")
                    self._writer = self._pa.ipc.new_stream(
                        self._sink, piece.schema)
                self._writer.write_batch(piece)
                self.write_seconds += time.perf_counter() - t0
            finally:
                gov.release(nbytes)
            self.num_batches += 1
            self.num_rows += piece.num_rows
            if self._stats is not None:
                self._stats.update(piece)

    def close(self) -> Dict[str, int]:
        if self._done:
            raise IoError(f"partition writer already closed: {self.path}")
        if self._writer is None:
            if self._schema is None:
                raise IoError("no batches to write")
            from ..columnar import empty_batch

            self.write_batch(empty_batch(self._schema))
        try:
            self._writer.close()
            self._sink.close()
            os.replace(self._tmp, self.path)
        except BaseException:
            self.abort()
            raise
        self._done = True
        out = {
            "num_rows": self.num_rows,
            "num_batches": self.num_batches,
            "num_bytes": os.path.getsize(self.path),
        }
        if self._stats is not None:
            out["columns"] = self._stats.rows()
        return out

    def abort(self) -> None:
        """Best-effort cleanup for failed writes: close handles, drop
        the tmp file (idempotent)."""
        self._done = True
        for h in (self._writer, self._sink):
            try:
                if h is not None:
                    h.close()
            except Exception:  # noqa: BLE001 - already broken
                pass
        try:
            os.unlink(self._tmp)
        except OSError:
            pass


def write_partition(path: str, batches: List[ColumnBatch],
                    compute_column_stats: bool = True) -> Dict[str, int]:
    """Write batches to an Arrow IPC stream file; returns PartitionStats
    dict (reference: PartitionStats {num_rows, num_batches, num_bytes},
    ballista.proto:478-485) plus per-column selectivity stats unless
    ``compute_column_stats`` is off (the n_out-way shuffle write path
    turns it off: per-file column stats there have no consumer and a
    64-way shuffle would pay 64 stat passes per task). Thin list-based
    wrapper over :class:`PartitionWriter`."""
    from ..lifecycle import check_cancel

    w = PartitionWriter(path, compute_column_stats=compute_column_stats)
    try:
        for b in batches:
            # batch-level cancellation on top of write_arrow's
            # chunk-level checks (w is dynamic, so the analyzer cannot
            # follow the call)
            check_cancel()
            w.write_batch(b)
        return w.close()
    except BaseException:
        w.abort()
        raise


def _norm_stat(v):
    """Normalize a pyarrow .as_py() scalar to the physical repr the
    proto carries (dates -> epoch days)."""
    import datetime as _dt

    if isinstance(v, _dt.datetime):
        v = v.date()
    if isinstance(v, _dt.date):
        return (v - _dt.date(1970, 1, 1)).days
    return v


def decode_fixed_size_list(chunk) -> np.ndarray:
    """FixedSizeListArray chunk -> (rows, width) ndarray of flat values.

    ``.values`` spans all slots (incl. null rows), so the reshape stays
    aligned with the row axis — but it ignores a slice offset on the
    chunk (an Arrow slice adjusts offset/length only, the child stays
    whole), so slice the flat child to this chunk's window first.
    In-repo IPC files always arrive unsliced (serialization materializes
    slices); the offset handling protects direct/zero-copy producers.
    """
    width = chunk.type.list_size
    flat = chunk.values.to_numpy(zero_copy_only=False)
    off = chunk.offset
    flat = flat[off * width:(off + len(chunk)) * width]
    return flat.reshape(len(chunk), width)


class _ChunkStream(_io.RawIOBase):
    """File-like adapter over an iterator of byte chunks — lets
    pyarrow's stream reader pull wire/spill chunks on demand, so decode
    consumes the transfer incrementally instead of requiring one
    contiguous whole-partition buffer."""

    def __init__(self, chunks: Iterable[bytes]):
        self._it = iter(chunks)
        self._buf = b""
        self._eof = False

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            parts = [self._buf] + list(self._it)
            self._buf = b""
            self._eof = True
            return b"".join(parts)
        while len(self._buf) < n and not self._eof:
            try:
                self._buf += next(self._it)
            except StopIteration:
                self._eof = True
        out, self._buf = self._buf[:n], self._buf[n:]
        return out


def open_arrow_reader(source):
    """Open an Arrow IPC source (path, bytes, or file-like) in either
    layout: legacy random-access FILE format (``ARROW1`` magic) or the
    streaming STREAM format the chunked shuffle writers emit. Returns a
    pyarrow reader exposing ``schema`` / ``read_all()`` /
    ``read_next_batch()``."""
    pa = _arrow()
    if isinstance(source, (str, os.PathLike)):
        with open(source, "rb") as fh:
            head = fh.read(len(ARROW_FILE_MAGIC))
        src = pa.memory_map(str(source), "r")
    elif isinstance(source, (bytes, bytearray, memoryview)):
        head = bytes(source[:len(ARROW_FILE_MAGIC)])
        src = pa.BufferReader(source)
    else:  # file-like: stream format only (no seekable magic check)
        return pa.ipc.open_stream(source)
    if head == ARROW_FILE_MAGIC:
        return pa.ipc.open_file(src)
    return pa.ipc.open_stream(src)


def read_partition_arrays(
    path_or_buf,
) -> Tuple[List[str], Dict[str, np.ndarray], Dict[str, np.ndarray],
           Dict[str, np.ndarray], Dict[str, Tuple[str, int]]]:
    """Read an IPC partition -> (names, arrays, null_masks, dictionaries,
    kinds).

    arrays hold PHYSICAL values (codes for utf8); dictionaries map colname ->
    np object array for utf8 columns; kinds map colname -> (kind, scale).
    Accepts both IPC layouts (see :func:`open_arrow_reader`); decode is
    incremental per record batch, so a memory-mapped stream file never
    materializes its wire bytes as one blob.
    """
    return _decode_reader(open_arrow_reader(path_or_buf))


def read_partition_arrays_from_chunks(chunks: Iterable[bytes]):
    """Incremental variant of :func:`read_partition_arrays` fed by an
    iterator of stream-format byte chunks (the flow-controlled data
    plane fetch, or a ChunkBuffer replay spanning RAM + spill files).
    Chunks are pulled — and can be released by the producer — as the
    decoder advances; a truncated stream raises pyarrow's invalid-IPC
    error, which shuffle readers tag into ShuffleFetchError."""
    pa = _arrow()
    return _decode_reader(pa.ipc.open_stream(_ChunkStream(chunks)))


def _batch_iter(reader):
    from ..lifecycle import check_cancel

    if hasattr(reader, "num_record_batches"):  # legacy FILE format
        for i in range(reader.num_record_batches):
            # per-record-batch cancellation at the producer, so every
            # consumer of this iterator inherits it
            check_cancel()
            yield reader.get_batch(i)
        return
    while True:
        check_cancel()
        try:
            rb = reader.read_next_batch()
        except StopIteration:
            return
        yield rb


def _decode_reader(reader):
    """Shared incremental decode core: accumulate per-record-batch
    numpy pieces (checking the thread's cancel token at every batch
    boundary) and concatenate once — peak host memory is the decoded
    arrays plus ONE batch's wire window, never decoded + whole blob."""
    pa = _arrow()
    from ..lifecycle import check_cancel

    schema = reader.schema
    names = list(schema.names)
    metas = [schema.field(i).metadata or {} for i in range(len(names))]
    pieces: Dict[str, List[np.ndarray]] = {n: [] for n in names}
    null_pieces: Dict[str, List[np.ndarray]] = {n: [] for n in names}
    # utf8 columns: per-batch (codes, dictionary) with replacement
    # detection — a stream is allowed to swap dictionaries mid-flight
    dict_state: Dict[str, dict] = {}
    n_batches = 0
    for rb in _batch_iter(reader):
        # chunk-level cancellation: ctx.cancel()/deadlines abort
        # mid-partition decodes (local mmap reads included)
        check_cancel()
        n_batches += 1
        for i, name in enumerate(names):
            col = rb.column(i)
            if pa.types.is_dictionary(col.type):
                codes = col.indices.to_numpy(
                    zero_copy_only=False).astype(np.int32)
                nm = np.asarray(col.indices.is_null())
                st = dict_state.setdefault(
                    name, {"first": col.dictionary, "multi": False,
                           "parts": []})
                if not st["multi"] and not (
                        col.dictionary is st["first"]
                        or col.dictionary.equals(st["first"])):
                    st["multi"] = True
                zeroed = np.where(nm, 0, codes).astype(np.int32)
                # dict columns assemble from st["parts"] alone (see
                # _finish_dict_column); pieces[name] stays unused
                st["parts"].append((zeroed, col.dictionary))
            elif pa.types.is_fixed_size_list(col.type):
                nm = np.asarray(col.is_null())
                pieces[name].append(decode_fixed_size_list(col))
            else:
                nm = np.asarray(col.is_null())
                if pa.types.is_integer(col.type):
                    # stay in integer domain: to_numpy on a nullable int
                    # array converts to float64, corrupting scaled-
                    # decimal/int64 values above 2^53; fill_null copies,
                    # so only when needed
                    src = col.fill_null(0) if nm.any() else col
                    pieces[name].append(src.to_numpy(zero_copy_only=False))
                else:
                    vals = col.to_numpy(zero_copy_only=False)
                    if nm.any():
                        vals = np.where(nm, 0, np.nan_to_num(vals))
                    pieces[name].append(vals)
            null_pieces[name].append(nm)

    arrays: Dict[str, np.ndarray] = {}
    nulls: Dict[str, np.ndarray] = {}
    dicts: Dict[str, np.ndarray] = {}
    kinds: Dict[str, Tuple[str, int]] = {}
    for i, name in enumerate(names):
        meta = metas[i]
        kind = meta.get(b"ballista.kind", b"").decode() or None
        scale = int(meta.get(b"ballista.scale", b"0") or 0)
        ftype = schema.field(i).type
        if pa.types.is_dictionary(ftype):
            arrays[name], dicts[name] = _finish_dict_column(
                name, dict_state.get(name), meta)
            kinds[name] = ("utf8", 0)
        elif pa.types.is_fixed_size_list(ftype):
            width = ftype.list_size
            edtype = np.dtype(ftype.value_type.to_pandas_dtype())
            arrays[name] = (
                np.concatenate(pieces[name])
                if pieces[name] else np.zeros((0, width), dtype=edtype))
            ekind = (meta.get(b"ballista.element_kind", b"").decode()
                     or str(ftype.value_type))
            escale = int(meta.get(b"ballista.element_scale", b"0") or 0)
            kinds[name] = (f"list:{ekind}", escale)
        else:
            arrays[name] = _concat_pieces(pieces[name], ftype)
            kinds[name] = (kind or str(ftype), scale)
        nps = null_pieces[name]
        nulls[name] = (nps[0] if len(nps) == 1
                       else np.concatenate(nps) if nps
                       else np.zeros(0, dtype=bool))
    return names, arrays, nulls, dicts, kinds


def _concat_pieces(ps: List[np.ndarray], ftype) -> np.ndarray:
    if len(ps) == 1:
        return ps[0]
    if not ps:
        return np.zeros(0, dtype=np.dtype(ftype.to_pandas_dtype()))
    return np.concatenate(ps)


def _finish_dict_column(name: str, st: Optional[dict], meta: dict):
    """Assemble one utf8 column from its per-batch (codes, dictionary)
    pieces. Single-dictionary streams (the writers' contract) resolve
    the registry stamp or adopt the values once, exactly like the old
    whole-table path; replacement dictionaries remap every batch onto
    the registry's sorted union before concatenating."""
    from .. import columnar_registry as _reg

    if st is None or not st["parts"]:
        return np.zeros(0, dtype=np.int32), np.asarray([], dtype=object)
    if st["multi"]:
        parts = [
            (codes, np.asarray(d.to_pylist(), dtype=object))
            for codes, d in st["parts"]
        ]
        unified, remapped = _reg.unify_parts(parts)
        codes = (remapped[0] if len(remapped) == 1
                 else np.concatenate(remapped)).astype(np.int32)
        return codes, unified
    codes_list = [codes for codes, _ in st["parts"]]
    codes = (codes_list[0] if len(codes_list) == 1
             else np.concatenate(codes_list))
    # a registry stamp resolves to the live interned Dictionary
    # (content-verified by epoch) without touching the shipped values;
    # otherwise adopt them once per content epoch so every part/read of
    # equal content shares ONE instance
    stamp = meta.get(b"ballista.dict", b"").decode() or None
    resolved = _reg.REGISTRY.resolve(stamp)
    if resolved is None and _reg.enabled():
        resolved = _reg.REGISTRY.adopt(
            stamp,
            np.asarray(st["first"].to_pylist(), dtype=object))
    if resolved is not None:
        return codes, resolved
    # registry off: legacy raw value array
    return codes, np.asarray(st["first"].to_pylist(), dtype=object)


def unify_dictionaries(
    parts: List[Tuple[np.ndarray, "Dictionary | np.ndarray"]]
) -> Tuple[Dictionary, List[np.ndarray]]:
    """[(codes, Dictionary-or-raw-values)] from several producers ->
    (shared Dictionary, remapped codes per part). Sorted union keeps
    codes ordinal. Routed through the dictionary registry: producers
    of one table resolve to ONE interned instance (no remap at all),
    version chains remap through cached integer tables, and only
    unregistered content pays a (cached) sorted union."""
    from ..observability.tracing import trace_span
    from .. import columnar_registry

    if not parts:
        return Dictionary([]), []
    with trace_span("host.dictionary", site="ipc.unify", n_parts=len(parts)):
        return columnar_registry.unify_parts(parts)


def batches_from_parts(
    schema: Schema,
    parts: List[Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                      Dict[str, np.ndarray]]],
    capacity: Optional[int] = None,
) -> List[ColumnBatch]:
    """Assemble ColumnBatches from several read_partition_arrays results
    (arrays, nulls, dicts per part), unioning utf8 dictionaries."""
    import jax.numpy as jnp

    from ..observability.memory import track_host_bytes

    if not parts:
        return []
    # shuffle-read host buffers: transient, but the peak matters — the
    # memory plane attributes them separately from scan parse buffers
    shuffle_bytes = sum(
        int(getattr(a, "nbytes", 0))
        for arrays, _nulls, _dicts in parts for a in arrays.values()
    )
    with track_host_bytes("shuffle", shuffle_bytes):
        return _batches_from_parts_inner(schema, parts, capacity, jnp)


def _batches_from_parts_inner(schema, parts, capacity, jnp):
    # union dictionaries per utf8 column — split from batches_from_parts
    # only so the shuffle-byte accounting brackets the whole assembly
    from ..lifecycle import check_cancel

    union_dicts: Dict[str, Dictionary] = {}
    remaps: Dict[str, List[np.ndarray]] = {}
    for f in schema.fields:
        if f.dtype.kind == "utf8":
            pieces = [(p[0][f.name], p[2][f.name]) for p in parts]
            d, remapped = unify_dictionaries(pieces)
            union_dicts[f.name] = d
            remaps[f.name] = remapped
    out = []
    for pi, (arrays, nulls, dicts) in enumerate(parts):
        # per-part cancellation: assembly pads + uploads every part
        # (H2D), real work a fired token must be able to stop
        check_cancel()
        n = len(next(iter(arrays.values()))) if arrays else 0
        # shuffle-read batches enter at canonical ladder capacities:
        # unevenly-sized shuffle partitions share compiled signatures
        cap = capacity or bucket_capacity(max(n, 1))
        cols = []
        for f in schema.fields:
            if f.dtype.kind == "utf8":
                vals = remaps[f.name][pi]
            else:
                vals = arrays[f.name].astype(f.dtype.device_dtype())
            vals = vals.astype(f.dtype.device_dtype())
            # pad along the row axis only (list columns are 2-D)
            pad = np.zeros((cap - n,) + vals.shape[1:],
                           dtype=f.dtype.device_dtype())
            vals = np.concatenate([vals, pad])
            nm = nulls.get(f.name)
            validity = None
            if nm is not None and nm.any():
                v = np.ones(cap, dtype=bool)
                v[:n] = ~nm
                validity = jnp.asarray(v)
            cols.append(
                Column(jnp.asarray(vals), f.dtype, validity,
                       union_dicts.get(f.name))
            )
        sel = np.zeros(cap, dtype=bool)
        sel[:n] = True
        out.append(
            ColumnBatch(schema, cols, jnp.asarray(sel),
                        jnp.asarray(np.int32(n)))
        )
    return out
