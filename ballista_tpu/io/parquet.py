"""Parquet table source (via pyarrow).

Equivalent of the reference's ParquetScan + GetFileMetadata surface
(reference: rust/core/proto/ballista.proto:348-354, rust/scheduler/src/
lib.rs:184-222). One partition per file (directory datasets) or per
row-group chunk of a single file.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..ingest.phases import phase

from ..columnar import ColumnBatch, Dictionary, DEFAULT_BATCH_CAPACITY
from ..compile import bucket_capacity
from ..datatypes import (
    Boolean,
    DataType,
    Date32,
    Decimal,
    Field,
    Float32,
    Float64,
    Int32,
    Int64,
    Schema,
    Utf8,
)
from ..errors import IoError
from ..logical import TableSource


def _arrow_to_dtype(t) -> DataType:
    import pyarrow as pa

    if pa.types.is_int64(t) or pa.types.is_uint32(t):
        return Int64
    if pa.types.is_integer(t):
        return Int32
    if pa.types.is_float64(t):
        return Float64
    if pa.types.is_floating(t):
        return Float32
    if pa.types.is_boolean(t):
        return Boolean
    if pa.types.is_decimal(t):
        return Decimal(t.scale)
    if pa.types.is_date(t):
        return Date32
    if pa.types.is_timestamp(t):
        from ..datatypes import TimestampNs

        return TimestampNs
    if pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_dictionary(t):
        return Utf8
    raise IoError(f"unsupported parquet type {t}")


class ParquetSource(TableSource):
    def __init__(self, path: str, schema: Optional[Schema] = None,
                 batch_capacity: int = DEFAULT_BATCH_CAPACITY):
        import pyarrow.parquet as pq

        self._path = path
        if os.path.isdir(path):
            self._files = sorted(
                os.path.join(path, f) for f in os.listdir(path)
                if f.endswith(".parquet")
            )
            if not self._files:
                raise IoError(f"no parquet files under {path}")
        else:
            self._files = [path]
        self._capacity = batch_capacity
        pf = pq.ParquetFile(self._files[0])
        arrow_schema = pf.schema_arrow
        if schema is None:
            fields = [
                Field(n, _arrow_to_dtype(arrow_schema.field(n).type), True)
                for n in arrow_schema.names
            ]
            schema = Schema(fields)
        self._schema = schema
        self._dicts: Dict[str, Dictionary] = {}
        # dictionary-registry entry identity (see io/text.py): same
        # parquet files -> shared interned dictionaries per column
        from .. import columnar_registry

        self._dict_key_base = columnar_registry.file_entry_key(
            "parquet", path, self._files)
        # concurrent partition scans (parallel ingest) share one
        # dictionary instance per column; per-COLUMN locks so builds of
        # distinct columns overlap on the ingest pool (each build reads
        # every file — serializing them would re-serialize the cold
        # path this subsystem pipelines)
        from ..ingest import KeyedLocks

        self._dict_locks = KeyedLocks()

    def table_schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self._files)

    def source_descriptor(self) -> dict:
        return {"kind": "parquet", "path": self._path}

    def estimated_rows(self) -> Optional[int]:
        est = getattr(self, "_est_rows", None)
        if est is None:  # footer reads are real IO — compute once
            import pyarrow.parquet as pq

            est = sum(pq.ParquetFile(f).metadata.num_rows
                      for f in self._files)
            self._est_rows = est
        return est

    def _dictionary_for(self, colname: str) -> Dictionary:
        import pyarrow.parquet as pq

        from .. import columnar_registry

        if colname in self._dicts:  # fast path once built
            return self._dicts[colname]
        with self._dict_locks.get(colname):
            if colname in self._dicts:
                return self._dicts[colname]
            key = self._dict_key_base + (colname,)
            d = columnar_registry.REGISTRY.lookup(key)
            if d is not None:
                self._dicts[colname] = d
                return d
            with phase("parse"):
                uniq: Optional[np.ndarray] = None
                for f in self._files:
                    t = pq.read_table(f, columns=[colname])
                    # NULL strings follow the text-path convention: ""
                    # is the stored value, validity rides separately
                    # (and None would break object-array sorting)
                    vals = np.asarray(
                        ["" if v is None else v
                         for v in t.column(0).to_pylist()], dtype=object)
                    u = np.unique(vals)  # dict-ok: raw-value dict build
                    uniq = (u if uniq is None
                            else np.unique(  # dict-ok: raw-value build
                                np.concatenate([uniq, u])))
                d = columnar_registry.intern(
                    key, uniq if uniq is not None else [])
                self._dicts[colname] = d
                return d

    def content_signature(self) -> Optional[tuple]:
        """Re-stat'd file identity — the result-cache invalidation
        signal for parquet tables."""
        from .. import columnar_registry

        return columnar_registry.file_entry_key(
            "parquet", self._path, self._files)

    def residency_key(self, partition: int,
                      projection=None) -> Optional[tuple]:
        from ..cache import residency

        return residency.scan_key(
            "parquet", self._files[partition], partition, projection,
            extra=(self._capacity,))

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        from ..cache import residency

        yield from residency.serve_or_fill(
            self.residency_key(partition, projection),
            lambda: self._scan_direct(partition, projection),
            outcome_sink=self._note_scan_outcome(partition))

    def _scan_direct(self, partition: int,
                     projection: Optional[Sequence[str]] = None):
        """The uncached parse + H2D path (residency misses land here)."""
        import pyarrow.parquet as pq

        names = list(projection) if projection is not None else list(self._schema.names())
        sub_schema = self._schema.project(names)
        with phase("parse", path=self._files[partition]):
            table = pq.read_table(self._files[partition], columns=names)
            n = table.num_rows
            arrays: Dict[str, np.ndarray] = {}
            dicts: Dict[str, Dictionary] = {}
            valids: Dict[str, np.ndarray] = {}
            for name in names:
                field = self._schema.field(name)
                colarr = table.column(name).combine_chunks()
                # NULLs: non-string columns surface validity=False (same
                # convention as the text scanners — the physical value is
                # a harmless fill, the mask is the truth); utf8 NULLs
                # store "" (a value), matching io/text.py's fillna("")
                null_mask = None
                if colarr.null_count:
                    null_mask = np.asarray(colarr.is_null())
                    if field.dtype.kind != "utf8":
                        valids[name] = ~null_mask
                if field.dtype.kind == "utf8":
                    d = self._dictionary_for(name)
                    vals = np.asarray(
                        ["" if v is None else v for v in colarr.to_pylist()],
                        dtype=object)
                    arrays[name] = d.positions_of(vals)
                    dicts[name] = d
                elif field.dtype.kind == "decimal":
                    from ..columnar import decimal_to_scaled

                    vals = colarr.cast("float64").to_numpy(
                        zero_copy_only=False)
                    if null_mask is not None:  # NaN would scale to garbage
                        vals = np.where(null_mask, 0.0, vals)
                    arrays[name] = decimal_to_scaled(vals, field.dtype.scale)
                elif field.dtype.kind == "date32":
                    import pyarrow as pa

                    # files may store dates as date32 OR timestamps
                    # (pandas writers); normalize through date32 ->
                    # days-since-epoch. NULLs fill at the ARROW level:
                    # to_numpy on a nullable array detours through
                    # float64, which the integer paths must never do
                    arr = colarr
                    if not pa.types.is_date32(arr.type):
                        arr = arr.cast(pa.date32())
                    arr = arr.cast(pa.int32())
                    if null_mask is not None:
                        arr = arr.fill_null(0)
                    arrays[name] = arr.to_numpy(
                        zero_copy_only=False).astype(np.int32)
                elif field.dtype.kind == "timestamp_ns":
                    import pyarrow as pa

                    arr = colarr.cast(pa.timestamp("ns")).cast(pa.int64())
                    if null_mask is not None:  # arrow-level fill: exact
                        arr = arr.fill_null(0)
                    arrays[name] = arr.to_numpy(
                        zero_copy_only=False).astype(np.int64)
                else:
                    # integers with NULLs: fill on the arrow array so the
                    # conversion stays integral end-to-end (a float64
                    # detour would silently round int64 above 2^53 —
                    # same invariant the text path pins with
                    # test_big_int64_survives_null_column)
                    arr = colarr
                    if null_mask is not None:
                        import pyarrow as pa

                        fill = (False if pa.types.is_boolean(arr.type)
                                else 0)
                        arr = arr.fill_null(fill)
                    arrays[name] = arr.to_numpy(
                        zero_copy_only=False).astype(
                            field.dtype.device_dtype())
        from ..lifecycle import check_cancel

        cap = min(self._capacity, bucket_capacity(max(n, 1)))
        start = 0
        emitted = False
        while start < n or not emitted:
            # chunk-level cancellation: each iteration slices + uploads
            # one batch, the boundary a fired token stops at
            check_cancel()
            end = min(start + cap, n)
            chunk = {k: v[start:end] for k, v in arrays.items()}
            vchunk = (
                {k: v[start:end] for k, v in valids.items()}
                if valids else None
            )
            with phase("h2d", rows=end - start):
                batch = ColumnBatch.from_numpy(sub_schema, chunk, dicts,
                                               capacity=cap, validity=vchunk)
            yield batch
            emitted = True
            start = end
            if start >= n:
                break
