"""ctypes bindings for the native C++ scanner (native/tblscan.cpp).

Returns (num_rows, arrays dict, dictionaries dict) in the engine's physical
representations. ``available()`` gates use; callers fall back to the pandas
reader when the shared library hasn't been built (`make -C
ballista_tpu/native`).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar import Dictionary
from ..datatypes import Schema
from ..errors import IoError

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "native", "libtblscan.so")
_lib = None
_lib_lock = threading.Lock()

_KIND_CODES = {
    "int64": 0,
    "int32": 1,
    "decimal": 2,
    "date32": 3,
    "utf8": 4,
    "float32": 5,
    "float64": 5,
    "boolean": 6,
}


def _try_build() -> bool:
    """Build the shared library on first use if a toolchain is present.

    The .so is not checked in, so a fresh checkout (or the driver's bench
    run) would otherwise silently fall back to the pandas reader and
    report a parse-bound cold path. Cross-PROCESS builds (several
    executors sharing a checkout) serialize on an flock'd lock file so
    one g++ never rewrites the .so another process is dlopen()ing."""
    import shutil
    import subprocess
    import sys

    if shutil.which("make") is None or shutil.which("g++") is None:
        return False
    native_dir = os.path.dirname(_LIB_PATH)
    lockfile = os.path.join(native_dir, ".buildlock")
    try:
        import fcntl

        with open(lockfile, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                if os.path.exists(_LIB_PATH):  # another process built it
                    return True
                print("ballista_tpu: building native scanner "
                      f"({native_dir})...", file=sys.stderr)
                subprocess.run(
                    ["make", "-C", native_dir],
                    capture_output=True, timeout=120, check=True,
                )
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)
    except Exception:  # noqa: BLE001 - build is best-effort
        return False
    return os.path.exists(_LIB_PATH)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH) and not _try_build():
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.tbl_open.restype = ctypes.c_void_p
        lib.tbl_open.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_char, ctypes.c_int,
        ]
        lib.tbl_open_range.restype = ctypes.c_void_p
        lib.tbl_open_range.argtypes = lib.tbl_open.argtypes + [
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.tbl_open_range_mt.restype = ctypes.c_void_p
        lib.tbl_open_range_mt.argtypes = lib.tbl_open_range.argtypes + [
            ctypes.c_int,
        ]
        lib.tbl_error.restype = ctypes.c_char_p
        lib.tbl_error.argtypes = [ctypes.c_void_p]
        lib.tbl_num_rows.restype = ctypes.c_int64
        lib.tbl_num_rows.argtypes = [ctypes.c_void_p]
        for fn, ptr_t in (
            ("tbl_fill_i64", ctypes.POINTER(ctypes.c_int64)),
            ("tbl_fill_i32", ctypes.POINTER(ctypes.c_int32)),
            ("tbl_fill_f32", ctypes.POINTER(ctypes.c_float)),
        ):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_void_p, ctypes.c_int, ptr_t]
        lib.tbl_dict_count.restype = ctypes.c_int64
        lib.tbl_dict_count.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tbl_dict_total_bytes.restype = ctypes.c_int64
        lib.tbl_dict_total_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tbl_fill_dict.restype = ctypes.c_int
        lib.tbl_fill_dict.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.tbl_has_null.restype = ctypes.c_int
        lib.tbl_has_null.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tbl_fill_valid.restype = ctypes.c_int
        lib.tbl_fill_valid.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.tbl_close.restype = None
        lib.tbl_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def scan_file(
    path: str,
    schema: Schema,
    wanted: Sequence[str],
    delimiter: str = "|",
    skip_header: bool = False,
    offset: int = 0,
    max_bytes: int = -1,
    threads: int = 0,
) -> Tuple[int, Dict[str, np.ndarray], Dict[str, np.ndarray],
           Dict[str, np.ndarray]]:
    """Parse one file (or a byte range of it) natively. Returns (num_rows,
    physical arrays, raw dictionary values per utf8 column — sorted, codes
    ordinal, validity bool arrays for columns that saw SQL NULLs — empty
    non-string fields; all-valid columns are absent from the dict).

    Range semantics (offset/max_bytes): rows start at the first line
    boundary after ``offset`` and include every row beginning before
    ``offset + max_bytes``, so adjacent ranges partition the file's rows
    exactly (bounded-RAM streaming / parallel chunk workers).

    ``threads``: parse the range with N parallel workers (sub-ranges
    merged in order, utf8 codes remapped onto a union dictionary).
    0 = auto: BALLISTA_SCAN_THREADS, else the host's CPU count. The
    native side clamps so each worker gets >= 16MB."""
    lib = _load()
    if lib is None:
        raise IoError("native scanner not built")
    ncols = len(schema)
    kinds = (ctypes.c_int32 * ncols)(
        *[_KIND_CODES[f.dtype.kind] for f in schema.fields]
    )
    scales = (ctypes.c_int32 * ncols)(*[f.dtype.scale for f in schema.fields])
    widx = [schema.index_of(n) for n in wanted]
    wantarr = (ctypes.c_int32 * max(len(widx), 1))(*(widx or [0]))

    if threads <= 0:
        threads = int(os.environ.get("BALLISTA_SCAN_THREADS", 0) or
                      (os.cpu_count() or 1))
    h = lib.tbl_open_range_mt(path.encode(), ncols, kinds, scales, wantarr,
                              len(widx), delimiter.encode()[0:1],
                              1 if skip_header else 0, offset, max_bytes,
                              threads)
    try:
        err = lib.tbl_error(h)
        if err:
            raise IoError(f"native scan of {path}: {err.decode()}")
        n = lib.tbl_num_rows(h)
        arrays: Dict[str, np.ndarray] = {}
        dicts: Dict[str, np.ndarray] = {}
        valids: Dict[str, np.ndarray] = {}
        for name in wanted:
            i = schema.index_of(name)
            f = schema.fields[i]
            kind = f.dtype.kind
            if kind in ("int64", "decimal"):
                buf = np.empty(n, dtype=np.int64)
                if n and lib.tbl_fill_i64(
                    h, i, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
                ):
                    raise IoError(f"column {name}: fill failed")
                arrays[name] = buf
            elif kind in ("int32", "date32", "utf8", "boolean"):
                buf = np.empty(n, dtype=np.int32)
                if n and lib.tbl_fill_i32(
                    h, i, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
                ):
                    raise IoError(f"column {name}: fill failed")
                arrays[name] = buf
                if kind == "utf8":
                    dc = lib.tbl_dict_count(h, i)
                    nbytes = lib.tbl_dict_total_bytes(h, i)
                    raw = ctypes.create_string_buffer(max(int(nbytes), 1))
                    offs = np.empty(dc + 1, dtype=np.int64)
                    lib.tbl_fill_dict(
                        h, i, raw,
                        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    )
                    blob = raw.raw[: int(nbytes)]
                    vals = np.empty(dc, dtype=object)
                    for j in range(dc):
                        vals[j] = blob[offs[j]:offs[j + 1]].decode(
                            "utf-8", errors="replace"
                        )
                    dicts[name] = vals
            else:  # float
                buf = np.empty(n, dtype=np.float32)
                if n and lib.tbl_fill_f32(
                    h, i, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                ):
                    raise IoError(f"column {name}: fill failed")
                arrays[name] = buf
            if n and lib.tbl_has_null(h, i):
                vbuf = np.empty(n, dtype=np.uint8)
                if lib.tbl_fill_valid(
                    h, i, vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
                ):
                    raise IoError(f"column {name}: validity fill failed")
                valids[name] = vbuf.astype(np.bool_)
        return int(n), arrays, dicts, valids
    finally:
        lib.tbl_close(h)
