"""In-memory table source (testing + intermediate results)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columnar import Column, ColumnBatch
from ..datatypes import Schema
from ..errors import IoError
from ..logical import TableSource


class MemTableSource(TableSource):
    def __init__(self, schema: Schema, partitions: List[List[ColumnBatch]]):
        self._schema = schema
        self._partitions = partitions

    def estimated_rows(self) -> Optional[int]:
        total = 0
        for part in self._partitions:
            for b in part:
                total += int(b.num_rows)
        return total

    @staticmethod
    def from_pydict(schema: Schema, data: Dict, num_partitions: int = 1,
                    capacity: Optional[int] = None) -> "MemTableSource":
        from ..columnar import Dictionary

        n = len(next(iter(data.values()))) if data else 0
        # encode once, table-wide, so all partitions share interned
        # dictionaries (required for cross-batch concat/compare)
        arrays: Dict[str, np.ndarray] = {}
        dicts: Dict[str, Dictionary] = {}
        for f in schema.fields:
            vals = data[f.name]
            if f.dtype.kind == "utf8":
                d, codes = Dictionary.encode([str(v) for v in vals])
                dicts[f.name] = d
                arrays[f.name] = codes
            elif f.dtype.kind == "decimal":
                from ..columnar import decimal_to_scaled

                arrays[f.name] = decimal_to_scaled(
                    [float(v) for v in vals], f.dtype.scale
                )
            else:
                arrays[f.name] = np.asarray(vals, dtype=f.dtype.device_dtype())
        per = max(1, -(-n // num_partitions))
        parts = []
        for p in range(num_partitions):
            lo, hi = p * per, min((p + 1) * per, n)
            if hi <= lo:
                parts.append([])
                continue
            sliced = {k: v[lo:hi] for k, v in arrays.items()}
            parts.append(
                [ColumnBatch.from_numpy(schema, sliced, dicts, capacity)]
            )
        return MemTableSource(schema, parts)

    def table_schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self._partitions)

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        for batch in self._partitions[partition]:
            if projection is None:
                yield batch
            else:
                sub = self._schema.project(projection)
                cols = [batch.column(n) for n in projection]
                yield batch.with_columns(sub, cols)

    def source_descriptor(self) -> dict:
        return {"kind": "memory", "num_partitions": self.num_partitions()}
