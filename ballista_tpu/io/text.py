"""Delimited text sources: TPC-H ``.tbl`` ('|'-separated) and CSV.

Equivalent of the reference's CSV scan path (reference:
rust/client/src/context.rs:88-108 read_csv; benchmark .tbl registration at
rust/benchmarks/tpch/src/main.rs:128-155). Parsing currently rides pandas'
C reader; the native C++ scanner in ballista_tpu/native replaces it on the
hot path when built.

Partitioning: a directory scans one file per partition (the reference's
testdata layout, rust/scheduler/testdata/*/partition{0,1}.tbl); a single
file is one partition, optionally chunked into multiple batches.

Dictionaries are built lazily per string column over ALL partitions at
first use (sorted + interned), so codes are ordinal and comparable across
every batch of the table.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..columnar import ColumnBatch, Dictionary, DEFAULT_BATCH_CAPACITY
from ..compile import bucket_capacity
from ..datatypes import Schema
from ..errors import IoError
from ..ingest.phases import phase
from ..logical import TableSource

# Files larger than this stream through the native scanner in byte-range
# chunks (bounded RAM at any scale factor) instead of one whole-file
# parse. Streaming pays one extra pre-pass over the file to build
# table-wide utf8 dictionaries, so the threshold is set where whole-file
# RAM actually hurts (~1GB of text -> a few GB resident), keeping
# SF<=1-class files on the single-parse fast path.
STREAM_CHUNK_BYTES = int(
    os.environ.get("BALLISTA_SCAN_CHUNK_BYTES", str(1 << 30))
)


def _list_files(path: str, suffixes=(".tbl", ".csv", ".txt", ".dat")) -> List[str]:
    if os.path.isdir(path):
        out = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(suffixes) or "." not in f
        )
        if not out:
            raise IoError(f"no data files under {path}")
        return out
    if not os.path.exists(path):
        raise IoError(f"no such path: {path}")
    return [path]


class DelimitedSource(TableSource):
    def __init__(
        self,
        path: str,
        schema: Schema,
        delimiter: str,
        has_header: bool = False,
        trailing_delimiter: bool = False,
        batch_capacity: int = DEFAULT_BATCH_CAPACITY,
    ):
        self._path = path
        self._schema = schema
        self._delim = delimiter
        self._header = has_header
        self._trailing = trailing_delimiter
        self._capacity = batch_capacity
        self._files = _list_files(path)
        self._dicts: Dict[str, Dictionary] = {}
        # dictionary-registry entry identity: every source instance
        # over the same table files (re-registrations, self-join
        # re-scans, executor tasks in one process) shares interned
        # dictionaries, so codes are comparable by construction
        from .. import columnar_registry

        self._dict_key_base = columnar_registry.file_entry_key(
            "text", path, self._files)
        # parallel ingest runs partitions of one table (and self-joined
        # re-scans) concurrently: dictionary builds must publish exactly
        # one instance per column (codes stay comparable across batches
        # without union remaps). RLock: _dictionary_for may call
        # _build_native_dicts.
        self._dict_lock = threading.RLock()

    # -- TableSource --------------------------------------------------------

    def table_schema(self) -> Schema:
        return self._schema

    def num_partitions(self) -> int:
        return len(self._files)

    def source_descriptor(self) -> dict:
        return {
            "kind": "tbl" if self._delim == "|" else "csv",
            "path": self._path,
            "delimiter": self._delim,
            "has_header": self._header,
        }

    def estimated_rows(self) -> Optional[int]:
        """file sizes / sampled average line length (no full read)."""
        if not self._files:
            return 0
        try:
            with open(self._files[0], "rb") as fh:
                sample = fh.read(1 << 16)
        except OSError:
            return None
        lines = sample.count(b"\n")
        if lines == 0:
            return None
        avg = len(sample) / lines
        total = sum(os.path.getsize(f) for f in self._files)
        return int(total / avg)

    # -- scanning -----------------------------------------------------------

    def _read_pandas(self, path: str, names: List[str], usecols: List[int]):
        import pandas as pd

        # integer columns parse as nullable Int64: exact above 2^53 AND
        # NA-capable (a bare float64 parse would silently round large
        # ints the moment any row has an empty field)
        dtype = {}
        for i in usecols:
            if i < len(self._schema):
                f = self._schema.fields[i]
                if f.dtype.kind in ("int64", "int32"):
                    dtype[f.name] = "Int64"
        return pd.read_csv(
            path,
            sep=self._delim,
            header=0 if self._header else None,
            names=names,
            usecols=usecols,
            engine="c",
            skipinitialspace=False,
            dtype=dtype or None,
        )

    def _column_names(self) -> List[str]:
        names = list(self._schema.names())
        if self._trailing:
            names = names + ["__trailing__"]
        return names

    def _build_native_dicts(self, colnames: List[str]) -> None:
        """ONE shared native pre-pass building global sorted dictionaries
        for several utf8 columns at once, range-chunked so RAM stays
        bounded on arbitrarily large files. Only dictionary values are
        kept; per-range codes are discarded."""
        from . import native

        with self._dict_lock:
            self._build_native_dicts_locked(colnames)

    def _dict_key(self, colname: str) -> tuple:
        return self._dict_key_base + (colname,)

    def _build_native_dicts_locked(self, colnames: List[str]) -> None:
        from . import native
        from .. import columnar_registry

        need = []
        for n in colnames:
            if n in self._dicts:
                continue
            # a sibling source over the same files already paid for
            # this build: reuse the interned dictionary outright
            d = columnar_registry.REGISTRY.lookup(self._dict_key(n))
            if d is not None:
                self._dicts[n] = d
            else:
                need.append(n)
        if not need:
            return
        uniq: Dict[str, Optional[np.ndarray]] = {n: None for n in need}
        for f in self._files:
            size = os.path.getsize(f)
            off = 0
            while True:
                mb = STREAM_CHUNK_BYTES if size > STREAM_CHUNK_BYTES else -1
                _, _, fd, _ = native.scan_file(
                    f, self._schema, need, self._delim, self._header,
                    offset=off, max_bytes=mb,
                )
                for n in need:
                    u = fd.get(n)
                    if u is None or len(u) == 0:
                        continue
                    uniq[n] = (
                        u if uniq[n] is None
                        else np.unique(  # dict-ok: raw-value dict build
                            np.concatenate([uniq[n], u])))
                if mb < 0:
                    break
                off += STREAM_CHUNK_BYTES
                if off >= size:
                    break
        for n in need:
            self._dicts[n] = columnar_registry.intern(
                self._dict_key(n),
                uniq[n] if uniq[n] is not None else [])

    def _dictionary_for(self, colname: str) -> Dictionary:
        """Global sorted dictionary over all partitions (built once per
        registry entry; concurrent scans serialize on the build, and
        sibling sources over the same files share the interned
        instance)."""
        from .. import columnar_registry

        with self._dict_lock:
            if colname in self._dicts:
                return self._dicts[colname]
            d = columnar_registry.REGISTRY.lookup(self._dict_key(colname))
            if d is not None:
                self._dicts[colname] = d
                return d
            with phase("parse"):
                if self._use_native():
                    self._build_native_dicts_locked([colname])
                    return self._dicts[colname]
                uniq: Optional[np.ndarray] = None
                for f in self._files:
                    idx = self._schema.index_of(colname)
                    df = self._read_pandas(f, self._column_names(), [idx])
                    # empty fields: "" is a utf8 VALUE (native-scanner
                    # convention), not NULL
                    u = np.unique(  # dict-ok: raw-value dict build
                        df[colname].fillna("").astype(str)
                        .to_numpy(dtype=object)
                    )
                    uniq = (u if uniq is None
                            else np.unique(  # dict-ok: raw-value build
                                np.concatenate([uniq, u])))
                d = columnar_registry.intern(
                    self._dict_key(colname),
                    uniq if uniq is not None else [])
                self._dicts[colname] = d
                return d

    def _use_native(self) -> bool:
        # the native scanner does no quote handling; use it only for the
        # unquoted '|' (TPC-H .tbl) format and keep quoted CSV on pandas.
        # Types it has no kind code for (timestamps) also fall back.
        from . import native

        return (native.available() and self._delim == "|"
                and all(f.dtype.kind in native._KIND_CODES
                        for f in self._schema.fields))

    def content_signature(self) -> Optional[tuple]:
        """Re-stat'd file identity + the format knobs that change parsed
        rows — the result-cache invalidation signal for text tables."""
        from .. import columnar_registry

        return columnar_registry.file_entry_key(
            "text", self._path, self._files
        ) + (self._delim, self._header, self._trailing)

    def residency_key(self, partition: int,
                      projection=None) -> Optional[tuple]:
        from ..cache import residency

        # large files stream in byte-range chunks (bounded RAM at any
        # scale): their output would evict the whole device cache for
        # one table, so they bypass residency (key=None -> plain
        # streaming with transient batches)
        try:
            size = os.path.getsize(self._files[partition])
        except OSError:
            size = 0
        if self._use_native() and size > STREAM_CHUNK_BYTES:
            return None
        return residency.scan_key(
            "tbl" if self._delim == "|" else "csv",
            self._files[partition], partition, projection,
            extra=(self._delim, self._header, self._trailing,
                   self._capacity),
        )

    def scan(self, partition: int, projection: Optional[Sequence[str]] = None):
        from ..cache import residency

        yield from residency.serve_or_fill(
            self.residency_key(partition, projection),
            lambda: self._scan_direct(partition, projection),
            outcome_sink=self._note_scan_outcome(partition))

    def _scan_direct(self, partition: int,
                     projection: Optional[Sequence[str]] = None):
        """The uncached parse + H2D path (residency misses land here)."""
        names = projection if projection is not None else self._schema.names()
        sub_schema = self._schema.project(names)
        if self._use_native():
            size = os.path.getsize(self._files[partition])
            if size > STREAM_CHUNK_BYTES:
                yield from self._scan_native_streaming(
                    partition, names, sub_schema)
                return
            with phase("parse", path=self._files[partition]):
                n, arrays, dicts, valids = self._scan_native(partition, names)
        else:
            with phase("parse", path=self._files[partition]):
                n, arrays, dicts, valids = self._scan_pandas(partition, names)
        # chunk into fixed-capacity batches
        yield from self._emit_batches(sub_schema, n, arrays, dicts, valids)

    def _scan_native_streaming(self, partition: int, names, sub_schema):
        """Parse one partition file in byte-range chunks, remapping each
        range's utf8 codes onto the table-wide dictionaries (built by one
        shared pre-pass) and emitting batches incrementally. Peak RAM is
        O(STREAM_CHUNK_BYTES), so SF=10+ scans without materializing the
        file. Reference anchor: partitioned CSV conversion,
        rust/benchmarks/tpch/src/main.rs:196-265."""
        from . import native

        path = self._files[partition]
        size = os.path.getsize(path)
        utf8_names = [n for n in names
                      if self._schema.field(n).dtype.kind == "utf8"]
        with phase("parse", path=path, prepass="dicts"):
            self._build_native_dicts(utf8_names)
        # hoist the fixed-width dictionary views out of the chunk loop:
        # values_str() declines to cache views past its size cap, and
        # re-materializing a big dictionary per 256MB range would churn
        # exactly the memory this path exists to bound
        dict_keys = {n: self._dicts[n].values_str() for n in utf8_names}
        off = 0
        emitted = False
        while off < size:
            with phase("parse", path=path, offset=off):
                n, arrays, fdicts, valids = native.scan_file(
                    path, self._schema, list(names), self._delim,
                    self._header, offset=off, max_bytes=STREAM_CHUNK_BYTES,
                )
                off += STREAM_CHUNK_BYTES
                if n == 0:
                    continue
                dicts: Dict[str, Dictionary] = {}
                for name in utf8_names:
                    d = self._dicts[name]
                    remap = np.searchsorted(  # dict-ok: hoisted encode
                        dict_keys[name],
                        np.asarray(fdicts[name]).astype(str)
                    ).astype(np.int32)
                    arrays[name] = remap[arrays[name]].astype(np.int32)
                    dicts[name] = d
            yield from self._emit_batches(sub_schema, n, arrays, dicts,
                                          valids, force_emit=False)
            emitted = True
        if not emitted:  # empty file: one empty batch keeps contracts
            yield from self._emit_batches(sub_schema, 0, {
                n: np.zeros(0, self._schema.field(n).dtype.device_dtype())
                for n in names
            }, {n: self._dicts[n] for n in utf8_names}, None)

    def _scan_native(self, partition: int, names):
        """Native C++ scan; per-file utf8 dictionaries are remapped onto the
        table-wide union dictionary so codes stay ordinal across
        partitions. Single-file tables adopt the file dictionary directly."""
        from . import native

        n, arrays, fdicts, valids = native.scan_file(
            self._files[partition], self._schema, list(names),
            self._delim, self._header,
        )
        dicts: Dict[str, Dictionary] = {}
        for name in names:
            if self._schema.field(name).dtype.kind != "utf8":
                continue
            fvals = fdicts[name]
            if len(self._files) == 1:
                from .. import columnar_registry

                with self._dict_lock:  # one adopted instance per column
                    if name not in self._dicts:
                        self._dicts[name] = columnar_registry.intern(
                            self._dict_key(name), fvals)
                    d = self._dicts[name]
                # same file scanned twice must yield the same dict (and
                # interning may have returned a superset version); remap
                # when the file's values are not the dictionary verbatim
                if len(d) != len(fvals) or not np.array_equal(
                    d.values_str(), np.asarray(fvals).astype(str)
                ):
                    remap = d.positions_of(fvals)
                    arrays[name] = remap[arrays[name]].astype(np.int32)
            else:
                d = self._dictionary_for(name)
                remap = d.positions_of(fvals)
                arrays[name] = remap[arrays[name]].astype(np.int32)
            dicts[name] = d
        return n, arrays, dicts, valids

    def _scan_pandas(self, partition: int, names):
        idxs = [self._schema.index_of(n) for n in names]
        df = self._read_pandas(self._files[partition], self._column_names(), idxs)
        n = len(df)
        arrays: Dict[str, np.ndarray] = {}
        dicts: Dict[str, Dictionary] = {}
        valids: Dict[str, np.ndarray] = {}
        for name in names:
            field = self._schema.field(name)
            raw = df[name]  # pandas labels used columns by the given names
            # empty non-string fields are SQL NULLs (same convention as
            # the native scanner); "" stays a utf8 VALUE
            na = raw.isna().to_numpy() if field.dtype.kind != "utf8" else None
            if na is not None and na.any():
                valids[name] = ~na
                fill = ("1970-01-01"
                        if field.dtype.kind in ("date32", "timestamp_ns")
                        else 0)
                raw = raw.fillna(fill)
            if field.dtype.kind == "utf8":
                d = self._dictionary_for(name)
                vals = raw.fillna("").astype(str).to_numpy(dtype=object)
                arrays[name] = d.positions_of(vals)
                dicts[name] = d
            elif field.dtype.kind == "decimal":
                from ..columnar import decimal_to_scaled

                arrays[name] = decimal_to_scaled(
                    raw.to_numpy(dtype=np.float64), field.dtype.scale
                )
            elif field.dtype.kind == "date32":
                vals = raw.astype(str).to_numpy(dtype="datetime64[D]")
                arrays[name] = vals.astype(np.int32)
            elif field.dtype.kind == "timestamp_ns":
                vals = raw.astype(str).to_numpy(dtype="datetime64[ns]")
                arrays[name] = vals.astype(np.int64)
            else:
                arrays[name] = raw.to_numpy(dtype=field.dtype.device_dtype())
        return n, arrays, dicts, valids

    def _emit_batches(self, sub_schema, n, arrays, dicts, valids=None,
                      force_emit=True):
        """``force_emit`` guarantees at least one (possibly empty) batch;
        streaming callers emit per range and handle the empty-table case
        themselves."""
        from ..observability.memory import track_host_bytes

        # parse buffers live on host until every chunk uploaded: account
        # them under "batches" for the peak-memory breakdown (the with
        # releases on generator close too — abandoned scans included)
        parse_bytes = sum(int(getattr(a, "nbytes", 0))
                          for a in arrays.values())
        with track_host_bytes("batches", parse_bytes):
            yield from self._emit_batches_inner(sub_schema, n, arrays,
                                                dicts, valids, force_emit)

    def _emit_batches_inner(self, sub_schema, n, arrays, dicts,
                            valids=None, force_emit=True):
        # scan batches enter at canonical ladder capacities so uneven
        # files/partitions reuse a handful of compiled signatures
        from ..lifecycle import check_cancel

        cap = min(self._capacity, bucket_capacity(max(n, 1)))
        start = 0
        emitted = not force_emit
        while start < n or not emitted:
            # chunk-level cancellation: each iteration slices + uploads
            # one batch, the boundary a fired token stops at
            check_cancel()
            end = min(start + cap, n)
            chunk = {k: v[start:end] for k, v in arrays.items()}
            vchunk = (
                {k: v[start:end] for k, v in valids.items()}
                if valids else None
            )
            with phase("h2d", rows=end - start):
                batch = ColumnBatch.from_numpy(sub_schema, chunk, dicts,
                                               capacity=cap, validity=vchunk)
            yield batch
            emitted = True
            start = end
            if start >= n:
                break


class TblSource(DelimitedSource):
    """TPC-H dbgen output: '|' separated, trailing '|', no header."""

    def __init__(self, path: str, schema: Schema,
                 batch_capacity: int = DEFAULT_BATCH_CAPACITY):
        super().__init__(path, schema, "|", has_header=False,
                         trailing_delimiter=True, batch_capacity=batch_capacity)


class CsvSource(DelimitedSource):
    def __init__(self, path: str, schema: Schema, has_header: bool = True,
                 delimiter: str = ",",
                 batch_capacity: int = DEFAULT_BATCH_CAPACITY):
        super().__init__(path, schema, delimiter, has_header=has_header,
                         trailing_delimiter=False, batch_capacity=batch_capacity)
