"""Logical plan optimizer.

The reference delegates optimization to DataFusion's optimizer before
distributed planning (reference: rust/scheduler/src/lib.rs:317-331 calls
``ctx.optimize``); for a TPU engine the two rules that matter most are
implemented natively:

- **filter pushdown**: WHERE conjuncts sink below joins to the side whose
  columns they reference (cuts probe/build sizes before any device work);
- **projection pruning**: table scans read only referenced columns (string
  columns that are never touched skip dictionary building entirely).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Set

from . import expr as ex
from .errors import PlanError
from .logical import (
    Aggregate,
    EmptyRelation,
    Explain,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Repartition,
    Sort,
    TableScan,
)


def optimize(plan: LogicalPlan) -> LogicalPlan:
    plan = push_filters(plan)
    plan = push_semi_joins(plan)
    plan = prune_columns(plan, None)
    return plan


# ---------------------------------------------------------------------------
# Semi/anti-join pushdown
# ---------------------------------------------------------------------------


def _map_children(plan: LogicalPlan, fn) -> LogicalPlan:
    """Rebuild ``plan`` with ``fn`` applied to every LogicalPlan field."""
    updates = {
        f.name: fn(v)
        for f in dataclasses.fields(plan)
        if isinstance(v := getattr(plan, f.name), LogicalPlan)
    }
    return dataclasses.replace(plan, **updates) if updates else plan


def _may_prune(plan: LogicalPlan) -> bool:
    """True when the subtree can shrink cardinality beyond FK matching
    (filters, limits, aggregates, semi/anti joins)."""
    if isinstance(plan, (Filter, Limit, Aggregate)):
        return True
    if isinstance(plan, Join) and plan.how in ("semi", "anti"):
        return True
    return any(_may_prune(c) for c in plan.children())


def push_semi_joins(plan: LogicalPlan) -> LogicalPlan:
    """Sink a semi/anti join below an inner join toward the input that
    produces its key columns.

    ``(A ⋈ B) ⋉ S`` on a key from A rewrites to ``(A ⋉ S) ⋈ B``: the
    key column rides through the inner join unchanged, so membership
    against S filters the same rows — but now BEFORE the join, so the
    join (and everything above it) runs at the pruned size. TPC-H q18's
    IN-subquery semi drops from probing the full 3-table join output to
    pruning orders at the scan (6M-row join shapes -> tens of rows).

    Guard: only applied when the OTHER inner-join input cannot itself
    prune (no filters/limits/aggregates/semi-antis beneath it). When it
    can — q21's exists/not-exists over a heavily filtered join — the
    child join may shrink the key side far below the pre-join table,
    and hoisted (current) placement probes fewer rows. Runs after
    push_filters so filters sit at their final depth.

    The reference gets this class of transform from DataFusion's
    decorrelation/filter-pushdown stack (reference: rust/scheduler/src/
    lib.rs:317-331 delegates to ctx.optimize); here it is native."""
    plan = _map_children(plan, push_semi_joins)
    if not (isinstance(plan, Join) and plan.how in ("semi", "anti")):
        return plan
    child = plan.left
    if not (isinstance(child, Join) and child.how == "inner"):
        return plan
    keys = [l for l, _ in plan.on]
    lnames = set(child.left.schema().names())
    rnames = set(child.right.schema().names())
    # name collisions resolve to the inner join's LEFT output column
    if all(k in lnames for k in keys) and not _may_prune(child.right):
        pushed = Join(child.left, plan.right, plan.on, plan.how,
                      plan.null_aware)
        return dataclasses.replace(child, left=push_semi_joins(pushed))
    if (all(k in rnames and k not in lnames for k in keys)
            and not _may_prune(child.left)):
        pushed = Join(child.right, plan.right, plan.on, plan.how,
                      plan.null_aware)
        return dataclasses.replace(child, right=push_semi_joins(pushed))
    return plan


# ---------------------------------------------------------------------------
# Filter pushdown
# ---------------------------------------------------------------------------


def split_conjuncts(e: ex.Expr) -> List[ex.Expr]:
    if isinstance(e, ex.BinaryExpr) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def conjoin(parts: List[ex.Expr]) -> ex.Expr:
    out = parts[0]
    for p in parts[1:]:
        out = ex.BinaryExpr(out, "and", p)
    return out


def split_disjuncts(e: ex.Expr) -> List[ex.Expr]:
    if isinstance(e, ex.BinaryExpr) and e.op == "or":
        return split_disjuncts(e.left) + split_disjuncts(e.right)
    return [e]


def _structural_key(e: ex.Expr) -> str:
    """Structural identity INCLUDING table qualifiers (display name() drops
    them, which would wrongly equate n1.n_name with n2.n_name)."""
    if isinstance(e, ex.ColumnRef):
        return f"col:{e.qualified()}"
    parts = [type(e).__name__]
    for attr in ("op", "alias_name", "pattern", "negated", "fn", "value",
                 "dtype", "ascending", "is_star"):
        if hasattr(e, attr):
            parts.append(repr(getattr(e, attr)))
    for c in e.children():
        parts.append(_structural_key(c))
    return "(" + " ".join(parts) + ")"


def factor_or(e: ex.Expr) -> List[ex.Expr]:
    """(A and X) or (A and Y) -> [A, (X or Y)] — plus derived IN lists.

    Pulls conjuncts common to every OR branch to the top (matched by
    qualifier-aware structural key). TPC-H q19's OR-of-ANDs hides its join
    condition this way; factoring exposes it to the join-graph extractor.

    Additionally derives IMPLIED per-column predicates: when every branch
    pins the same column to literal(s) (``c = v`` / ``c IN (...)``), the
    OR implies ``c IN (union)`` — a redundant-but-pushable conjunct. q7's
    ``(n1=F AND n2=G) OR (n1=G AND n2=F)`` shares no common conjunct, yet
    implies n1 IN (F,G) AND n2 IN (F,G), which pushdown sinks onto the
    nation scans so the join pyramid above them shrinks by ~12x.
    """
    branches = split_disjuncts(e)
    if len(branches) < 2:
        return [e]
    branch_sets = [
        {_structural_key(c): c for c in split_conjuncts(b)} for b in branches
    ]
    common_names = set(branch_sets[0])
    for s in branch_sets[1:]:
        common_names &= set(s)
    if not common_names:
        return [e] + _derive_in_predicates(branches)
    out: List[ex.Expr] = [branch_sets[0][n] for n in sorted(common_names)]
    residuals = []
    for s in branch_sets:
        rest = [c for n, c in s.items() if n not in common_names]
        if not rest:
            # a branch with no residual makes the OR vacuous beyond the
            # common part
            return out
        residuals.append(conjoin(rest))
    ored = residuals[0]
    for r in residuals[1:]:
        ored = ex.BinaryExpr(ored, "or", r)
    out.append(ored)
    # derive from the residuals only: the factored commons already pin
    # their columns exactly
    return out + _derive_in_predicates(residuals)


def _branch_literal_constraints(branch: ex.Expr):
    """column structural key -> (ColumnRef, literal values) for conjuncts
    of the form ``col = lit`` / ``col IN (lits)``. None values = column
    not literal-pinned in this branch."""
    out = {}
    for c in split_conjuncts(branch):
        col = vals = None
        if isinstance(c, ex.BinaryExpr) and c.op == "=":
            if isinstance(c.left, ex.ColumnRef) and isinstance(
                    c.right, ex.Literal):
                col, vals = c.left, [c.right]
            elif isinstance(c.right, ex.ColumnRef) and isinstance(
                    c.left, ex.Literal):
                col, vals = c.right, [c.left]
        elif (isinstance(c, ex.InList) and not c.negated
              and isinstance(c.expr, ex.ColumnRef)
              and all(isinstance(v, ex.Literal) for v in c.list)):
            col, vals = c.expr, list(c.list)
        if col is not None:
            key = _structural_key(col)
            entry = out.setdefault(key, (col, []))
            entry[1].extend(vals)
    return out


def _derive_in_predicates(branches) -> List[ex.Expr]:
    """Columns literal-pinned in EVERY branch -> implied IN conjuncts."""
    maps = [_branch_literal_constraints(b) for b in branches]
    keys = set(maps[0])
    for m in maps[1:]:
        keys &= set(m)
    derived = []
    for k in sorted(keys):
        col = maps[0][k][0]
        seen, lits = set(), []
        for m in maps:
            for lit in m[k][1]:
                if lit.value not in seen:
                    seen.add(lit.value)
                    lits.append(lit)
        derived.append(ex.InList(col, lits))
    return derived


def push_filters(plan: LogicalPlan) -> LogicalPlan:
    if isinstance(plan, Filter):
        child = push_filters(plan.input)
        conjuncts = split_conjuncts(plan.predicate)
        return _sink(conjuncts, child)
    if isinstance(plan, Projection):
        return Projection(plan.exprs, push_filters(plan.input))
    if isinstance(plan, Aggregate):
        return Aggregate(plan.group_exprs, plan.agg_exprs, push_filters(plan.input))
    if isinstance(plan, Sort):
        return Sort(plan.sort_exprs, push_filters(plan.input))
    if isinstance(plan, Limit):
        return Limit(plan.n, push_filters(plan.input))
    if isinstance(plan, Repartition):
        return Repartition(push_filters(plan.input), plan.num_partitions,
                           plan.hash_exprs)
    if isinstance(plan, Join):
        # dataclasses.replace: never silently drop a Join field
        return dataclasses.replace(plan, left=push_filters(plan.left),
                                   right=push_filters(plan.right))
    if isinstance(plan, Explain):
        return Explain(push_filters(plan.input), plan.verbose, plan.analyze)
    return plan


def _sink(conjuncts: List[ex.Expr], node: LogicalPlan) -> LogicalPlan:
    """Place each conjunct as low as possible over ``node``."""
    if isinstance(node, Join) and node.how == "inner":
        lcols = set(node.left.schema().names())
        rcols = set(node.right.schema().names())
        left_preds, right_preds, keep = [], [], []
        for c in conjuncts:
            refs = set(ex.referenced_columns(c))
            if refs and refs <= lcols:
                left_preds.append(c)
            elif refs and refs <= rcols:
                right_preds.append(c)
            else:
                keep.append(c)
        left = _sink(left_preds, node.left) if left_preds else node.left
        right = _sink(right_preds, node.right) if right_preds else node.right
        out: LogicalPlan = dataclasses.replace(node, left=left, right=right)
        if keep:
            out = Filter(conjoin(keep), out)
        return out
    if isinstance(node, Filter):
        # merge adjacent filters, keep sinking
        return _sink(conjuncts + split_conjuncts(node.predicate), node.input)
    if not conjuncts:
        return node
    return Filter(conjoin(conjuncts), node)


# ---------------------------------------------------------------------------
# Projection pruning
# ---------------------------------------------------------------------------


def _cols_of(exprs) -> Set[str]:
    out: Set[str] = set()
    for e in exprs:
        out.update(ex.referenced_columns(e))
    return out


def prune_columns(plan: LogicalPlan, required: Optional[Set[str]]) -> LogicalPlan:
    """required=None means every column of this node's schema is needed."""
    if isinstance(plan, TableScan):
        if required is None:
            return plan
        schema = plan.source.table_schema()
        names = [n for n in schema.names() if n in required]
        if not names:  # degenerate count(*)-style scan: keep first column
            names = [schema.names()[0]]
        return TableScan(plan.table_name, plan.source, tuple(names))
    if isinstance(plan, Projection):
        need = _cols_of(plan.exprs)
        return Projection(plan.exprs, prune_columns(plan.input, need))
    if isinstance(plan, Filter):
        need = None if required is None else set(required) | _cols_of([plan.predicate])
        return Filter(plan.predicate, prune_columns(plan.input, need))
    if isinstance(plan, Aggregate):
        need = _cols_of(plan.group_exprs) | _cols_of(plan.agg_exprs)
        return Aggregate(plan.group_exprs, plan.agg_exprs,
                         prune_columns(plan.input, need))
    if isinstance(plan, Sort):
        need = None if required is None else set(required) | _cols_of(plan.sort_exprs)
        return Sort(plan.sort_exprs, prune_columns(plan.input, need))
    if isinstance(plan, Limit):
        return Limit(plan.n, prune_columns(plan.input, required))
    if isinstance(plan, Repartition):
        need = required
        if plan.hash_exprs and required is not None:
            need = set(required) | _cols_of(plan.hash_exprs)
        return Repartition(prune_columns(plan.input, need),
                           plan.num_partitions, plan.hash_exprs)
    if isinstance(plan, Join):
        lnames = set(plan.left.schema().names())
        rnames = set(plan.right.schema().names())
        on_l = {l for l, _ in plan.on}
        on_r = {r for _, r in plan.on}
        if required is None:
            lneed, rneed = None, None
        else:
            lneed = (set(required) & lnames) | on_l
            rneed = (set(required) & rnames) | on_r
        return dataclasses.replace(plan,
                                   left=prune_columns(plan.left, lneed),
                                   right=prune_columns(plan.right, rneed))
    if isinstance(plan, Explain):
        return Explain(prune_columns(plan.input, None), plan.verbose,
                       plan.analyze)
    return plan
