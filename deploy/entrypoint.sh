#!/bin/sh
set -e
ROLE="$1"; shift || true
case "$ROLE" in
  scheduler) exec python -m ballista_tpu.distributed.scheduler_main "$@";;
  executor)  exec python -m ballista_tpu.distributed.executor_main "$@";;
  tpch)      exec python -m benchmarks.tpch.main "$@";;
  *) echo "usage: scheduler|executor|tpch [args...]" >&2; exit 2;;
esac
