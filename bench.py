"""ballista-tpu benchmark: TPC-H q1 on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Baseline: the reference engine's only published number — TPC-H q1 at SF~1
in 1956.1 ms on a docker-compose cluster (reference:
rust/benchmarks/tpch/README.md:70-84). SF1 lineitem is 6,001,215 rows, so
the reference throughput is ~3.068M rows/s. ``vs_baseline`` compares our
warm end-to-end q1 rows/sec (device-resident cached table, like a Spark
.cache() workload) against that; cold (re-scan per run, like the
reference does) numbers ride along in the extras.

Usage: python bench.py [--scale 1.0] [--data DIR] [--runs 3] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REF_ROWS_PER_SEC = 6_001_215 / 1.9561  # reference q1 SF1 wall time


def _probe_tpu(attempts: int = 3, timeout_s: float = 150.0,
               retry_wait_s: float = 30.0) -> "tuple[bool, str]":
    """Probe TPU availability; returns (ok, probe_log).

    Backend init can hang if the TPU tunnel is wedged, so each attempt is
    a SUBPROCESS with a timeout (an in-process probe thread would hold
    jax's backend-init lock and deadlock the fallback path). The probe
    runs a real tiny jit, not just ``jax.devices()`` — a listed device
    whose compile path is dead would otherwise hang the benchmark proper.
    Retries a few times over several minutes before giving up; the
    returned log string records why it fell back."""
    import subprocess

    code = (
        "import time, jax\n"
        "t0 = time.time()\n"
        "d = jax.devices()\n"
        "if all('cpu' in str(x).lower() for x in d):\n"
        "    print('CPU_ONLY'); raise SystemExit(0)\n"
        "import jax.numpy as jnp\n"
        "(jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()\n"
        "print(f'TPU_OK {d[0].platform} jit={time.time()-t0:.1f}s')\n"
    )
    log = []
    for i in range(attempts):
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if "TPU_OK" in out.stdout:
                line = out.stdout.strip().splitlines()[-1]
                log.append(f"attempt {i+1}: {line}")
                return True, "; ".join(log)
            if "CPU_ONLY" in out.stdout:
                # deterministic: the device list won't change on retry
                log.append(f"attempt {i+1}: no accelerator device listed")
                return False, "; ".join(log)
            else:
                tail = (out.stderr or out.stdout).strip().splitlines()
                log.append(
                    f"attempt {i+1}: rc={out.returncode} "
                    f"{tail[-1][:120] if tail else 'no output'}"
                )
        except subprocess.TimeoutExpired:
            log.append(
                f"attempt {i+1}: timeout at {time.time()-t0:.0f}s "
                "(backend init or first compile hung — tunnel wedged?)"
            )
        except Exception as e:  # noqa: BLE001 - record and keep trying
            log.append(f"attempt {i+1}: {type(e).__name__}: {e}")
        if i < attempts - 1:
            time.sleep(retry_wait_s)
    return False, "; ".join(log) or f"probe skipped (attempts={attempts})"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--data", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_data"))
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--cpu", action="store_true", help="force CPU")
    ap.add_argument("--probe-attempts", type=int,
                    default=int(os.environ.get("BALLISTA_PROBE_ATTEMPTS", 3)))
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get("BALLISTA_PROBE_TIMEOUT", 150)))
    args = ap.parse_args()

    if args.cpu:
        force_cpu, probe_log = True, "forced by --cpu"
    else:
        ok, probe_log = _probe_tpu(args.probe_attempts, args.probe_timeout)
        force_cpu = not ok
        print(f"# tpu probe: {probe_log}", file=sys.stderr)
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import TPCH_SCHEMAS, TPCH_PKS
    from ballista_tpu.client import BallistaContext

    # -- data ---------------------------------------------------------------
    data_dir = os.path.join(args.data, f"sf{args.scale:g}")
    marker = os.path.join(data_dir, ".complete")
    if not os.path.exists(marker):
        t0 = time.time()
        datagen.generate(data_dir, scale=args.scale, num_parts=1)
        open(marker, "w").write("ok")
        print(f"# generated sf{args.scale:g} in {time.time()-t0:.1f}s",
              file=sys.stderr)

    sql = open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")).read()

    def run_once(ctx):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        return time.time() - t0, out

    # -- cold: re-scan per run (what the reference benchmark does) ----------
    ctx_cold = BallistaContext.standalone()
    ctx_cold.register_tbl("lineitem", os.path.join(data_dir, "lineitem"),
                          TPCH_SCHEMAS["lineitem"],
                          primary_key=TPCH_PKS["lineitem"])
    cold_warmup, out = run_once(ctx_cold)  # includes compile
    cold_s, _ = run_once(ctx_cold)

    # -- warm: device-resident cached table + prepared (pre-compiled) query -
    from benchmarks.tpch.schema_def import register_tpch

    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl", cached=True)
    df = ctx.sql(sql)
    df.collect()  # load + compile once

    def timed(frame):
        t0 = time.time()
        frame.collect()
        return time.time() - t0

    warm = min(timed(df) for _ in range(args.runs))

    # -- q5 (join + shuffle-shaped query; BASELINE metric is q1+q5) ---------
    q5_sql = open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "tpch", "queries", "q5.sql")).read()
    q5_warm = None
    try:
        df5 = ctx.sql(q5_sql)
        df5.collect()  # load + compile
        q5_warm = min(timed(df5) for _ in range(max(args.runs - 1, 1)))
    except Exception as e:  # noqa: BLE001 - q1 metric still reports
        print(f"# q5 failed: {e}", file=sys.stderr)

    total_rows = _count_lineitem_rows(data_dir)
    value = total_rows / warm
    result = {
        "metric": "tpch_q1_rows_per_sec_warm",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(value / REF_ROWS_PER_SEC, 3),
        "platform": platform,
        "tpu_probe": probe_log,
        "scale": args.scale,
        "lineitem_rows": total_rows,
        "warm_seconds": round(warm, 4),
        "cold_seconds": round(cold_s, 4),
        "cold_rows_per_sec": round(total_rows / cold_s, 1),
        "cold_vs_baseline": round(total_rows / cold_s / REF_ROWS_PER_SEC, 3),
        "first_run_seconds": round(cold_warmup, 4),
        "q1_groups": int(len(out)),
    }
    if q5_warm is not None:
        result["q5_warm_seconds"] = round(q5_warm, 4)
        result["q5_rows_per_sec"] = round(total_rows / q5_warm, 1)

    # -- Pallas A/B on real accelerators ------------------------------------
    # q1's dense aggregation has a fused Pallas kernel (kernels/
    # pallas_agg.py); on a chip, re-run q1 with it enabled so the
    # XLA-vs-Pallas delta is recorded automatically. A FRESH context is
    # required: operator jit caches bake the path chosen at trace time.
    if platform != "cpu":
        try:
            os.environ["BALLISTA_PALLAS"] = "on"
            ctx_p = BallistaContext.standalone()
            register_tpch(ctx_p, data_dir, "tbl", cached=True)
            dfp = ctx_p.sql(sql)
            dfp.collect()  # load + compile with the Pallas path
            q1_pallas = min(timed(dfp) for _ in range(args.runs))
            result["q1_pallas_warm_seconds"] = round(q1_pallas, 4)
            result["q1_pallas_rows_per_sec"] = round(total_rows / q1_pallas, 1)
        except Exception as e:  # noqa: BLE001 - A/B is best-effort
            print(f"# pallas q1 failed: {e}", file=sys.stderr)
            result["q1_pallas_error"] = str(e)[:200]
        finally:
            os.environ.pop("BALLISTA_PALLAS", None)
    print(json.dumps(result))


def _count_lineitem_rows(data_dir: str) -> int:
    total = 0
    d = os.path.join(data_dir, "lineitem")
    for f in os.listdir(d):
        if f.endswith(".tbl"):
            with open(os.path.join(d, f), "rb") as fh:
                total += sum(buf.count(b"\n") for buf in
                             iter(lambda: fh.read(1 << 20), b""))
    return total


if __name__ == "__main__":
    main()
