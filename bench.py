"""ballista-tpu benchmark: TPC-H q1 on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Baseline: the reference engine's only published number — TPC-H q1 at SF~1
in 1956.1 ms on a docker-compose cluster (reference:
rust/benchmarks/tpch/README.md:70-84). SF1 lineitem is 6,001,215 rows, so
the reference throughput is ~3.068M rows/s. ``vs_baseline`` compares our
warm end-to-end q1 rows/sec (device-resident cached table, like a Spark
.cache() workload) against that; cold (re-scan per run, like the
reference does) numbers ride along in the extras.

Usage: python bench.py [--scale 1.0] [--data DIR] [--runs 3] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REF_ROWS_PER_SEC = 6_001_215 / 1.9561  # reference q1 SF1 wall time

# Peak dense-compute rates for the MFU estimate, by device_kind substring.
# q1 is integer/VPU-bound, so MFU vs the MXU bf16 peak is structurally
# tiny — the number is a utilization *floor* recorded for trend-tracking,
# with the assumed peak alongside so it can be reinterpreted.
_PEAK_FLOPS = [
    ("v5 lite", 197e12),  # TPU v5e: 197 TFLOP/s bf16
    ("v5e", 197e12),
    ("v4", 275e12),
    ("cpu", 1e11),  # nominal single-core AVX-512 figure for this box
]


def _peak_flops(device_kind: str) -> float:
    dk = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in dk:
            return peak
    return 1e11


def cold_phase_split(run_fn):
    """Run ``run_fn()`` and attribute its wall time across the ingest
    phases (parse / H2D / execute-and-compile remainder) using the
    process-wide accumulators in ballista_tpu.ingest.

    ``parse_seconds``/``h2d_seconds`` are THREAD time: with the ingest
    pipeline ON they overlap each other and device compute, so they can
    legitimately sum past wall time (that overlap IS the win);
    ``execute_seconds`` is the non-ingest remainder of the wall clock,
    clamped at 0. With the pipeline gated off (serial scans) the three
    fields sum to the wall time exactly — the tier-1 smoke test pins
    that identity. Returns ``(run_fn result, phase dict)``."""
    from ballista_tpu.ingest import phase_totals

    p0 = phase_totals()
    t0 = time.time()
    ret = run_fn()
    wall = time.time() - t0
    p1 = phase_totals()
    parse = p1["parse"] - p0["parse"]
    h2d = p1["h2d"] - p0["h2d"]
    return ret, {
        "wall_seconds": round(wall, 4),
        "parse_seconds": round(parse, 4),
        "h2d_seconds": round(h2d, 4),
        "execute_seconds": round(max(wall - parse - h2d, 0.0), 4),
    }


def profiled_query(ctx, name: str, sql: str, runs: int, result: dict,
                   timed, lane_prefix: str,
                   progress_field: str = "") -> None:
    """Shared TPC-H query measurement: the FIRST run executes under a
    profiler window so the named wall-time lanes land in the JSON line
    (`{lane_prefix}device_blocked_seconds` etc. — q5 keeps the
    unprefixed legacy names, q3/q18 prefix theirs), then a warm
    minimum. Lanes land only for a SUCCESSFUL first run: a query that
    died mid-run must not gate truncated (artificially good) lane
    values against a baseline in dev/check_bench_regress.py."""
    prof = None
    try:
        from ballista_tpu.observability.profiler import Profiler

        prof = Profiler(label=f"{name}-first")
        prof.start()
    except Exception as e:  # noqa: BLE001 - lanes are best-effort
        print(f"# {name} lane profiler unavailable: {e}", file=sys.stderr)
        prof = None
    try:
        df = ctx.sql(sql)
        if progress_field:
            # live progress plane: count the on_progress callbacks the
            # first (cold) run delivers — pins that the sampler stays
            # alive on the bench workload (gated as higher-is-better by
            # dev/check_bench_regress.py)
            samples = []
            t0 = time.time()
            df.collect(on_progress=samples.append)
            first = time.time() - t0
            result[progress_field] = len(samples)
        else:
            first = timed(df)  # load + compile
        if prof is not None:
            try:
                from ballista_tpu.observability.export import compute_lanes

                session, prof = prof.stop(), None
                lane_info = compute_lanes(session)
                lanes = lane_info["lanes"]
                result[f"{lane_prefix}device_blocked_seconds"] = \
                    lanes["device_blocked"]
                result[f"{lane_prefix}host_dictionary_seconds"] = \
                    lanes["host_dictionary"]
                result[f"{lane_prefix}compile_trace_lower_seconds"] = \
                    lanes["compile_trace_lower"]
                result[f"{lane_prefix}attributed_fraction"] = \
                    lane_info["attributed_fraction"]
            except Exception as e:  # noqa: BLE001
                print(f"# {name} lane extraction failed: {e}",
                      file=sys.stderr)
        warm = min(timed(df) for _ in range(max(runs - 1, 1)))
        result[f"{name}_first_seconds"] = round(first, 4)
        result[f"{name}_warm_seconds"] = round(warm, 4)
    except Exception as e:  # noqa: BLE001 - q1 metric still reports
        print(f"# {name} failed: {e}", file=sys.stderr)
        if prof is not None:
            try:
                prof.stop()
            except Exception:  # noqa: BLE001 - already stopped
                pass


def instrument_q1(data_dir: str, runs: int):
    """Per-stage decomposition of q1 + an AOT-compiled kernel measurement.

    Stages: parse (native .tbl scan -> numpy), h2d (host->device
    transfer), kernel (the engine's OWN partial-aggregation program —
    HashAggregateExec._get_grouped_fn — over the device-resident table,
    AOT-compiled and XLA cost-analyzed for flops/bytes so an estimated
    MFU rides along on any platform). VERDICT r2 asked for exactly this
    so one on-chip run yields a full decomposition vs BASELINE.md.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ballista_tpu import col, count, sum_
    from ballista_tpu.columnar import ColumnBatch, round_capacity
    from ballista_tpu.io import TblSource
    from ballista_tpu.physical.aggregate import HashAggregateExec
    from ballista_tpu.physical.base import PhysicalPlan
    from benchmarks.tpch.schema_def import TPCH_SCHEMAS

    out: dict = {}
    schema = TPCH_SCHEMAS["lineitem"]
    src = TblSource(os.path.join(data_dir, "lineitem"), schema)
    names = ["l_returnflag", "l_linestatus", "l_quantity",
             "l_extendedprice", "l_discount", "l_tax", "l_shipdate"]
    sub = schema.project(names)

    # -- stage: parse (file -> numpy physical arrays, native C++ scanner) --
    t0 = time.time()
    n_total, arrays, dicts, valids = 0, None, {}, {}
    for p in range(src.num_partitions()):
        if src._use_native():
            n, arrs, ds, vs = src._scan_native(p, names)
        else:
            n, arrs, ds, vs = src._scan_pandas(p, names)
        if arrays is None:
            arrays, dicts, valids = arrs, ds, dict(vs or {})
            n_total = n
        else:  # multi-partition: host concat (parse-stage cost)
            # validity masks default to all-true when a chunk lacks one
            for k in set(valids) | set(vs or {}):
                left = valids.get(k, np.ones(n_total, dtype=bool))
                right = (vs or {}).get(k, np.ones(n, dtype=bool))
                valids[k] = np.concatenate([left, right])
            arrays = {k: np.concatenate([arrays[k], arrs[k]])
                      for k in arrays}
            n_total += n
    parse_s = time.time() - t0
    in_bytes = sum(a.nbytes for a in arrays.values())
    out["parse_s"] = round(parse_s, 4)
    out["parse_mb_per_s"] = round(in_bytes / parse_s / 1e6, 1)

    # -- stage: h2d (host numpy -> device buffers) --------------------------
    t0 = time.time()
    cap = round_capacity(n_total)
    batch = ColumnBatch.from_numpy(sub, arrays, dicts, capacity=cap,
                                   validity=valids or None)
    jax.block_until_ready([c.values for c in batch.columns])
    h2d_s = time.time() - t0
    out["h2d_s"] = round(h2d_s, 4)
    out["h2d_gb_per_s"] = round(in_bytes / h2d_s / 1e9, 2)
    out["rows"] = n_total

    # -- stage: kernel (the engine's q1 partial aggregation, AOT) ----------
    class _Stub(PhysicalPlan):
        def output_schema(self):
            return sub

        def with_new_children(self, children):
            return self

    from ballista_tpu import lit
    from ballista_tpu import expr as ex

    cutoff = ex.parse_date_literal("1998-09-02")
    pred = col("l_shipdate") <= ex.Literal(cutoff, sub.field("l_shipdate").dtype)
    disc_price = col("l_extendedprice") * (lit(1) - col("l_discount"))
    charge = disc_price * (lit(1) + col("l_tax"))
    aggs = [
        sum_(col("l_quantity")).alias("sum_qty"),
        sum_(col("l_extendedprice")).alias("sum_base_price"),
        sum_(disc_price).alias("sum_disc_price"),
        sum_(charge).alias("sum_charge"),
        sum_(col("l_discount")).alias("sum_disc"),
        count().alias("count_order"),
    ]
    partial = HashAggregateExec(
        "partial", [col("l_returnflag"), col("l_linestatus")], aggs,
        _Stub(), group_capacity=8,
    )
    from ballista_tpu.kernels.expr_eval import Evaluator

    ev = Evaluator(sub)

    def q1_program(b):
        live = jnp.logical_and(b.selection, ev.evaluate_predicate(pred, b))
        return partial._get_grouped_fn(8, cap)(b.with_selection(live))

    jitted = jax.jit(q1_program)
    t0 = time.time()
    lowered = jitted.lower(batch)
    compiled = lowered.compile()
    out["kernel_aot_compile_s"] = round(time.time() - t0, 3)
    flops = bytes_accessed = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        bytes_accessed = float(ca.get("bytes accessed", 0.0))
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        pass

    def run_kernel():
        t = time.time()
        jax.block_until_ready(compiled(batch))
        return time.time() - t

    run_kernel()  # warm any lazy allocs
    kernel_s = min(run_kernel() for _ in range(max(runs, 2)))
    out["kernel_s"] = round(kernel_s, 4)
    out["kernel_rows_per_s"] = round(n_total / kernel_s, 1)
    dev = jax.devices()[0]
    peak = _peak_flops(getattr(dev, "device_kind", dev.platform))
    if flops:
        out["kernel_flops"] = flops
        out["kernel_bytes_accessed"] = bytes_accessed
        out["kernel_flops_per_s"] = round(flops / kernel_s, 1)
        out["est_mfu"] = round(flops / kernel_s / peak, 6)
        out["peak_flops_assumed"] = peak
        if bytes_accessed:
            out["kernel_gb_per_s"] = round(
                bytes_accessed / kernel_s / 1e9, 2)
    return out


def _probe_tpu(attempts: int = 3, timeout_s: float = 150.0,
               retry_wait_s: float = 30.0) -> "tuple[bool, str]":
    """Probe TPU availability; returns (ok, probe_log).

    Backend init can hang if the TPU tunnel is wedged, so each attempt is
    a SUBPROCESS with a timeout (an in-process probe thread would hold
    jax's backend-init lock and deadlock the fallback path). The probe
    runs a real tiny jit, not just ``jax.devices()`` — a listed device
    whose compile path is dead would otherwise hang the benchmark proper.
    Retries a few times over several minutes before giving up; the
    returned log string records why it fell back."""
    import subprocess

    code = (
        "import time, jax\n"
        "t0 = time.time()\n"
        "d = jax.devices()\n"
        "if all('cpu' in str(x).lower() for x in d):\n"
        "    print('CPU_ONLY'); raise SystemExit(0)\n"
        "import jax.numpy as jnp\n"
        "(jnp.ones((256, 256)) @ jnp.ones((256, 256))).block_until_ready()\n"
        "print(f'TPU_OK {d[0].platform} jit={time.time()-t0:.1f}s')\n"
    )
    log = []
    for i in range(attempts):
        t0 = time.time()
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if "TPU_OK" in out.stdout:
                line = out.stdout.strip().splitlines()[-1]
                log.append(f"attempt {i+1}: {line}")
                return True, "; ".join(log)
            if "CPU_ONLY" in out.stdout:
                # deterministic: the device list won't change on retry
                log.append(f"attempt {i+1}: no accelerator device listed")
                return False, "; ".join(log)
            else:
                tail = (out.stderr or out.stdout).strip().splitlines()
                log.append(
                    f"attempt {i+1}: rc={out.returncode} "
                    f"{tail[-1][:120] if tail else 'no output'}"
                )
        except subprocess.TimeoutExpired:
            log.append(
                f"attempt {i+1}: timeout at {time.time()-t0:.0f}s "
                "(backend init or first compile hung — tunnel wedged?)"
            )
        except Exception as e:  # noqa: BLE001 - record and keep trying
            log.append(f"attempt {i+1}: {type(e).__name__}: {e}")
        if i < attempts - 1:
            time.sleep(retry_wait_s)
    return False, "; ".join(log) or f"probe skipped (attempts={attempts})"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--data", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_data"))
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--cpu", action="store_true", help="force CPU")
    ap.add_argument("--probe-attempts", type=int,
                    default=int(os.environ.get("BALLISTA_PROBE_ATTEMPTS", 3)))
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get("BALLISTA_PROBE_TIMEOUT", 150)))
    ap.add_argument("--inner", action="store_true",
                    help="run the measured workload in THIS process "
                         "(no probe, no watchdog) — used by the parent")
    ap.add_argument("--inner-timeout", type=float,
                    default=float(os.environ.get("BALLISTA_INNER_TIMEOUT",
                                                 1200)))
    args = ap.parse_args()

    if args.inner:
        _run_bench(args)
        return

    # Parent: probe, then run the workload in a watchdogged SUBPROCESS.
    # The probe catches a tunnel that is dead BEFORE the run; the
    # watchdog catches one that dies MID-run (observed: backend calls
    # block forever holding jax's internal locks — unkillable from
    # inside the process). On timeout the child is killed and the whole
    # benchmark reruns on CPU, so the driver's round-end invocation
    # always emits a JSON line.
    if args.cpu:
        force_cpu, probe_log = True, "forced by --cpu"
    else:
        ok, probe_log = _probe_tpu(args.probe_attempts, args.probe_timeout)
        force_cpu = not ok
        print(f"# tpu probe: {probe_log}", file=sys.stderr)

    import subprocess

    def _scan_json(text: str):
        for line in reversed((text or "").strip().splitlines()):
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    pass
        return None

    def attempt(cpu: bool, timeout_s: float):
        cmd = [sys.executable, "-u", os.path.abspath(__file__), "--inner",
               "--scale", str(args.scale), "--data", args.data,
               "--runs", str(args.runs)]
        if cpu:
            cmd.append("--cpu")
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=timeout_s)
        except subprocess.TimeoutExpired as e:
            def _txt(b):
                return (b or b"").decode(errors="replace") \
                    if isinstance(b, bytes) else (b or "")
            sys.stderr.write(_txt(e.stderr)[-4000:])
            # the child may have printed its JSON and then hung in
            # teardown — salvage a completed measurement if present
            got = _scan_json(_txt(e.stdout))
            if got is not None:
                got["watchdog_note"] = (
                    f"child hung after completing (killed at "
                    f"{timeout_s:.0f}s); result salvaged from its stdout")
                return got, None
            return None, f"timeout at {timeout_s:.0f}s"
        sys.stderr.write(out.stderr[-4000:])
        got = _scan_json(out.stdout)
        if got is not None:
            return got, None
        return None, f"rc={out.returncode}, no JSON line"

    # one timeout floor for ALL attempts: a CPU SF1 run (cold+warm q1,
    # q5, instrumentation, possibly datagen) must fit it regardless of
    # which path selected CPU
    budget = max(args.inner_timeout, 1800)
    result, err = attempt(force_cpu, budget)
    watchdog_log = []
    if result is None and not force_cpu:
        watchdog_log.append(f"tpu run failed ({err}); retrying on cpu")
        print(f"# watchdog: {watchdog_log[-1]}", file=sys.stderr)
        result, err = attempt(True, budget)
    if result is None:
        # last resort: still one well-formed JSON line for the driver
        result = {"metric": "tpch_q1_rows_per_sec_warm", "value": 0,
                  "unit": "rows/s", "vs_baseline": 0.0,
                  "platform": "none", "error": err}
    result["tpu_probe"] = probe_log
    if watchdog_log:
        result["watchdog"] = "; ".join(watchdog_log)
    print(json.dumps(result))


def _run_bench(args) -> None:
    force_cpu = args.cpu
    if force_cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    # overlap scan-chain XLA compiles with parse/H2D on the cold path
    # (compile/prewarm.py; an explicit user setting wins)
    os.environ.setdefault("BALLISTA_PREWARM", "1")
    # persist fused-stage programs next to the bench data: the first
    # round exports them, every later fresh-process round loads instead
    # of re-tracing (compile/aot.py; an explicit user setting wins)
    os.environ.setdefault(
        "BALLISTA_FUSION_AOT_DIR",
        os.path.join(os.path.abspath(args.data), "aot_cache"))
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import TPCH_SCHEMAS, TPCH_PKS
    from ballista_tpu.client import BallistaContext

    # -- data ---------------------------------------------------------------
    data_dir = os.path.join(args.data, f"sf{args.scale:g}")
    marker = os.path.join(data_dir, ".complete")
    want = f"v{datagen.DATAGEN_VERSION}"
    have = open(marker).read().strip() if os.path.exists(marker) else None
    if have != want:
        if have is not None:
            print(f"# datagen version changed ({have} -> {want}): "
                  f"regenerating sf{args.scale:g}", file=sys.stderr)
        t0 = time.time()
        datagen.generate(data_dir, scale=args.scale, num_parts=1)
        open(marker, "w").write(want)
        print(f"# generated sf{args.scale:g} in {time.time()-t0:.1f}s",
              file=sys.stderr)

    sql = open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "tpch", "queries", "q1.sql")).read()

    def run_once(ctx):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        return time.time() - t0, out

    # Tunnel resilience: the parent watchdog salvages the LAST JSON line
    # from our stdout if we hang/die mid-run, so a partial snapshot is
    # flushed after every phase — a wedged TPU tunnel then costs the
    # remaining phases, not the whole round's measurement.
    result = {
        "metric": "tpch_q1_rows_per_sec_warm", "value": 0,
        "unit": "rows/s", "vs_baseline": 0.0, "platform": platform,
        "scale": args.scale, "partial": "init",
    }

    from ballista_tpu.compile import compile_stats

    def record_compiles():
        # cold-path trajectory: process-wide XLA compile work and how
        # much of it the persistent disk cache absorbed (ISSUE 3 asks
        # for these in every bench line from this PR on)
        st = compile_stats()
        result["compile_count"] = int(st["backend_compiles"])
        result["compile_seconds"] = round(float(st["compile_seconds"]), 3)
        result["persistent_cache_hit"] = int(st["persistent_cache_hits"])
        # jit_programs = distinct governed entries minted this process
        # (ISSUE 6 tracks the whole-stage-fusion trajectory on this
        # field); per-specialization compile/retrieval events ride
        # alongside as compile_count / persistent_cache_hit, and
        # aot_loads counts whole programs deserialized WITHOUT tracing
        # (jit_trace_seconds pins the GIL-bound trace/lower mass those
        # loads eliminate)
        result["jit_programs"] = int(st.get("entries_built", 0))
        result["jit_trace_seconds"] = round(float(
            st.get("trace_seconds", 0.0)), 3)
        result["aot_loads"] = int(st.get("aot_loads", 0))
        # memory trajectory (ISSUE 5): BENCH_*.json records peak RSS
        # and peak device bytes alongside latency from this PR on
        from ballista_tpu.observability import memory as obs_memory

        result["peak_rss_mb"] = round(obs_memory.peak_rss_bytes() / 1e6, 1)
        result["peak_device_bytes"] = int(
            obs_memory.peak_device_bytes(refresh=True))
        result["peak_host_tracked_bytes"] = int(
            obs_memory.peak_host_bytes())
        # shuffle memory governor (ISSUE 12): in-flight peak + spill
        # volume per JSON line; the fixed-budget q5 phase below resets
        # and re-reads them for its gated fields
        from ballista_tpu.distributed import spill as _spill

        gov = _spill.governor().stats()
        result["spill_bytes"] = int(gov["spilled_bytes_total"])
        result["shuffle_peak_inflight_mb"] = round(
            gov["peak_inflight_bytes"] / 1e6, 2)
        # warm-path serving caches (docs/caching.md): scans served
        # device-resident, collects served from the result cache, and
        # governed calls that donated their input buffers — per JSON
        # line so dev/check_bench_regress.py can gate aliveness
        from ballista_tpu.cache import cache_counters

        cc = cache_counters()
        result["table_cache_hits"] = int(cc["table_cache_hits"])
        result["result_cache_hits"] = int(cc["result_cache_hits"])
        result["donated_buffers"] = int(cc["donated_buffers"])

    def snapshot(phase: str):
        result["partial"] = phase
        record_compiles()
        print(json.dumps(result), flush=True)

    # -- cold: re-scan per run (what the reference benchmark does) ----------
    ctx_cold = BallistaContext.standalone()
    ctx_cold.register_tbl("lineitem", os.path.join(data_dir, "lineitem"),
                          TPCH_SCHEMAS["lineitem"],
                          primary_key=TPCH_PKS["lineitem"])
    # first run with parse/H2D/execute attribution (cold-path trajectory:
    # joins compile_count below; ISSUE 4 asks for these per JSON line)
    (cold_warmup, out), cold_phases = cold_phase_split(
        lambda: run_once(ctx_cold))
    result.update({
        "parse_seconds": cold_phases["parse_seconds"],
        "h2d_seconds": cold_phases["h2d_seconds"],
        "execute_seconds": cold_phases["execute_seconds"],
    })
    cold_s, _ = run_once(ctx_cold)
    total_rows = _count_lineitem_rows(data_dir)
    result.update({
        "lineitem_rows": total_rows,
        "cold_seconds": round(cold_s, 4),
        "cold_rows_per_sec": round(total_rows / cold_s, 1),
        "cold_vs_baseline": round(total_rows / cold_s / REF_ROWS_PER_SEC, 3),
        "first_run_seconds": round(cold_warmup, 4),
        "q1_groups": int(len(out)),
    })
    snapshot("cold_done")

    # -- warm: device-resident cached table + prepared (pre-compiled) query -
    from benchmarks.tpch.schema_def import register_tpch

    # On an accelerator, fewer/bigger batches amortize per-dispatch and
    # per-sync round-trips (decisive when the chip is remote); CPU keeps
    # the default where padding waste costs more than dispatches.
    reg_kw = {"batch_capacity": 1 << 23} if platform != "cpu" else {}
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl", cached=True, **reg_kw)
    df = ctx.sql(sql)
    df.collect()  # load + compile once

    def timed(frame):
        t0 = time.time()
        frame.collect()
        return time.time() - t0

    warm = min(timed(df) for _ in range(args.runs))
    value = total_rows / warm
    result.update({
        "value": round(value, 1),
        "vs_baseline": round(value / REF_ROWS_PER_SEC, 3),
        "warm_seconds": round(warm, 4),
    })
    snapshot("warm_done")

    # -- q5 (join + shuffle-shaped query; BASELINE metric is q1+q5) ---------
    # The first q5 run executes under a profiler window so the named
    # wall-time lanes land in the JSON line: ROADMAP targets cite them
    # (item 2 wants host_dictionary < 0.5s) and
    # dev/check_bench_regress.py gates them between rounds. q5 keeps
    # the unprefixed legacy lane field names.
    qdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "tpch", "queries")
    profiled_query(ctx, "q5", open(os.path.join(qdir, "q5.sql")).read(),
                   args.runs, result, timed, lane_prefix="",
                   progress_field="progress_samples")
    if "q5_warm_seconds" in result:
        result["q5_rows_per_sec"] = round(
            total_rows / result["q5_warm_seconds"], 1)
    snapshot("q5_done")

    # -- q3 / q18 (ROADMAP item 5: grow bench coverage beyond
    # q1/q5/q12/q16 so the caches and AQE rules see diverse plan shapes
    # — q3 is join-heavy with a top-k sort, q18 a high-cardinality
    # aggregation feeding a join). Same lane/phase fields as q5,
    # prefixed per query; dev/check_bench_regress.py gates them.
    for qname in ("q3", "q18"):
        profiled_query(ctx, qname,
                       open(os.path.join(qdir, f"{qname}.sql")).read(),
                       args.runs, result, timed, lane_prefix=f"{qname}_")
    snapshot("q3_q18_done")

    # -- q16 (COUNT(DISTINCT) query; the fused distinct-count kernel's
    # pinned workload — ISSUE 6 targets >=2x its r05 warm time). It is
    # also the bench's string-heavy JOIN query (partsupp joins part
    # under brand/type string predicates, groups by three string
    # columns, and anti-joins a comment LIKE subquery), so per ISSUE 11
    # / ROADMAP item 1 its first run emits the q16_-prefixed profiler
    # lane fields — q16_host_dictionary_seconds pins the lane the
    # dictionary registry exists to kill, gated between rounds by
    # dev/check_bench_regress.py.
    profiled_query(ctx, "q16", open(os.path.join(qdir, "q16.sql")).read(),
                   args.runs, result, timed, lane_prefix="q16_")
    snapshot("q16_done")

    # -- warm-path serving caches (docs/caching.md): repeated-query
    # warm phase (table-cache repeat scan + result-cache repeat
    # collect, byte-identity checked) and a fixed-budget residency
    # phase (budget sized below two tables, so the second fill EVICTS
    # the first and a re-scan degrades to re-ingest — never fails).
    # Gated by dev/check_bench_regress.py: the identity/ok fields are
    # aliveness gates, the warm latencies ride the ratio gates.
    try:
        _cache_phase(data_dir, result, sql, qdir)
    except Exception as e:  # noqa: BLE001 - phase is best-effort
        print(f"# cache phase failed: {e}", file=sys.stderr)
        result["cache_phase_error"] = str(e)[:200]
    snapshot("cache_done")

    # -- fixed-budget spill q5 (ISSUE 12: memory-governed streaming
    # shuffle). q5 on an in-process LocalCluster with remote fetches
    # forced and a small BALLISTA_SHUFFLE_MEM_BUDGET: every shuffle
    # read streams through the governor and past-watermark chunks
    # spill to disk. Gated by dev/check_bench_regress.py — spill_bytes
    # must stay nonzero (the lane engaged) and the in-flight peak must
    # respect the budget (absolute budget_check).
    try:
        _spill_q5(data_dir, result, qdir)
    except Exception as e:  # noqa: BLE001 - phase is best-effort
        print(f"# spill q5 failed: {e}", file=sys.stderr)
        result["spill_q5_error"] = str(e)[:200]
    snapshot("spill_q5_done")

    # -- per-stage decomposition + AOT kernel + MFU estimate ----------------
    try:
        result["stages"] = instrument_q1(data_dir, args.runs)
    except Exception as e:  # noqa: BLE001 - decomposition is best-effort
        print(f"# stage instrumentation failed: {e}", file=sys.stderr)
        result["stages_error"] = str(e)[:200]
    snapshot("stages_done")

    # -- Pallas A/B on real accelerators ------------------------------------
    # The default dense path is XLA (measured faster for q1's tiny group
    # counts — see kernels/aggregate.py); re-run q1 with the Pallas
    # kernel forced ON so the delta is recorded automatically each run
    # and a future shape class that favors the kernel shows up in the
    # JSON. A FRESH context is required: operator jit caches bake the
    # path at trace time.
    if platform != "cpu":
        try:
            os.environ["BALLISTA_PALLAS"] = "on"
            ctx_p = BallistaContext.standalone()
            register_tpch(ctx_p, data_dir, "tbl", cached=True, **reg_kw)
            dfp = ctx_p.sql(sql)
            dfp.collect()  # load + compile with the Pallas path
            q1_pallas = min(timed(dfp) for _ in range(args.runs))
            result["q1_pallas_warm_seconds"] = round(q1_pallas, 4)
            result["q1_pallas_rows_per_sec"] = round(total_rows / q1_pallas, 1)
            result["pallas_vs_default"] = round(warm / q1_pallas, 3)
        except Exception as e:  # noqa: BLE001 - A/B is best-effort
            print(f"# pallas q1 A/B failed: {e}", file=sys.stderr)
            result["q1_pallas_error"] = str(e)[:200]
        finally:
            os.environ.pop("BALLISTA_PALLAS", None)
    result.pop("partial", None)  # complete: drop the phase marker
    record_compiles()
    # flush so the parent's watchdog can salvage the line even if this
    # process subsequently wedges in teardown and gets killed
    print(json.dumps(result), flush=True)


def _cache_phase(data_dir: str, result: dict, sql: str,
                 qdir: str) -> None:
    """Warm-path serving caches (docs/caching.md), three measured
    legs on a FRESH residency tier so earlier phases' fills don't
    pollute the numbers:

    - repeat-scan q1: cold run fills the device table cache, warm run
      scans from pinned batches (parse + H2D ~ 0), byte-identity
      checked;
    - repeat-collect q1 with the result cache opted in: the second
      collect returns host-cached rows without executing;
    - fixed-budget leg: budget sized so lineitem fits but lineitem +
      orders does NOT — the orders fill evicts the coldest entry, the
      q1 re-scan degrades to re-ingest, results stay identical and the
      governed peak respects the budget."""
    from benchmarks.tpch.schema_def import TPCH_PKS, TPCH_SCHEMAS
    from ballista_tpu.cache import cache_counters, reset_cache_stats
    from ballista_tpu.cache import residency
    from ballista_tpu.client import BallistaContext

    def fresh_ctx(settings=None, tables=("lineitem",)):
        ctx = BallistaContext("standalone", settings=settings)
        for t in tables:
            ctx.register_tbl(t, os.path.join(data_dir, t),
                             TPCH_SCHEMAS[t], primary_key=TPCH_PKS[t])
        return ctx

    # -- tier (a): repeat-scan ---------------------------------------------
    residency._reset_for_tests()
    reset_cache_stats()
    df = fresh_ctx().sql(sql)
    t0 = time.time()
    base = df.collect()
    cold = time.time() - t0
    t0 = time.time()
    warm_out = df.collect()
    warm = time.time() - t0
    fill_bytes = int(cache_counters()["table_cache_resident_bytes"])
    result["cache_cold_q1_seconds"] = round(cold, 4)
    result["cache_warm_q1_seconds"] = round(warm, 4)
    result["cache_q1_speedup"] = round(cold / warm, 2) if warm > 0 else 0.0
    result["cache_q1_identical"] = int(base.equals(warm_out))
    result["table_cache_fill_bytes"] = fill_bytes

    # -- tier (c): repeat-collect (opt-in per context) -----------------------
    df_rc = fresh_ctx({"result_cache.enabled": "on"}).sql(sql)
    df_rc.collect()  # miss + fill (scans serve from the table cache)
    t0 = time.time()
    hit = df_rc.collect()
    rc = time.time() - t0
    result["result_cache_hit_seconds"] = round(rc, 4)
    result["result_cache_speedup"] = round(warm / rc, 1) if rc > 0 else 0.0
    result["result_cache_identical"] = int(base.equals(hit))

    # -- fixed-budget leg ----------------------------------------------------
    # the smallest whole-MB budget whose watermark still admits
    # lineitem: q1 pins it and the peak must respect the budget. Then
    # the budget is SHRUNK to 1 MB mid-leg (the knobs read the env at
    # call time, so an operator can tighten a live process): the orders
    # fill can only charge by evicting lineitem, and the q1 re-scan no
    # longer fits — it degrades to the plain streaming re-ingest.
    # Results stay byte-identical throughout; nothing ever fails.
    budget_mb = max(1, -(-fill_bytes // int(0.9 * (1 << 20))))
    residency._reset_for_tests()
    saved = os.environ.get("BALLISTA_TABLE_CACHE_BUDGET_MB")
    os.environ["BALLISTA_TABLE_CACHE_BUDGET_MB"] = str(budget_mb)
    try:
        ctx_b = fresh_ctx(tables=("lineitem", "orders"))
        dfb = ctx_b.sql(sql)
        out1 = dfb.collect()  # fills lineitem under the sized budget
        os.environ["BALLISTA_TABLE_CACHE_BUDGET_MB"] = "1"
        ctx_b.sql("SELECT COUNT(*) AS n FROM orders").collect()  # evicts
        out2 = dfb.collect()  # no longer fits: degrade to re-ingest
        cc = cache_counters()
        result["cache_budget_mb"] = budget_mb
        result["cache_budget_peak_resident_bytes"] = int(
            cc["table_cache_peak_resident_bytes"])
        result["cache_budget_ok"] = int(
            cc["table_cache_peak_resident_bytes"] <= budget_mb << 20)
        result["cache_budget_evictions"] = int(
            cc["table_cache_evictions"])
        result["cache_budget_identical"] = int(
            base.equals(out1) and base.equals(out2))
    finally:
        if saved is None:
            os.environ.pop("BALLISTA_TABLE_CACHE_BUDGET_MB", None)
        else:
            os.environ["BALLISTA_TABLE_CACHE_BUDGET_MB"] = saved
        residency._reset_for_tests()


def _spill_q5(data_dir: str, result: dict, qdir: str) -> None:
    """Fixed-budget q5 on an in-process LocalCluster: remote fetches
    forced so every shuffle read streams through the governed data
    plane, with a budget small enough that past-watermark chunks spill
    to size-rotated disk files. Emits the gated fields: wall time,
    spill volume, in-flight peak and the configured budget."""
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed import spill as _spill
    from ballista_tpu.distributed.executor import LocalCluster
    from ballista_tpu.observability import memory as obs_memory
    from ballista_tpu.physical.shuffle import ShuffleReaderExec

    # 128 KiB budget / 32 KiB chunks: in-flight wire bytes are bounded
    # by parts concurrently in fetch+decode (each part's buffer drains
    # at decode), so the budget must sit BELOW one part's wire volume
    # to genuinely force the spill lane at bench scales (>= 0.1)
    budget = 128 << 10
    chunk = 32 << 10
    saved = {k: os.environ.get(k) for k in
             ("BALLISTA_SHUFFLE_MEM_BUDGET", "BALLISTA_SHUFFLE_CHUNK_BYTES")}
    os.environ["BALLISTA_SHUFFLE_MEM_BUDGET"] = str(budget)
    os.environ["BALLISTA_SHUFFLE_CHUNK_BYTES"] = str(chunk)
    force_remote0 = ShuffleReaderExec.FORCE_REMOTE
    ShuffleReaderExec.FORCE_REMOTE = True
    gov = _spill.governor()
    gov.reset_stats()
    rss0 = obs_memory.peak_rss_bytes()
    cluster = LocalCluster(num_executors=2, concurrent_tasks=2)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port,
                                     **{"job.timeout": "600"})
        register_tpch(ctx, data_dir, "tbl")
        sql = open(os.path.join(qdir, "q5.sql")).read()
        t0 = time.time()
        ctx.sql(sql).collect()
        wall = time.time() - t0
    finally:
        cluster.shutdown()
        ShuffleReaderExec.FORCE_REMOTE = force_remote0
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    st = gov.stats()
    result["spill_q5_seconds"] = round(wall, 4)
    result["spill_bytes"] = int(st["spilled_bytes_total"])
    result["shuffle_peak_inflight_mb"] = round(
        st["peak_inflight_bytes"] / 1e6, 2)
    result["spill_budget_mb"] = round(budget / 1e6, 2)
    result["spill_chunk_mb"] = round(chunk / 1e6, 2)
    result["spill_q5_peak_rss_mb"] = round(
        max(obs_memory.peak_rss_bytes(), rss0) / 1e6, 1)


def _count_lineitem_rows(data_dir: str) -> int:
    total = 0
    d = os.path.join(data_dir, "lineitem")
    for f in os.listdir(d):
        if f.endswith(".tbl"):
            with open(os.path.join(d, f), "rb") as fh:
                total += sum(buf.count(b"\n") for buf in
                             iter(lambda: fh.read(1 << 20), b""))
    return total


if __name__ == "__main__":
    main()
