"""Large-scale-factor TPC-H runs (BASELINE.json configs 2-3: q1/q3 at
SF=10) with pandas-oracle verification, emitting a JSON artifact.

The engine path exercises the bounded-RAM streaming scan
(io/text.py STREAM_CHUNK_BYTES byte-range chunks through the native C++
scanner) — the machinery that breaks the old whole-file-in-RAM SF=1
ceiling. The oracle is an independent pandas computation over the same
files (benchmarks/tpch/oracle.py), so correctness at scale is asserted,
not assumed.

Usage: python benchmarks/sf_run.py --data bench_data/sf10 \
           [--queries q1,q3] [--runs 2] [--no-oracle] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if os.environ.get("BALLISTA_SF_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

QDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpch",
                    "queries")

# tables each query's oracle needs (loading all 8 at SF=10 wastes RAM/time)
ORACLE_TABLES = {
    "q1": ["lineitem"],
    "q3": ["customer", "orders", "lineitem"],
    "q5": ["customer", "orders", "lineitem", "supplier", "nation", "region"],
    "q6": ["lineitem"],
}


def _normalize(df):
    out = df.copy()
    for c in out.columns:
        if out[c].dtype.kind == "M":
            out[c] = out[c].values.astype("datetime64[D]")
    return out.reset_index(drop=True)


def run_query(ctx, qname: str, runs: int):
    sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
    t0 = time.time()
    out = ctx.sql(sql).collect()
    first = time.time() - t0
    times = []
    for _ in range(max(runs - 1, 1)):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        times.append(time.time() - t0)
    return out, first, min(times)


def check_oracle(data_dir: str, qname: str, got) -> str:
    import pandas as pd

    from benchmarks.tpch import oracle

    tables = oracle.load_tables(data_dir, only=ORACLE_TABLES.get(qname))
    exp = _normalize(oracle.ORACLES[qname](tables))
    got = _normalize(got)
    assert list(got.columns) == list(exp.columns), (got.columns, exp.columns)
    assert len(got) == len(exp), f"{qname}: {len(got)} vs {len(exp)} rows"
    for c in exp.columns:
        g, e = got[c], exp[c]
        if e.dtype.kind in "fc":
            np.testing.assert_allclose(g.astype(float), e.astype(float),
                                       rtol=1e-5, atol=1e-5,
                                       err_msg=f"{qname}.{c}")
        else:
            assert list(g.astype(str)) == list(e.astype(str)), f"{qname}.{c}"
    return "ok"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--queries", default="q1,q3")
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument("--no-oracle", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    lineitem_rows = 0
    d = os.path.join(args.data, "lineitem")
    for f in os.listdir(d):
        if f.endswith(".tbl"):
            with open(os.path.join(d, f), "rb") as fh:
                lineitem_rows += sum(
                    buf.count(b"\n")
                    for buf in iter(lambda: fh.read(1 << 20), b""))

    result = {
        "data": args.data,
        "platform": jax.devices()[0].platform,
        "lineitem_rows": lineitem_rows,
        "queries": {},
    }
    for qname in args.queries.split(","):
        qname = qname.strip()
        # fresh context per query: holds only this query's cache
        ctx = BallistaContext.standalone()
        register_tpch(ctx, args.data, "tbl")
        out, first, warm = run_query(ctx, qname, args.runs)
        entry = {
            "first_s": round(first, 2),
            "warm_s": round(warm, 2),
            "rows_out": int(len(out)),
            "lineitem_rows_per_s_first": round(lineitem_rows / first, 1),
        }
        print(f"# {qname}: first={first:.2f}s warm={warm:.2f}s "
              f"rows={len(out)}", file=sys.stderr)
        if not args.no_oracle:
            t0 = time.time()
            entry["oracle"] = check_oracle(args.data, qname, out)
            entry["oracle_s"] = round(time.time() - t0, 1)
            print(f"# {qname}: oracle ok ({entry['oracle_s']}s)",
                  file=sys.stderr)
        result["queries"][qname] = entry
        del ctx
    line = json.dumps(result)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
