"""1->N device scaling for the fused ICI shuffle operators.

Measures the BASELINE metric's unmeasured half ("1->8 chip shuffle
scaling efficiency"): the same fused MeshAggExec / MeshJoinExec SPMD
programs the scheduler produces (lax.all_to_all row exchange + per-device
final op) run over meshes of 1/2/4/8 devices.

Two curves per operator:
- weak scaling: rows-per-device fixed, total data grows with N
  (efficiency = t1 / tN, ideal 1.0 — the shuffle's all_to_all volume per
  device is constant);
- strong scaling: total rows fixed, split N ways
  (efficiency = t1 / (N * tN), ideal 1.0).

On the virtual CPU mesh all N devices share host cores, so wall-clock
efficiency there mainly validates that per-device *work* shrinks and the
collective path compiles/executes at every N; chip-true numbers come from
running the same script on real multi-device hardware
(JAX_PLATFORMS=tpu BALLISTA_SCALING_DEVICES=...).

Reference anchor: stage-parallel shuffle scheduling,
rust/scheduler/src/planner.rs:292-330.

Usage: python benchmarks/scaling.py [--rows-per-dev 262144]
           [--total-rows 1048576] [--runs 3] [--out results.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# default to the virtual CPU mesh: the ambient environment often points
# JAX at a single remote TPU chip, useless for 1..8-device curves. Real
# hardware runs opt in with BALLISTA_SCALING_TPU=1 (+ JAX_PLATFORMS).
if os.environ.get("BALLISTA_SCALING_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if ("xla_force_host_platform_device_count" not in flags
        and os.environ["JAX_PLATFORMS"] == "cpu"):
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# sitecustomize may have imported jax before this script ran (with the
# ambient platform already latched), so the env var alone is too late —
# config.update is what actually flips the backend (see tests/conftest.py)
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _agg_exec(n_dev: int, rows: int, n_groups: int = 4096):
    """Production-shaped MeshAggExec: scan -> partial agg producer per
    partition, ICI all_to_all on the group key, per-device final agg."""
    from ballista_tpu import col, count, sum_
    from ballista_tpu.distributed.planner import DistributedPlanner
    from ballista_tpu.distributed.scheduler import _fuse_mesh_stages
    from ballista_tpu.io import MemTableSource
    from ballista_tpu.logical import LogicalPlanBuilder
    from ballista_tpu.physical.mesh_agg import MeshAggExec
    from ballista_tpu.physical.planner import (
        PlannerOptions, create_physical_plan,
    )
    from ballista_tpu import schema as mk_schema, Int64

    rng = np.random.default_rng(11)
    s = mk_schema(("k", Int64), ("v", Int64))
    src = MemTableSource.from_pydict(
        s,
        {"k": rng.integers(0, n_groups, rows),
         "v": rng.integers(0, 1000, rows)},
        num_partitions=max(n_dev, 1),
    )
    plan = (
        LogicalPlanBuilder.scan("t", src)
        .aggregate([col("k")], [sum_(col("v")).alias("sv"),
                                count().alias("n")])
        .build()
    )
    phys = create_physical_plan(plan, PlannerOptions(agg_partitions=max(n_dev, 2)))
    stages = DistributedPlanner().plan_query_stages("scale", phys)
    # fusion gates on a cluster mesh of >= 2; the n=1 baseline point
    # reuses the fused node shape with a 1-device mesh (all_to_all is
    # identity there), so every N runs the identical SPMD program
    fused = _fuse_mesh_stages(stages, max(n_dev, 2))
    node = fused[-1].child
    assert isinstance(node, MeshAggExec), type(node)
    if node.n_devices != n_dev:
        node = MeshAggExec(node.producer, node.group_exprs, node.agg_exprs,
                           node.hash_exprs, n_dev, node.group_capacity)
    return node


def _join_exec(n_dev: int, rows: int):
    """MeshJoinExec: both sides hashed over the mesh + per-device join."""
    from ballista_tpu.io import MemTableSource
    from ballista_tpu.physical.mesh_agg import MeshJoinExec
    from ballista_tpu.physical.operators import ScanExec
    from ballista_tpu import schema as mk_schema, Int64

    rng = np.random.default_rng(13)
    n_keys = max(rows // 4, 16)
    bs = mk_schema(("bk", Int64), ("bv", Int64))
    ps = mk_schema(("pk_", Int64), ("pv", Int64))
    build = MemTableSource.from_pydict(
        bs,
        {"bk": np.arange(n_keys, dtype=np.int64),
         "bv": rng.integers(0, 1000, n_keys)},
        num_partitions=max(n_dev, 1),
    )
    probe = MemTableSource.from_pydict(
        ps,
        {"pk_": rng.integers(0, n_keys, rows),
         "pv": rng.integers(0, 1000, rows)},
        num_partitions=max(n_dev, 1),
    )
    return MeshJoinExec(ScanExec("b", build), ScanExec("p", probe),
                        [("bk", "pk_")], "inner", n_dev)


def _time_exec(node, runs: int):
    """(first_run_s incl. compile, min warm s). Consumes all batches."""
    import jax

    def once():
        t0 = time.time()
        for b in node.execute(0):
            jax.block_until_ready([c.values for c in b.columns])
        return time.time() - t0

    first = once()
    warm = min(once() for _ in range(max(runs, 2)))
    return first, warm


def run_curves(dev_counts, rows_per_dev: int, total_rows: int, runs: int):
    import jax

    out = {
        "platform": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", ""),
        "n_devices_available": len(jax.devices()),
        "rows_per_dev": rows_per_dev,
        "total_rows": total_rows,
        "curves": {},
    }
    for op_name, make in (("mesh_agg", _agg_exec), ("mesh_join", _join_exec)):
        for mode in ("weak", "strong"):
            rows_list = []
            for n in dev_counts:
                rows = rows_per_dev * n if mode == "weak" else total_rows
                node = make(n, rows)
                first, warm = _time_exec(node, runs)
                rows_list.append({
                    "n_devices": n, "rows": rows,
                    "first_s": round(first, 4), "warm_s": round(warm, 4),
                    "rows_per_s": round(rows / warm, 1),
                })
                print(f"# {op_name} {mode} n={n} rows={rows} "
                      f"warm={warm:.4f}s", file=sys.stderr)
            t1 = rows_list[0]["warm_s"]
            for r in rows_list:
                n = r["n_devices"]
                r["efficiency"] = round(
                    t1 / r["warm_s"] if mode == "weak"
                    else t1 / (n * r["warm_s"]), 3)
            out["curves"][f"{op_name}_{mode}"] = rows_list
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows-per-dev", type=int, default=262_144)
    ap.add_argument("--total-rows", type=int, default=1_048_576)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--devices", default=os.environ.get(
        "BALLISTA_SCALING_DEVICES", "1,2,4,8"))
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    dev_counts = [int(x) for x in args.devices.split(",") if x]
    result = run_curves(dev_counts, args.rows_per_dev, args.total_rows,
                        args.runs)
    line = json.dumps(result)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
