"""Cross-engine comparison harness.

The reference ships a Spark comparison harness (reference:
spark/benchmarks/src/main/scala/.../Main.scala:24-121 — same tables and
queries through a Spark session, timed). No Spark exists in this
environment, so the comparison engine is pandas (the same independent
implementations that serve as correctness oracles): every query runs
through BOTH engines on identical data, results are cross-checked, and
per-query timings are reported side by side.

Usage:
  python -m benchmarks.compare --path bench_data/sf02 [--queries 1,5,18]
         [--iterations 2]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--path", required=True)
    ap.add_argument("--format", default="tbl")
    ap.add_argument("--queries", default=",".join(str(i) for i in range(1, 23)))
    ap.add_argument("--iterations", type=int, default=2)
    ap.add_argument("--out", default=None,
                    help="also write the JSON line to this file")
    args = ap.parse_args(argv)

    import numpy as np

    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch import oracle
    from benchmarks.tpch.schema_def import register_tpch

    ctx = BallistaContext.standalone()
    register_tpch(ctx, args.path, args.format, cached=True)
    tables = oracle.load_tables(args.path)
    qdir = os.path.join(os.path.dirname(__file__), "tpch", "queries")

    rows = []
    print(f"{'query':>6} | {'ballista-tpu (s)':>16} | {'pandas (s)':>10} "
          f"| {'speedup':>7} | match")
    print("-" * 60)
    for q in args.queries.split(","):
        qname = f"q{q}"
        sql = open(os.path.join(qdir, f"{qname}.sql")).read()
        df = ctx.sql(sql)
        first = _timed(df.collect)  # first run: scan + compile + execute
        bt = min(_timed(df.collect) for _ in range(args.iterations))
        oracle_fn = oracle.ORACLES[qname]
        oracle_fn(tables)
        pt = min(_timed(lambda: oracle_fn(tables))
                 for _ in range(args.iterations))
        got = df.collect().reset_index(drop=True)
        exp = oracle_fn(tables).reset_index(drop=True)
        match = len(got) == len(exp)
        if match:
            for c in exp.columns:
                g, e = got[c], exp[c]
                try:
                    if e.dtype.kind in "fc":
                        np.testing.assert_allclose(
                            g.astype(float), e.astype(float),
                            rtol=1e-6, atol=1e-6)
                    else:
                        np.testing.assert_array_equal(g.to_numpy(),
                                                      e.to_numpy())
                except AssertionError:
                    match = False
                    break
        speed = pt / bt if bt > 0 else float("inf")
        rows.append({"query": qname, "ballista_s": round(bt, 3),
                     "ballista_first_s": round(first, 3),
                     "pandas_s": round(pt, 3), "speedup": round(speed, 2),
                     "match": match})
        print(f"{qname:>6} | {bt:16.3f} | {pt:10.3f} | {speed:6.2f}x "
              f"| {'OK' if match else 'MISMATCH'}")

    total_b = sum(r["ballista_s"] for r in rows)
    total_p = sum(r["pandas_s"] for r in rows)
    print("-" * 60)
    print(f"{'total':>6} | {total_b:16.3f} | {total_p:10.3f} "
          f"| {total_p / total_b:6.2f}x | "
          f"{'all OK' if all(r['match'] for r in rows) else 'MISMATCHES'}")
    line = json.dumps({"path": args.path,
                       "total_ballista_s": round(total_b, 2),
                       "total_pandas_s": round(total_p, 2),
                       "speedup": round(total_p / total_b, 2),
                       "all_match": all(r["match"] for r in rows),
                       "rows": rows})
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


def _timed(fn) -> float:
    t0 = time.time()
    fn()
    return time.time() - t0


if __name__ == "__main__":
    raise SystemExit(main())
