"""BASELINE config-4 analogue: q5-class queries through a REAL cluster
(scheduler + N executors, hash-shuffle stages over the data plane),
cross-checked against the standalone engine on the same data.

The reference's config is "TPC-H q5 SF=100, 4 executors, Flight shuffle"
(BASELINE.json); SF=100 needs ~90GB of .tbl which exceeds this box's
disk, so the default here is the largest disk-feasible scale — the
structure (4 executors, multi-stage shuffle plan, partitioned joins) is
the config's point. On real TPU slices the same plan fuses into
MeshAgg/MeshJoin SPMD stages (see benchmarks/scaling.py).

Usage: python benchmarks/cluster_run.py --data bench_data/sf30
           [--executors 4] [--queries q5] [--runs 2] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if os.environ.get("BALLISTA_CLUSTER_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

QDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpch",
                    "queries")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", required=True)
    ap.add_argument("--executors", type=int, default=4)
    ap.add_argument("--concurrent-tasks", type=int, default=2)
    ap.add_argument("--queries", default="q5")
    ap.add_argument("--runs", type=int, default=2)
    ap.add_argument("--shuffle-partitions", default="8",
                    help="hash-shuffle width for joins AND aggregations "
                         "(maps to the join.partitions/agg.partitions "
                         "settings)")
    ap.add_argument("--skip-standalone-check", action="store_true")
    ap.add_argument("--timeout", type=float, default=7200.0,
                    help="per-query job timeout seconds (large SF on few "
                         "cores runs long)")
    ap.add_argument("--speculation-secs", type=float, default=0.0,
                    help="straggler speculation age; 0 = off (the default "
                         "here: on a shared-core box every task looks like "
                         "a straggler and duplicates strictly add work)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from ballista_tpu.client import BallistaContext
    from ballista_tpu.distributed.executor import LocalCluster
    from benchmarks.tpch.schema_def import register_tpch

    result = {
        "data": args.data,
        "platform": jax.devices()[0].platform,
        "executors": args.executors,
        "concurrent_tasks": args.concurrent_tasks,
        "shuffle_partitions": args.shuffle_partitions,
        "speculation_secs": args.speculation_secs,
        "queries": {},
    }
    cluster = LocalCluster(num_executors=args.executors,
                           concurrent_tasks=args.concurrent_tasks,
                           speculation_age_secs=args.speculation_secs)
    try:
        ctx = BallistaContext.remote(
            "localhost", cluster.port,
            **{"join.partitions": args.shuffle_partitions,
               "agg.partitions": args.shuffle_partitions,
               "job.timeout": str(args.timeout)})
        register_tpch(ctx, args.data, "tbl")
        for qname in args.queries.split(","):
            qname = qname.strip()
            sql = open(os.path.join(QDIR, f"{qname}.sql")).read()
            t0 = time.time()
            out = ctx.sql(sql).collect()
            first = time.time() - t0
            times = []
            for _ in range(args.runs - 1):
                t0 = time.time()
                out = ctx.sql(sql).collect()
                times.append(time.time() - t0)
            entry = {
                "first_s": round(first, 2),
                "rows_out": int(len(out)),
            }
            if times:
                entry["warm_s"] = round(min(times), 2)
            print(f"# cluster {qname}: first={first:.1f}s "
                  f"warm={min(times) if times else float('nan'):.1f}s "
                  f"rows={len(out)}", file=sys.stderr)
            if not args.skip_standalone_check:
                sctx = BallistaContext.standalone()
                register_tpch(sctx, args.data, "tbl")
                t0 = time.time()
                sa = sctx.sql(sql).collect()
                entry["standalone_s"] = round(time.time() - t0, 2)
                sort_cols = list(out.columns)
                a = out.sort_values(sort_cols).reset_index(drop=True)
                b = sa.sort_values(sort_cols).reset_index(drop=True)
                assert len(a) == len(b), (len(a), len(b))
                for c in a.columns:
                    if b[c].dtype.kind in "fc":
                        np.testing.assert_allclose(
                            a[c].astype(float), b[c].astype(float),
                            rtol=1e-5, atol=1e-5, err_msg=f"{qname}.{c}")
                    else:
                        assert list(a[c].astype(str)) == \
                            list(b[c].astype(str)), f"{qname}.{c}"
                entry["matches_standalone"] = True
                print(f"# cluster {qname}: matches standalone "
                      f"({entry['standalone_s']}s)", file=sys.stderr)
                del sctx
            result["queries"][qname] = entry
    finally:
        cluster.shutdown()
    line = json.dumps(result)
    print(line)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
