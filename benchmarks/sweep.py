"""First-run sweep: run all 22 TPC-H queries once in a fresh process and
report per-query wall time (dominated by trace+compile on first touch).

Usage: python -m benchmarks.sweep --path bench_data/sf02 [--queries 1,5,18]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--path", required=True)
    ap.add_argument("--format", default="tbl")
    ap.add_argument("--queries", default=",".join(str(i) for i in range(1, 23)))
    args = ap.parse_args(argv)

    from ballista_tpu.client import BallistaContext
    from benchmarks.tpch.schema_def import register_tpch

    t0 = time.time()
    ctx = BallistaContext.standalone()
    register_tpch(ctx, args.path, args.format, cached=True)
    qdir = os.path.join(os.path.dirname(__file__), "tpch", "queries")

    times = {}
    for q in args.queries.split(","):
        sql = open(os.path.join(qdir, f"q{q}.sql")).read()
        t1 = time.time()
        ctx.sql(sql).collect()
        times[f"q{q}"] = round(time.time() - t1, 2)
        print(f"q{q}: {times[f'q{q}']:.2f}s", flush=True)

    worst = max(times, key=times.get)
    print(json.dumps({
        "total_s": round(time.time() - t0, 1),
        "sum_query_s": round(sum(times.values()), 1),
        "worst": worst,
        "worst_s": times[worst],
        "times": times,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
