"""TPC-H benchmark CLI.

(reference: rust/benchmarks/tpch/src/main.rs:97-265 — ``tpch benchmark``
runs queries N times through a context and reports per-iteration + avg ms;
``tpch convert`` rewrites .tbl into csv/parquet with repartitioning.)

Usage:
  python -m benchmarks.tpch.main benchmark --path DATA_DIR --query 1 \
      [--iterations 3] [--host H --port P] [--cached] [--debug]
  python -m benchmarks.tpch.main convert --input DIR --output DIR \
      --format parquet [--partitions N]
  python -m benchmarks.tpch.main gen --output DIR --scale 0.01 [--parts 2]
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def cmd_benchmark(args) -> int:
    from ballista_tpu.client import BallistaContext
    from .schema_def import register_tpch

    if args.host:
        ctx = BallistaContext.remote(args.host, args.port,
                                     **{"batch.size": str(args.batch_size)})
    else:
        ctx = BallistaContext.standalone()
    register_tpch(ctx, args.path, args.format, cached=args.cached)

    qdir = os.path.join(os.path.dirname(__file__), "queries")
    sql = open(os.path.join(qdir, f"q{args.query}.sql")).read()
    if args.debug:
        print(sql)
        print(ctx.sql(sql).explain())

    times = []
    out = None
    for i in range(args.iterations):
        t0 = time.time()
        out = ctx.sql(sql).collect()
        ms = 1000 * (time.time() - t0)
        times.append(ms)
        print(f"Query {args.query} iteration {i} took {ms:.1f} ms")
    print(f"Query {args.query} avg time: {sum(times)/len(times):.2f} ms")
    if args.debug and out is not None:
        print(out.to_string())
    return 0


def cmd_convert(args) -> int:
    """Rewrite .tbl data to csv/parquet via the engine's scan + pyarrow."""
    from .schema_def import TPCH_SCHEMAS
    from ballista_tpu.io import TblSource
    import numpy as np

    os.makedirs(args.output, exist_ok=True)
    for name, sch in TPCH_SCHEMAS.items():
        src_path = os.path.join(args.input, name)
        if not os.path.exists(src_path):
            src_path = os.path.join(args.input, f"{name}.tbl")
            if not os.path.exists(src_path):
                print(f"skipping {name}: not found", file=sys.stderr)
                continue
        src = TblSource(src_path, sch)
        frames = []
        for p in range(src.num_partitions()):
            for batch in src.scan(p):
                frames.append(batch.to_pydict())
        import pandas as pd

        df = pd.concat([pd.DataFrame(f) for f in frames], ignore_index=True)
        n_parts = max(args.partitions, 1)
        per = -(-len(df) // n_parts)
        out_dir = os.path.join(args.output, name)
        os.makedirs(out_dir, exist_ok=True)
        for p in range(n_parts):
            chunk = df.iloc[p * per : (p + 1) * per]
            if chunk.empty and p > 0:
                continue
            if args.format == "parquet":
                chunk.to_parquet(
                    os.path.join(out_dir, f"part-{p}.parquet"), index=False
                )
            else:
                chunk.to_csv(
                    os.path.join(out_dir, f"part-{p}.csv"), index=False
                )
        print(f"converted {name}: {len(df)} rows -> {out_dir}")
    return 0


def cmd_gen(args) -> int:
    from . import datagen

    t0 = time.time()
    datagen.generate(args.output, args.scale, args.parts)
    print(f"generated scale {args.scale} in {time.time()-t0:.1f}s at "
          f"{args.output}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpch")
    sub = ap.add_subparsers(dest="cmd", required=True)

    b = sub.add_parser("benchmark")
    b.add_argument("--path", required=True)
    b.add_argument("--format", default="tbl", choices=["tbl", "csv", "parquet"])
    b.add_argument("--query", type=int, required=True)
    b.add_argument("--iterations", type=int, default=3)
    b.add_argument("--host", default="")
    b.add_argument("--port", type=int, default=50050)
    b.add_argument("--batch-size", type=int, default=1 << 20)
    b.add_argument("--cached", action="store_true")
    b.add_argument("--debug", action="store_true")
    b.set_defaults(fn=cmd_benchmark)

    c = sub.add_parser("convert")
    c.add_argument("--input", required=True)
    c.add_argument("--output", required=True)
    c.add_argument("--format", default="parquet", choices=["csv", "parquet"])
    c.add_argument("--partitions", type=int, default=1)
    c.set_defaults(fn=cmd_convert)

    g = sub.add_parser("gen")
    g.add_argument("--output", required=True)
    g.add_argument("--scale", type=float, default=0.01)
    g.add_argument("--parts", type=int, default=2)
    g.set_defaults(fn=cmd_gen)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
