"""TPC-H-like data generator (numpy, vectorized, STREAMING).

Produces dbgen-compatible ``.tbl`` layout (| separated, trailing |) with the
standard schemas, row-count ratios, and value distributions/correlations the
benchmark queries rely on (date-correlated returnflag/linestatus, price =
f(partkey), etc.). It is NOT bit-identical to official dbgen (different
RNG), so golden results come from the pandas oracle in oracle.py rather
than the spec's answer sets. Reference equivalent: dockerized dbgen
(reference: rust/benchmarks/tpch/tpch-gen.sh:1-16; partitioned generation
like the convert step at rust/benchmarks/tpch/src/main.rs:196-265).

Generation is CHUNKED: large tables are produced and written in bounded
slices (``chunk_rows`` orders / parts / customers at a time), with chunk
slices appended round-robin across the partition files. Peak RSS is a few
hundred MB regardless of scale factor, so SF=10+ generates on a laptop;
the monolithic whole-table-in-RAM layout capped out near SF=1.
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np

EPOCH = np.datetime64("1970-01-01", "D")
START = np.datetime64("1992-01-01", "D")
END_ORDER = np.datetime64("1998-08-02", "D")
CUTOFF = np.datetime64("1995-06-17", "D")  # returnflag/linestatus boundary

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
# Bump when the generated DISTRIBUTION changes (not just speed): callers
# caching generated dirs key their freshness marker on this, so stale
# data from an older generator is regenerated instead of silently reused.
DATAGEN_VERSION = 2  # v2: custkey%3==0 get no orders (dbgen rule, q22)

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
CONTAINERS = ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE", "LG BOX",
              "JUMBO PACK", "WRAP CASE"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
NOUNS = ["packages", "requests", "accounts", "deposits", "foxes", "ideas",
         "theodolites", "pinto beans", "instructions", "dependencies"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "chartreuse", "green", "red", "white", "yellow", "ivory"]
VERBS = ["sleep", "wake", "haggle", "nag", "cajole", "detect", "integrate",
         "boost", "doze", "wake blithely"]


def _phones(rng, n):
    # country prefix 10-34 like dbgen (q22 uses the 2-digit country code)
    return np.char.add(
        np.char.add(rng.integers(10, 35, n).astype(str), "-"),
        rng.integers(10**6, 10**7, n).astype(str),
    )


def _comments(rng, n):
    a = rng.choice(NOUNS, n)
    b = rng.choice(VERBS, n)
    c = rng.integers(0, 1000, n).astype(str)
    return np.char.add(np.char.add(np.char.add(a, " "), b), np.char.add(" #", c))


def _money(rng, n, lo, hi):
    return rng.integers(int(lo * 100), int(hi * 100), n) / 100.0


def _format_lines(cols) -> str:
    """Columns (np arrays, equal length) -> '|'-joined .tbl text block."""
    strs = []
    for c in cols:
        a = np.asarray(c)
        if np.issubdtype(a.dtype, np.floating):
            strs.append(np.char.mod("%.2f", a))
        elif a.dtype.kind == "M":  # datetime64
            strs.append(np.datetime_as_string(a, unit="D"))
        else:
            strs.append(a.astype(str))
    lines = strs[0]
    for s in strs[1:]:
        lines = np.char.add(np.char.add(lines, "|"), s)
    lines = np.char.add(lines, "|")
    return "\n".join(lines.tolist()) + "\n"


class _TableWriter:
    """Appends chunk column-slices round-robin across partition files.

    Files are truncated on first touch so regeneration never appends to a
    previous run's output."""

    def __init__(self, path: str, num_parts: int):
        os.makedirs(path, exist_ok=True)
        for f in os.listdir(path):
            if f.endswith(".tbl"):
                os.unlink(os.path.join(path, f))
        self._paths = [os.path.join(path, f"partition{p}.tbl")
                       for p in range(num_parts)]
        for p in self._paths:  # every partition file exists even if empty
            open(p, "w").close()
        self._next = 0

    def write_chunk(self, cols) -> None:
        if len(np.asarray(cols[0])) == 0:
            return
        text = _format_lines(cols)
        with open(self._paths[self._next], "a") as f:
            f.write(text)
        self._next = (self._next + 1) % len(self._paths)


def _write_tbl(path, cols, num_parts=1, chunk_rows: int = 0):
    """Write columns as .tbl partition files (chunked when asked)."""
    n = len(np.asarray(cols[0]))
    w = _TableWriter(path, num_parts)
    step = chunk_rows or max(n, 1)
    # split into >= num_parts slices so every partition file gets rows
    step = min(step, -(-n // num_parts)) if n else step
    lo = 0
    while lo < n:
        hi = min(lo + step, n)
        w.write_chunk([np.asarray(c)[lo:hi] for c in cols])
        lo = hi


def _gen_orders_chunk(rng, lo, hi, n_cust, n_part, n_supp):
    """Generate orders rows [lo, hi) plus their lineitems (both as column
    lists). Self-contained per chunk: lineitem attributes derive from this
    chunk's orders only, so peak memory is O(chunk)."""
    n = hi - lo
    okey = (np.arange(lo, hi) + 1) * 4 - 3  # sparse keys like dbgen
    # dbgen never assigns orders to custkey % 3 == 0 (a third of
    # customers have no orders) — q22's "customers without orders"
    # anti-join is vacuously empty without this. Drawn uniformly over
    # the non-multiples via j -> j + (j-1)//2 (the j-th positive
    # integer not divisible by 3), so every eligible customer has the
    # same order probability.
    n_eligible = n_cust - n_cust // 3
    j = rng.integers(1, n_eligible + 1, n)
    o_cust = j + (j - 1) // 2
    span = int((END_ORDER - START) / np.timedelta64(1, "D"))
    o_date = START + rng.integers(0, span, n).astype("timedelta64[D]")
    orders_cols = [
        okey, o_cust,
        rng.choice(["O", "F", "P"], n, p=[0.49, 0.49, 0.02]),
        _money(rng, n, 1000.0, 400000.0),
        o_date,
        rng.choice(PRIORITIES, n),
        np.char.add("Clerk#", rng.integers(1, 1000, n).astype(str)),
        np.zeros(n, dtype=np.int64),
        _comments(rng, n),
    ]

    n_lines_per = rng.integers(1, 8, n)
    l_okey = np.repeat(okey, n_lines_per)
    l_odate = np.repeat(o_date, n_lines_per)
    n_li = len(l_okey)
    l_pkey = rng.integers(1, n_part + 1, n_li)
    l_skey = ((l_pkey - 1 + rng.integers(0, 4, n_li) * (n_supp // 4 + 1))
              % n_supp) + 1
    # l_linenumber: 1..k within each order, vectorized
    starts = np.cumsum(n_lines_per) - n_lines_per
    l_lnum = np.arange(n_li) - np.repeat(starts, n_lines_per) + 1
    qty = rng.integers(1, 51, n_li)
    retail_of = (90000 + (l_pkey % 20001) + 100 * (l_pkey % 1000)) / 100.0
    eprice = np.round(qty * retail_of, 2)
    disc = rng.integers(0, 11, n_li) / 100.0
    tax = rng.integers(0, 9, n_li) / 100.0
    sdate = l_odate + rng.integers(1, 122, n_li).astype("timedelta64[D]")
    cdate = l_odate + rng.integers(30, 91, n_li).astype("timedelta64[D]")
    rdate = sdate + rng.integers(1, 31, n_li).astype("timedelta64[D]")
    returned = rdate <= CUTOFF
    rflag = np.where(returned,
                     np.where(rng.random(n_li) < 0.5, "R", "A"), "N")
    lstatus = np.where(sdate > CUTOFF, "O", "F")
    lineitem_cols = [
        l_okey, l_pkey, l_skey, l_lnum,
        qty.astype(np.float64), eprice, disc, tax,
        rflag, lstatus, sdate, cdate, rdate,
        rng.choice(INSTRUCTIONS, n_li),
        rng.choice(SHIPMODES, n_li),
        _comments(rng, n_li),
    ]
    return orders_cols, lineitem_cols


def generate(data_dir: str, scale: float = 0.01, num_parts: int = 2,
             seed: int = 7, chunk_rows: int = 500_000) -> None:
    """Generate all 8 tables at ``scale`` into ``data_dir``.

    ``chunk_rows`` bounds how many orders/parts/customers are materialized
    at once (lineitem ~4x that); RAM stays O(chunk_rows) at any scale."""
    rng = np.random.default_rng(seed)
    n_cust = max(int(150_000 * scale), 10)
    n_ord = n_cust * 10
    n_part = max(int(200_000 * scale), 20)
    n_supp = max(int(10_000 * scale), 5)

    # region / nation ------------------------------------------------------
    _write_tbl(os.path.join(data_dir, "region"), [
        np.arange(5), np.asarray(REGIONS),
        _comments(rng, 5),
    ], 1)
    _write_tbl(os.path.join(data_dir, "nation"), [
        np.arange(25), np.asarray([n for n, _ in NATIONS]),
        np.asarray([r for _, r in NATIONS]), _comments(rng, 25),
    ], 1)

    # supplier -------------------------------------------------------------
    skey = np.arange(1, n_supp + 1)
    _write_tbl(os.path.join(data_dir, "supplier"), [
        skey,
        np.char.add("Supplier#", skey.astype(str)),
        np.char.add("Addr S", rng.integers(0, 10**6, n_supp).astype(str)),
        rng.integers(0, 25, n_supp),
        _phones(rng, n_supp),
        _money(rng, n_supp, -999.99, 9999.99),
        _comments(rng, n_supp),
    ], 1, chunk_rows)

    # customer (chunked) ---------------------------------------------------
    # chunk never exceeds a partition's share, so every partition file
    # gets rows even at tiny scales
    def _step(n):
        return max(1, min(chunk_rows, -(-n // num_parts)))

    cw = _TableWriter(os.path.join(data_dir, "customer"), num_parts)
    for lo in range(0, n_cust, _step(n_cust)):
        hi = min(lo + _step(n_cust), n_cust)
        ckey = np.arange(lo + 1, hi + 1)
        m = hi - lo
        cw.write_chunk([
            ckey,
            np.char.add("Customer#", ckey.astype(str)),
            np.char.add("Addr C", rng.integers(0, 10**6, m).astype(str)),
            rng.integers(0, 25, m),
            _phones(rng, m),
            _money(rng, m, -999.99, 9999.99),
            rng.choice(SEGMENTS, m),
            _comments(rng, m),
        ])

    # part + partsupp (chunked together: partsupp derives from the part
    # chunk's keys, 4 suppliers per part like dbgen) ------------------------
    pw = _TableWriter(os.path.join(data_dir, "part"), num_parts)
    psw = _TableWriter(os.path.join(data_dir, "partsupp"), num_parts)
    for lo in range(0, n_part, _step(n_part)):
        hi = min(lo + _step(n_part), n_part)
        pkey = np.arange(lo + 1, hi + 1)
        m = hi - lo
        ptype = np.char.add(
            np.char.add(np.char.add(rng.choice(TYPE_S1, m), " "),
                        np.char.add(rng.choice(TYPE_S2, m), " ")),
            rng.choice(TYPE_S3, m),
        )
        retail = (90000 + (pkey % 20001) + 100 * (pkey % 1000)) / 100.0
        pw.write_chunk([
            pkey,
            np.char.add(
                np.char.add(rng.choice(COLORS, m), " "),
                rng.choice(NOUNS, m),
            ),
            np.char.add("Manufacturer#", rng.integers(1, 6, m).astype(str)),
            rng.choice(BRANDS, m),
            ptype,
            rng.integers(1, 51, m),
            rng.choice(CONTAINERS, m),
            retail,
            _comments(rng, m),
        ])
        ps_pkey = np.repeat(pkey, 4)
        ps_skey = ((ps_pkey - 1 + np.tile(np.arange(4), m) *
                    (n_supp // 4 + 1)) % n_supp) + 1
        n_ps = 4 * m
        psw.write_chunk([
            ps_pkey, ps_skey,
            rng.integers(1, 10000, n_ps),
            _money(rng, n_ps, 1.00, 1000.00),
            _comments(rng, n_ps),
        ])

    # orders + lineitem (chunked together) ---------------------------------
    ow = _TableWriter(os.path.join(data_dir, "orders"), num_parts)
    lw = _TableWriter(os.path.join(data_dir, "lineitem"), num_parts)
    for lo in range(0, n_ord, _step(n_ord)):
        hi = min(lo + _step(n_ord), n_ord)
        orders_cols, lineitem_cols = _gen_orders_chunk(
            rng, lo, hi, n_cust, n_part, n_supp)
        ow.write_chunk(orders_cols)
        lw.write_chunk(lineitem_cols)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--parts", type=int, default=2)
    ap.add_argument("--chunk-rows", type=int, default=500_000)
    args = ap.parse_args()
    generate(args.out, args.scale, args.parts, chunk_rows=args.chunk_rows)
    print(f"generated TPC-H-like data at scale {args.scale} in {args.out}")
