"""TPC-H-like data generator (numpy, vectorized).

Produces dbgen-compatible ``.tbl`` layout (| separated, trailing |) with the
standard schemas, row-count ratios, and value distributions/correlations the
benchmark queries rely on (date-correlated returnflag/linestatus, price =
f(partkey), etc.). It is NOT bit-identical to official dbgen (different
RNG), so golden results come from the pandas oracle in oracle.py rather
than the spec's answer sets. Reference equivalent: dockerized dbgen
(reference: rust/benchmarks/tpch/tpch-gen.sh:1-16).
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np

EPOCH = np.datetime64("1970-01-01", "D")
START = np.datetime64("1992-01-01", "D")
END_ORDER = np.datetime64("1998-08-02", "D")
CUTOFF = np.datetime64("1995-06-17", "D")  # returnflag/linestatus boundary

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
INSTRUCTIONS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
CONTAINERS = ["SM CASE", "SM BOX", "MED BAG", "MED BOX", "LG CASE", "LG BOX",
              "JUMBO PACK", "WRAP CASE"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
NOUNS = ["packages", "requests", "accounts", "deposits", "foxes", "ideas",
         "theodolites", "pinto beans", "instructions", "dependencies"]
COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
          "black", "blanched", "blue", "blush", "brown", "burlywood",
          "chartreuse", "green", "red", "white", "yellow", "ivory"]
VERBS = ["sleep", "wake", "haggle", "nag", "cajole", "detect", "integrate",
         "boost", "doze", "wake blithely"]


def _phones(rng, n):
    # country prefix 10-34 like dbgen (q22 uses the 2-digit country code)
    return np.char.add(
        np.char.add(rng.integers(10, 35, n).astype(str), "-"),
        rng.integers(10**6, 10**7, n).astype(str),
    )


def _comments(rng, n):
    a = rng.choice(NOUNS, n)
    b = rng.choice(VERBS, n)
    c = rng.integers(0, 1000, n).astype(str)
    return np.char.add(np.char.add(np.char.add(a, " "), b), np.char.add(" #", c))


def _money(rng, n, lo, hi):
    return rng.integers(int(lo * 100), int(hi * 100), n) / 100.0


def _write_tbl(path, cols, num_parts=1):
    """Write columns (list of np arrays) as .tbl partition files."""
    n = len(cols[0])
    os.makedirs(path, exist_ok=True)
    per = -(-n // num_parts)
    for p in range(num_parts):
        lo, hi = p * per, min((p + 1) * per, n)
        if lo >= hi and p > 0:
            continue
        strs = []
        for c in cols:
            if np.issubdtype(np.asarray(c).dtype, np.floating):
                strs.append(np.char.mod("%.2f", c[lo:hi]))
            elif np.asarray(c).dtype.kind == "M":  # datetime64
                strs.append(np.datetime_as_string(c[lo:hi], unit="D"))
            else:
                strs.append(np.asarray(c[lo:hi]).astype(str))
        lines = strs[0]
        for s in strs[1:]:
            lines = np.char.add(np.char.add(lines, "|"), s)
        lines = np.char.add(lines, "|")
        with open(os.path.join(path, f"partition{p}.tbl"), "w") as f:
            f.write("\n".join(lines.tolist()))
            f.write("\n")


def generate(data_dir: str, scale: float = 0.01, num_parts: int = 2,
             seed: int = 7) -> None:
    rng = np.random.default_rng(seed)
    n_cust = max(int(150_000 * scale), 10)
    n_ord = n_cust * 10
    n_part = max(int(200_000 * scale), 20)
    n_supp = max(int(10_000 * scale), 5)
    n_psupp = n_part * 4

    # region / nation ------------------------------------------------------
    _write_tbl(os.path.join(data_dir, "region"), [
        np.arange(5), np.asarray(REGIONS),
        _comments(rng, 5),
    ], 1)
    _write_tbl(os.path.join(data_dir, "nation"), [
        np.arange(25), np.asarray([n for n, _ in NATIONS]),
        np.asarray([r for _, r in NATIONS]), _comments(rng, 25),
    ], 1)

    # supplier -------------------------------------------------------------
    skey = np.arange(1, n_supp + 1)
    _write_tbl(os.path.join(data_dir, "supplier"), [
        skey,
        np.char.add("Supplier#", skey.astype(str)),
        np.char.add("Addr S", rng.integers(0, 10**6, n_supp).astype(str)),
        rng.integers(0, 25, n_supp),
        _phones(rng, n_supp),
        _money(rng, n_supp, -999.99, 9999.99),
        _comments(rng, n_supp),
    ], 1)

    # customer -------------------------------------------------------------
    ckey = np.arange(1, n_cust + 1)
    _write_tbl(os.path.join(data_dir, "customer"), [
        ckey,
        np.char.add("Customer#", ckey.astype(str)),
        np.char.add("Addr C", rng.integers(0, 10**6, n_cust).astype(str)),
        rng.integers(0, 25, n_cust),
        _phones(rng, n_cust),
        _money(rng, n_cust, -999.99, 9999.99),
        rng.choice(SEGMENTS, n_cust),
        _comments(rng, n_cust),
    ], num_parts)

    # part -----------------------------------------------------------------
    pkey = np.arange(1, n_part + 1)
    ptype = np.char.add(
        np.char.add(np.char.add(rng.choice(TYPE_S1, n_part), " "),
                    np.char.add(rng.choice(TYPE_S2, n_part), " ")),
        rng.choice(TYPE_S3, n_part),
    )
    retail = (90000 + (pkey % 20001) + 100 * (pkey % 1000)) / 100.0
    _write_tbl(os.path.join(data_dir, "part"), [
        pkey,
        np.char.add(
            np.char.add(rng.choice(COLORS, n_part), " "),
            rng.choice(NOUNS, n_part),
        ),
        np.char.add("Manufacturer#", rng.integers(1, 6, n_part).astype(str)),
        rng.choice(BRANDS, n_part),
        ptype,
        rng.integers(1, 51, n_part),
        rng.choice(CONTAINERS, n_part),
        retail,
        _comments(rng, n_part),
    ], num_parts)

    # partsupp (4 suppliers per part, dbgen layout) -------------------------
    ps_pkey = np.repeat(pkey, 4)
    ps_skey = ((ps_pkey - 1 + np.tile(np.arange(4), n_part) *
                (n_supp // 4 + 1)) % n_supp) + 1
    _write_tbl(os.path.join(data_dir, "partsupp"), [
        ps_pkey, ps_skey,
        rng.integers(1, 10000, n_psupp),
        _money(rng, n_psupp, 1.00, 1000.00),
        _comments(rng, n_psupp),
    ], num_parts)

    # orders ---------------------------------------------------------------
    okey = np.arange(1, n_ord + 1) * 4 - 3  # sparse keys like dbgen
    o_cust = rng.integers(1, n_cust + 1, n_ord)
    span = int((END_ORDER - START) / np.timedelta64(1, "D"))
    o_date = START + rng.integers(0, span, n_ord).astype("timedelta64[D]")
    _write_tbl(os.path.join(data_dir, "orders"), [
        okey, o_cust,
        rng.choice(["O", "F", "P"], n_ord, p=[0.49, 0.49, 0.02]),
        _money(rng, n_ord, 1000.0, 400000.0),
        o_date,
        rng.choice(PRIORITIES, n_ord),
        np.char.add("Clerk#", rng.integers(1, 1000, n_ord).astype(str)),
        np.zeros(n_ord, dtype=np.int64),
        _comments(rng, n_ord),
    ], num_parts)

    # lineitem -------------------------------------------------------------
    n_lines_per = rng.integers(1, 8, n_ord)
    l_okey = np.repeat(okey, n_lines_per)
    l_odate = np.repeat(o_date, n_lines_per)
    n_li = len(l_okey)
    l_pkey = rng.integers(1, n_part + 1, n_li)
    l_skey = ((l_pkey - 1 + rng.integers(0, 4, n_li) * (n_supp // 4 + 1))
              % n_supp) + 1
    l_lnum = np.concatenate([np.arange(1, k + 1) for k in n_lines_per])
    qty = rng.integers(1, 51, n_li)
    retail_of = (90000 + (l_pkey % 20001) + 100 * (l_pkey % 1000)) / 100.0
    eprice = np.round(qty * retail_of, 2)
    disc = rng.integers(0, 11, n_li) / 100.0
    tax = rng.integers(0, 9, n_li) / 100.0
    sdate = l_odate + rng.integers(1, 122, n_li).astype("timedelta64[D]")
    cdate = l_odate + rng.integers(30, 91, n_li).astype("timedelta64[D]")
    rdate = sdate + rng.integers(1, 31, n_li).astype("timedelta64[D]")
    returned = rdate <= CUTOFF
    rflag = np.where(returned,
                     np.where(rng.random(n_li) < 0.5, "R", "A"), "N")
    lstatus = np.where(sdate > CUTOFF, "O", "F")
    _write_tbl(os.path.join(data_dir, "lineitem"), [
        l_okey, l_pkey, l_skey, l_lnum,
        qty.astype(np.float64), eprice, disc, tax,
        rflag, lstatus, sdate, cdate, rdate,
        rng.choice(INSTRUCTIONS, n_li),
        rng.choice(SHIPMODES, n_li),
        _comments(rng, n_li),
    ], num_parts)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--parts", type=int, default=2)
    args = ap.parse_args()
    generate(args.out, args.scale, args.parts)
    print(f"generated TPC-H-like data at scale {args.scale} in {args.out}")
