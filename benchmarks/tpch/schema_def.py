"""TPC-H table schemas (standard spec; reference equivalent:
rust/benchmarks/tpch/src/main.rs:267-360 hard-coded schemas)."""

from ballista_tpu import schema, Int32, Int64, Decimal, Utf8, Date32

TPCH_SCHEMAS = {
    "region": schema(
        ("r_regionkey", Int64), ("r_name", Utf8), ("r_comment", Utf8)
    ),
    "nation": schema(
        ("n_nationkey", Int64), ("n_name", Utf8), ("n_regionkey", Int64),
        ("n_comment", Utf8),
    ),
    "supplier": schema(
        ("s_suppkey", Int64), ("s_name", Utf8), ("s_address", Utf8),
        ("s_nationkey", Int64), ("s_phone", Utf8), ("s_acctbal", Decimal(2)),
        ("s_comment", Utf8),
    ),
    "customer": schema(
        ("c_custkey", Int64), ("c_name", Utf8), ("c_address", Utf8),
        ("c_nationkey", Int64), ("c_phone", Utf8), ("c_acctbal", Decimal(2)),
        ("c_mktsegment", Utf8), ("c_comment", Utf8),
    ),
    "part": schema(
        ("p_partkey", Int64), ("p_name", Utf8), ("p_mfgr", Utf8),
        ("p_brand", Utf8), ("p_type", Utf8), ("p_size", Int32),
        ("p_container", Utf8), ("p_retailprice", Decimal(2)),
        ("p_comment", Utf8),
    ),
    "partsupp": schema(
        ("ps_partkey", Int64), ("ps_suppkey", Int64), ("ps_availqty", Int32),
        ("ps_supplycost", Decimal(2)), ("ps_comment", Utf8),
    ),
    "orders": schema(
        ("o_orderkey", Int64), ("o_custkey", Int64), ("o_orderstatus", Utf8),
        ("o_totalprice", Decimal(2)), ("o_orderdate", Date32),
        ("o_orderpriority", Utf8), ("o_clerk", Utf8),
        ("o_shippriority", Int32), ("o_comment", Utf8),
    ),
    "lineitem": schema(
        ("l_orderkey", Int64), ("l_partkey", Int64), ("l_suppkey", Int64),
        ("l_linenumber", Int32), ("l_quantity", Decimal(2)),
        ("l_extendedprice", Decimal(2)), ("l_discount", Decimal(2)),
        ("l_tax", Decimal(2)), ("l_returnflag", Utf8),
        ("l_linestatus", Utf8), ("l_shipdate", Date32),
        ("l_commitdate", Date32), ("l_receiptdate", Date32),
        ("l_shipinstruct", Utf8), ("l_shipmode", Utf8), ("l_comment", Utf8),
    ),
}

# primary keys for join-side selection (lineitem/partsupp have composite
# PKs -> none usable as a single unique column)
TPCH_PKS = {
    "region": "r_regionkey",
    "nation": "n_nationkey",
    "supplier": "s_suppkey",
    "customer": "c_custkey",
    "part": "p_partkey",
    "partsupp": None,
    "orders": "o_orderkey",
    "lineitem": None,
}


def register_tpch(ctx, data_dir: str, fmt: str = "tbl", cached: bool = False,
                  **kw):
    import os

    for name, sch in TPCH_SCHEMAS.items():
        path = os.path.join(data_dir, name)
        if not os.path.exists(path):
            path = os.path.join(data_dir, f"{name}.{fmt}")
        if fmt == "tbl":
            ctx.register_tbl(name, path, sch, primary_key=TPCH_PKS[name],
                             cached=cached, **kw)
        elif fmt == "parquet":
            ctx.register_parquet(name, path, sch, primary_key=TPCH_PKS[name],
                                 cached=cached, **kw)
        else:
            ctx.register_csv(name, path, sch, primary_key=TPCH_PKS[name],
                             cached=cached, **kw)
