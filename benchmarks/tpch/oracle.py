"""Independent pandas implementations of the TPC-H queries, used as the
correctness oracle for the engine (golden results; the reference eyeballs a
known q1 table, rust/benchmarks/tpch/README.md:70-84 — we assert
programmatically instead)."""

from __future__ import annotations

import os

import numpy as np
import pandas as pd

from .schema_def import TPCH_SCHEMAS

_D = lambda s: np.datetime64(s, "D")


def load_tables(data_dir: str) -> dict:
    out = {}
    for name, sch in TPCH_SCHEMAS.items():
        base = os.path.join(data_dir, name)
        files = (
            sorted(
                os.path.join(base, f) for f in os.listdir(base)
                if f.endswith(".tbl")
            )
            if os.path.isdir(base)
            else [base + ".tbl"]
        )
        names = list(sch.names()) + ["__t"]
        parts = [
            pd.read_csv(f, sep="|", header=None, names=names,
                        usecols=range(len(sch)))
            for f in files
        ]
        df = pd.concat(parts, ignore_index=True)
        for f_ in sch.fields:
            if f_.dtype.kind == "date32":
                df[f_.name] = pd.to_datetime(df[f_.name]).values.astype(
                    "datetime64[D]"
                )
        out[name] = df
    return out


def q1(t):
    l = t["lineitem"]
    d = l[l.l_shipdate <= _D("1998-09-02")]
    g = d.groupby(["l_returnflag", "l_linestatus"])

    def agg(x):
        disc = x.l_extendedprice * (1 - x.l_discount)
        return pd.Series({
            "sum_qty": x.l_quantity.sum(),
            "sum_base_price": x.l_extendedprice.sum(),
            "sum_disc_price": disc.sum(),
            "sum_charge": (disc * (1 + x.l_tax)).sum(),
            "avg_qty": x.l_quantity.mean(),
            "avg_price": x.l_extendedprice.mean(),
            "avg_disc": x.l_discount.mean(),
            "count_order": len(x),
        })

    return (
        g.apply(agg, include_groups=False)
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
        .reset_index(drop=True)
    )


def q3(t):
    c = t["customer"]; o = t["orders"]; l = t["lineitem"]
    c = c[c.c_mktsegment == "BUILDING"]
    o = o[o.o_orderdate < _D("1995-03-15")]
    l = l[l.l_shipdate > _D("1995-03-15")]
    j = l.merge(o, left_on="l_orderkey", right_on="o_orderkey").merge(
        c, left_on="o_custkey", right_on="c_custkey"
    )
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    out = (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"]
        .sum()
        .reset_index()[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10)
        .reset_index(drop=True)
    )
    return out


def q5(t):
    c, o, l = t["customer"], t["orders"], t["lineitem"]
    s, n, r = t["supplier"], t["nation"], t["region"]
    r = r[r.r_name == "ASIA"]
    o = o[(o.o_orderdate >= _D("1994-01-01")) & (o.o_orderdate < _D("1995-01-01"))]
    j = (
        l.merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    )
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(n, left_on="s_nationkey", right_on="n_nationkey").merge(
        r, left_on="n_regionkey", right_on="r_regionkey"
    )
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    return (
        j.groupby("n_name")["revenue"].sum().reset_index()
        .sort_values("revenue", ascending=False).reset_index(drop=True)
    )


def q6(t):
    l = t["lineitem"]
    d = l[
        (l.l_shipdate >= _D("1994-01-01")) & (l.l_shipdate < _D("1995-01-01"))
        & (l.l_discount >= 0.05) & (l.l_discount <= 0.07) & (l.l_quantity < 24)
    ]
    return pd.DataFrame({"revenue": [(d.l_extendedprice * d.l_discount).sum()]})


def q10(t):
    c, o, l, n = t["customer"], t["orders"], t["lineitem"], t["nation"]
    o = o[(o.o_orderdate >= _D("1993-10-01")) & (o.o_orderdate < _D("1994-01-01"))]
    l = l[l.l_returnflag == "R"]
    j = (
        l.merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    out = (
        j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"])["revenue"].sum().reset_index()
    )
    out = out[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
               "c_address", "c_phone", "c_comment"]]
    return (
        out.sort_values("revenue", ascending=False).head(20).reset_index(drop=True)
    )


def q12(t):
    o, l = t["orders"], t["lineitem"]
    d = l[
        l.l_shipmode.isin(["MAIL", "SHIP"])
        & (l.l_commitdate < l.l_receiptdate)
        & (l.l_shipdate < l.l_commitdate)
        & (l.l_receiptdate >= _D("1994-01-01"))
        & (l.l_receiptdate < _D("1995-01-01"))
    ]
    j = d.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    out = (
        j.assign(high=high.astype(int), low=(~high).astype(int))
        .groupby("l_shipmode")[["high", "low"]].sum().reset_index()
        .rename(columns={"high": "high_line_count", "low": "low_line_count"})
        .sort_values("l_shipmode").reset_index(drop=True)
    )
    return out


ORACLES = {"q1": q1, "q3": q3, "q5": q5, "q6": q6, "q10": q10, "q12": q12}
