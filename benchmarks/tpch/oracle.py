"""Independent pandas implementations of the TPC-H queries, used as the
correctness oracle for the engine (golden results; the reference eyeballs a
known q1 table, rust/benchmarks/tpch/README.md:70-84 — we assert
programmatically instead)."""

from __future__ import annotations

import os

import numpy as np
import pandas as pd

from .schema_def import TPCH_SCHEMAS

_D = lambda s: np.datetime64(s, "D")


def load_tables(data_dir: str, only=None) -> dict:
    """``only``: subset of table names to load (large scale factors:
    loading all 8 tables into pandas costs tens of GB of RAM)."""
    out = {}
    for name, sch in TPCH_SCHEMAS.items():
        if only is not None and name not in only:
            continue
        base = os.path.join(data_dir, name)
        files = (
            sorted(
                os.path.join(base, f) for f in os.listdir(base)
                if f.endswith(".tbl")
            )
            if os.path.isdir(base)
            else [base + ".tbl"]
        )
        names = list(sch.names()) + ["__t"]
        parts = [
            pd.read_csv(f, sep="|", header=None, names=names,
                        usecols=range(len(sch)))
            for f in files
        ]
        df = pd.concat(parts, ignore_index=True)
        for f_ in sch.fields:
            if f_.dtype.kind == "date32":
                df[f_.name] = pd.to_datetime(df[f_.name]).values.astype(
                    "datetime64[D]"
                )
        out[name] = df
    return out


def q1(t):
    l = t["lineitem"]
    d = l[l.l_shipdate <= _D("1998-09-02")]
    g = d.groupby(["l_returnflag", "l_linestatus"])

    def agg(x):
        disc = x.l_extendedprice * (1 - x.l_discount)
        return pd.Series({
            "sum_qty": x.l_quantity.sum(),
            "sum_base_price": x.l_extendedprice.sum(),
            "sum_disc_price": disc.sum(),
            "sum_charge": (disc * (1 + x.l_tax)).sum(),
            "avg_qty": x.l_quantity.mean(),
            "avg_price": x.l_extendedprice.mean(),
            "avg_disc": x.l_discount.mean(),
            "count_order": len(x),
        })

    return (
        g.apply(agg, include_groups=False)
        .reset_index()
        .sort_values(["l_returnflag", "l_linestatus"])
        .reset_index(drop=True)
    )


def q3(t):
    c = t["customer"]; o = t["orders"]; l = t["lineitem"]
    c = c[c.c_mktsegment == "BUILDING"]
    o = o[o.o_orderdate < _D("1995-03-15")]
    l = l[l.l_shipdate > _D("1995-03-15")]
    j = l.merge(o, left_on="l_orderkey", right_on="o_orderkey").merge(
        c, left_on="o_custkey", right_on="c_custkey"
    )
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    out = (
        j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"]
        .sum()
        .reset_index()[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
        .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
        .head(10)
        .reset_index(drop=True)
    )
    return out


def q5(t):
    c, o, l = t["customer"], t["orders"], t["lineitem"]
    s, n, r = t["supplier"], t["nation"], t["region"]
    r = r[r.r_name == "ASIA"]
    o = o[(o.o_orderdate >= _D("1994-01-01")) & (o.o_orderdate < _D("1995-01-01"))]
    j = (
        l.merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
    )
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(n, left_on="s_nationkey", right_on="n_nationkey").merge(
        r, left_on="n_regionkey", right_on="r_regionkey"
    )
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    return (
        j.groupby("n_name")["revenue"].sum().reset_index()
        .sort_values("revenue", ascending=False).reset_index(drop=True)
    )


def q6(t):
    l = t["lineitem"]
    d = l[
        (l.l_shipdate >= _D("1994-01-01")) & (l.l_shipdate < _D("1995-01-01"))
        & (l.l_discount >= 0.05) & (l.l_discount <= 0.07) & (l.l_quantity < 24)
    ]
    return pd.DataFrame({"revenue": [(d.l_extendedprice * d.l_discount).sum()]})


def q10(t):
    c, o, l, n = t["customer"], t["orders"], t["lineitem"], t["nation"]
    o = o[(o.o_orderdate >= _D("1993-10-01")) & (o.o_orderdate < _D("1994-01-01"))]
    l = l[l.l_returnflag == "R"]
    j = (
        l.merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n, left_on="c_nationkey", right_on="n_nationkey")
    )
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    out = (
        j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"])["revenue"].sum().reset_index()
    )
    out = out[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
               "c_address", "c_phone", "c_comment"]]
    return (
        out.sort_values("revenue", ascending=False).head(20).reset_index(drop=True)
    )


def q12(t):
    o, l = t["orders"], t["lineitem"]
    d = l[
        l.l_shipmode.isin(["MAIL", "SHIP"])
        & (l.l_commitdate < l.l_receiptdate)
        & (l.l_shipdate < l.l_commitdate)
        & (l.l_receiptdate >= _D("1994-01-01"))
        & (l.l_receiptdate < _D("1995-01-01"))
    ]
    j = d.merge(o, left_on="l_orderkey", right_on="o_orderkey")
    high = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    out = (
        j.assign(high=high.astype(int), low=(~high).astype(int))
        .groupby("l_shipmode")[["high", "low"]].sum().reset_index()
        .rename(columns={"high": "high_line_count", "low": "low_line_count"})
        .sort_values("l_shipmode").reset_index(drop=True)
    )
    return out


def q4(t):
    o, l = t["orders"], t["lineitem"]
    o = o[(o.o_orderdate >= _D("1993-07-01")) & (o.o_orderdate < _D("1993-10-01"))]
    late = l[l.l_commitdate < l.l_receiptdate].l_orderkey.unique()
    d = o[o.o_orderkey.isin(late)]
    return (
        d.groupby("o_orderpriority").size().reset_index(name="order_count")
        .sort_values("o_orderpriority").reset_index(drop=True)
    )


def q7(t):
    s, l, o, c, n = (t["supplier"], t["lineitem"], t["orders"], t["customer"],
                     t["nation"])
    l = l[(l.l_shipdate >= _D("1995-01-01")) & (l.l_shipdate <= _D("1996-12-31"))]
    j = (
        l.merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n.add_prefix("n1_"), left_on="s_nationkey",
               right_on="n1_n_nationkey")
        .merge(n.add_prefix("n2_"), left_on="c_nationkey",
               right_on="n2_n_nationkey")
    )
    j = j[
        ((j.n1_n_name == "FRANCE") & (j.n2_n_name == "GERMANY"))
        | ((j.n1_n_name == "GERMANY") & (j.n2_n_name == "FRANCE"))
    ]
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    j["l_year"] = pd.to_datetime(j.l_shipdate).dt.year
    out = (
        j.groupby([j.n1_n_name.rename("supp_nation"),
                   j.n2_n_name.rename("cust_nation"), "l_year"])["volume"]
        .sum().reset_index().rename(columns={"volume": "revenue"})
        .sort_values(["supp_nation", "cust_nation", "l_year"])
        .reset_index(drop=True)
    )
    return out


def q8(t):
    p, s, l, o, c, n, r = (t["part"], t["supplier"], t["lineitem"],
                           t["orders"], t["customer"], t["nation"], t["region"])
    o = o[(o.o_orderdate >= _D("1995-01-01")) & (o.o_orderdate <= _D("1996-12-31"))]
    p = p[p.p_type == "ECONOMY ANODIZED STEEL"]
    j = (
        l.merge(p, left_on="l_partkey", right_on="p_partkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
        .merge(n.add_prefix("n1_"), left_on="c_nationkey",
               right_on="n1_n_nationkey")
        .merge(r, left_on="n1_n_regionkey", right_on="r_regionkey")
        .merge(n.add_prefix("n2_"), left_on="s_nationkey",
               right_on="n2_n_nationkey")
    )
    j = j[j.r_name == "AMERICA"]
    j["o_year"] = pd.to_datetime(j.o_orderdate).dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    j["brazil"] = np.where(j.n2_n_name == "BRAZIL", j.volume, 0.0)
    out = (
        j.groupby("o_year").agg(b=("brazil", "sum"), v=("volume", "sum"))
        .reset_index()
    )
    out["mkt_share"] = out.b / out.v
    return out[["o_year", "mkt_share"]].sort_values("o_year").reset_index(drop=True)


def q9(t):
    p, s, l, ps, o, n = (t["part"], t["supplier"], t["lineitem"],
                         t["partsupp"], t["orders"], t["nation"])
    p = p[p.p_name.str.contains("green")]
    j = (
        l.merge(p, left_on="l_partkey", right_on="p_partkey")
        .merge(s, left_on="l_suppkey", right_on="s_suppkey")
        .merge(ps, left_on=["l_partkey", "l_suppkey"],
               right_on=["ps_partkey", "ps_suppkey"])
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
    )
    j["o_year"] = pd.to_datetime(j.o_orderdate).dt.year
    j["amount"] = (j.l_extendedprice * (1 - j.l_discount)
                   - j.ps_supplycost * j.l_quantity)
    return (
        j.groupby([j.n_name.rename("nation"), "o_year"])["amount"].sum()
        .reset_index().rename(columns={"amount": "sum_profit"})
        .sort_values(["nation", "o_year"], ascending=[True, False])
        .reset_index(drop=True)
    )


def q11(t):
    ps, s, n = t["partsupp"], t["supplier"], t["nation"]
    j = ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey").merge(
        n, left_on="s_nationkey", right_on="n_nationkey"
    )
    j = j[j.n_name == "GERMANY"]
    j["value"] = j.ps_supplycost * j.ps_availqty
    total = j.value.sum() * 0.0001
    out = j.groupby("ps_partkey")["value"].sum().reset_index()
    out = out[out.value > total]
    return out.sort_values("value", ascending=False).reset_index(drop=True)


def q13(t):
    c, o = t["customer"], t["orders"]
    o = o[~o.o_comment.str.contains("special.*requests")]
    counts = (
        c.merge(o, left_on="c_custkey", right_on="o_custkey", how="left")
        .groupby("c_custkey")["o_orderkey"].count().reset_index(name="c_count")
    )
    return (
        counts.groupby("c_count").size().reset_index(name="custdist")
        .sort_values(["custdist", "c_count"], ascending=[False, False])
        .reset_index(drop=True)
    )


def q14(t):
    l, p = t["lineitem"], t["part"]
    l = l[(l.l_shipdate >= _D("1995-09-01")) & (l.l_shipdate < _D("1995-10-01"))]
    j = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev.where(j.p_type.str.startswith("PROMO"), 0.0)
    return pd.DataFrame({"promo_revenue": [100.0 * promo.sum() / rev.sum()]})


def q16(t):
    ps, p, s = t["partsupp"], t["part"], t["supplier"]
    bad = s[s.s_comment.str.contains("Customer.*Complaints")].s_suppkey
    d = p[
        (p.p_brand != "Brand#45")
        & ~p.p_type.str.startswith("MEDIUM POLISHED")
        & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])
    ]
    j = ps.merge(d, left_on="ps_partkey", right_on="p_partkey")
    j = j[~j.ps_suppkey.isin(bad)]
    out = (
        j.groupby(["p_brand", "p_type", "p_size"])["ps_suppkey"].nunique()
        .reset_index(name="supplier_cnt")
        .sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                     ascending=[False, True, True, True])
        .reset_index(drop=True)
    )
    return out


def q18(t):
    c, o, l = t["customer"], t["orders"], t["lineitem"]
    big = l.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > 300].index
    j = (
        l[l.l_orderkey.isin(big)]
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(c, left_on="o_custkey", right_on="c_custkey")
    )
    out = (
        j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"])["l_quantity"].sum()
        .reset_index(name="total_qty")
        .sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True])
        .head(100).reset_index(drop=True)
    )
    return out


def q19(t):
    l, p = t["lineitem"], t["part"]
    j = l.merge(p, left_on="l_partkey", right_on="p_partkey")
    common = j.l_shipmode.isin(["AIR", "AIR REG"]) & (
        j.l_shipinstruct == "DELIVER IN PERSON"
    )
    b1 = (
        (j.p_brand == "Brand#12")
        & j.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
        & (j.l_quantity >= 1) & (j.l_quantity <= 11)
        & (j.p_size >= 1) & (j.p_size <= 5)
    )
    b2 = (
        (j.p_brand == "Brand#23")
        & j.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
        & (j.l_quantity >= 10) & (j.l_quantity <= 20)
        & (j.p_size >= 1) & (j.p_size <= 10)
    )
    b3 = (
        (j.p_brand == "Brand#34")
        & j.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
        & (j.l_quantity >= 20) & (j.l_quantity <= 30)
        & (j.p_size >= 1) & (j.p_size <= 15)
    )
    d = j[common & (b1 | b2 | b3)]
    # SQL: SUM over zero rows is NULL (NaN), not 0
    rev = (d.l_extendedprice * (1 - d.l_discount)).sum() if len(d) else np.nan
    return pd.DataFrame({"revenue": [rev]})


def q22(t):
    c, o = t["customer"], t["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = c[c.c_phone.str[:2].isin(codes)]
    avg_bal = cc[cc.c_acctbal > 0].c_acctbal.mean()
    d = cc[(cc.c_acctbal > avg_bal) & ~cc.c_custkey.isin(o.o_custkey)]
    out = (
        d.assign(cntrycode=d.c_phone.str[:2])
        .groupby("cntrycode")
        .agg(numcust=("c_acctbal", "size"), totacctbal=("c_acctbal", "sum"))
        .reset_index().sort_values("cntrycode").reset_index(drop=True)
    )
    return out


def q2(t):
    p, s, ps, n, r = (t["part"], t["supplier"], t["partsupp"], t["nation"],
                      t["region"])
    europe = (
        ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
        .merge(r, left_on="n_regionkey", right_on="r_regionkey")
    )
    europe = europe[europe.r_name == "EUROPE"]
    mins = europe.groupby("ps_partkey")["ps_supplycost"].min().reset_index(
        name="min_cost"
    )
    d = p[(p.p_size == 15) & p.p_type.str.endswith("BRASS")]
    j = (
        europe.merge(d, left_on="ps_partkey", right_on="p_partkey")
        .merge(mins, on="ps_partkey")
    )
    j = j[j.ps_supplycost == j.min_cost]
    out = j[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
             "s_address", "s_phone", "s_comment"]]
    return (
        out.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                        ascending=[False, True, True, True])
        .head(100).reset_index(drop=True)
    )


def q15(t):
    s, l = t["supplier"], t["lineitem"]
    d = l[(l.l_shipdate >= _D("1996-01-01")) & (l.l_shipdate < _D("1996-04-01"))]
    rev = (
        d.assign(r=d.l_extendedprice * (1 - d.l_discount))
        .groupby("l_suppkey")["r"].sum().reset_index(name="total_revenue")
    )
    top = rev[rev.total_revenue == rev.total_revenue.max()]
    j = s.merge(top, left_on="s_suppkey", right_on="l_suppkey")
    return (
        j[["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]
        .sort_values("s_suppkey").reset_index(drop=True)
    )


def q17(t):
    l, p = t["lineitem"], t["part"]
    d = p[(p.p_brand == "Brand#23") & (p.p_container == "MED BOX")]
    j = l.merge(d, left_on="l_partkey", right_on="p_partkey")
    avg_qty = l.groupby("l_partkey")["l_quantity"].mean().rename("avg_q")
    j = j.join(avg_qty, on="l_partkey")
    j = j[j.l_quantity < 0.2 * j.avg_q]
    val = j.l_extendedprice.sum() / 7.0 if len(j) else np.nan
    return pd.DataFrame({"avg_yearly": [val]})


def q20(t):
    s, n, ps, p, l = (t["supplier"], t["nation"], t["partsupp"], t["part"],
                      t["lineitem"])
    green = p[p.p_name.str.startswith("green")].p_partkey
    d = l[(l.l_shipdate >= _D("1994-01-01")) & (l.l_shipdate < _D("1995-01-01"))]
    qty = (
        d.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum()
        .reset_index(name="sumq")
    )
    j = ps[ps.ps_partkey.isin(green)].merge(
        qty, left_on=["ps_partkey", "ps_suppkey"],
        right_on=["l_partkey", "l_suppkey"],
    )
    good = j[j.ps_availqty > 0.5 * j.sumq].ps_suppkey.unique()
    out = s[s.s_suppkey.isin(good)].merge(
        n, left_on="s_nationkey", right_on="n_nationkey"
    )
    out = out[out.n_name == "CANADA"][["s_name", "s_address"]]
    return out.sort_values("s_name").reset_index(drop=True)


def q21(t):
    s_, l, o, n = t["supplier"], t["lineitem"], t["orders"], t["nation"]
    late = l[l.l_receiptdate > l.l_commitdate]
    # per order: distinct suppliers among all / among late lineitems
    nsupp = l.groupby("l_orderkey")["l_suppkey"].nunique()
    nlate = late.groupby("l_orderkey")["l_suppkey"].nunique()
    j = (
        late.merge(o[o.o_orderstatus == "F"], left_on="l_orderkey",
                   right_on="o_orderkey")
        .merge(s_, left_on="l_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
    )
    j = j[j.n_name == "SAUDI ARABIA"]
    j = j.join(nsupp.rename("nsupp"), on="l_orderkey")
    j = j.join(nlate.rename("nlate"), on="l_orderkey")
    # exists other-supplier lineitem; no other-supplier LATE lineitem
    j = j[(j.nsupp >= 2) & (j.nlate == 1)]
    return (
        j.groupby("s_name").size().reset_index(name="numwait")
        .sort_values(["numwait", "s_name"], ascending=[False, True])
        .head(100).reset_index(drop=True)
    )


ORACLES = {
    "q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5, "q6": q6, "q7": q7,
    "q8": q8, "q9": q9, "q10": q10, "q11": q11, "q12": q12, "q13": q13,
    "q14": q14, "q15": q15, "q16": q16, "q17": q17, "q18": q18, "q19": q19,
    "q20": q20, "q21": q21, "q22": q22,
}
