select
    s_suppkey,
    s_name,
    s_address,
    s_phone,
    total_revenue
from
    supplier,
    (
        select
            l_suppkey as supplier_no,
            sum(l_extendedprice * (1 - l_discount)) as total_revenue
        from
            lineitem
        where
            l_shipdate >= date '1996-01-01'
            and l_shipdate < date '1996-01-01' + interval '3' month
        group by
            l_suppkey
    ) as revenue
where
    s_suppkey = supplier_no
    and total_revenue = (
        select
            max(total_revenue)
        from
            (
                select
                    l_suppkey as supplier_no,
                    sum(l_extendedprice * (1 - l_discount)) as total_revenue
                from
                    lineitem
                where
                    l_shipdate >= date '1996-01-01'
                    and l_shipdate < date '1996-01-01' + interval '3' month
                group by
                    l_suppkey
            ) as revenue0
    )
order by
    s_suppkey;
