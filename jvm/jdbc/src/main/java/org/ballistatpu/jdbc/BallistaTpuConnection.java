/*
 * One Flight channel per JDBC connection. Only the surface a BI tool's
 * read path needs is implemented; everything transactional is a clean
 * SQLFeatureNotSupportedException (the engine is a query engine).
 */
package org.ballistatpu.jdbc;

import org.apache.arrow.flight.FlightClient;
import org.apache.arrow.flight.Location;
import org.apache.arrow.memory.BufferAllocator;
import org.apache.arrow.memory.RootAllocator;

import java.sql.Connection;
import java.sql.DatabaseMetaData;
import java.sql.PreparedStatement;
import java.sql.SQLException;
import java.sql.SQLFeatureNotSupportedException;
import java.sql.Statement;

public final class BallistaTpuConnection implements Connection {
    private final BufferAllocator allocator;
    private final FlightClient client;
    private boolean closed;

    BallistaTpuConnection(String host, int port) {
        this.allocator = new RootAllocator(Long.MAX_VALUE);
        this.client = FlightClient.builder(
                allocator, Location.forGrpcInsecure(host, port)).build();
    }

    FlightClient flightClient() {
        return client;
    }

    BufferAllocator allocator() {
        return allocator;
    }

    @Override
    public Statement createStatement() {
        return new BallistaTpuStatement(this);
    }

    @Override
    public void close() throws SQLException {
        if (closed) {
            return;
        }
        closed = true;
        try {
            client.close();
            allocator.close();
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
            throw new SQLException("interrupted closing Flight channel", e);
        }
    }

    @Override
    public boolean isClosed() {
        return closed;
    }

    @Override
    public boolean isValid(int timeout) {
        return !closed;
    }

    // -- read-only query engine: the rest is boilerplate ------------------

    @Override
    public PreparedStatement prepareStatement(String sql) throws SQLException {
        throw new SQLFeatureNotSupportedException("prepared statements");
    }

    @Override
    public java.sql.CallableStatement prepareCall(String sql) throws SQLException {
        throw new SQLFeatureNotSupportedException("callable statements");
    }

    @Override
    public String nativeSQL(String sql) {
        return sql;
    }

    @Override
    public void setAutoCommit(boolean autoCommit) {
    }

    @Override
    public boolean getAutoCommit() {
        return true;
    }

    @Override
    public void commit() {
    }

    @Override
    public void rollback() {
    }

    @Override
    public DatabaseMetaData getMetaData() throws SQLException {
        throw new SQLFeatureNotSupportedException("metadata");
    }

    @Override
    public void setReadOnly(boolean readOnly) {
    }

    @Override
    public boolean isReadOnly() {
        return true;
    }

    @Override
    public void setCatalog(String catalog) {
    }

    @Override
    public String getCatalog() {
        return "";
    }

    @Override
    public void setTransactionIsolation(int level) {
    }

    @Override
    public int getTransactionIsolation() {
        return TRANSACTION_NONE;
    }

    @Override
    public java.sql.SQLWarning getWarnings() {
        return null;
    }

    @Override
    public void clearWarnings() {
    }

    @Override
    public Statement createStatement(int resultSetType, int resultSetConcurrency) {
        return new BallistaTpuStatement(this);
    }

    @Override
    public PreparedStatement prepareStatement(String sql, int t, int c) throws SQLException {
        throw new SQLFeatureNotSupportedException("prepared statements");
    }

    @Override
    public java.sql.CallableStatement prepareCall(String sql, int t, int c) throws SQLException {
        throw new SQLFeatureNotSupportedException("callable statements");
    }

    @Override
    public java.util.Map<String, Class<?>> getTypeMap() {
        return java.util.Collections.emptyMap();
    }

    @Override
    public void setTypeMap(java.util.Map<String, Class<?>> map) {
    }

    @Override
    public void setHoldability(int holdability) {
    }

    @Override
    public int getHoldability() {
        return java.sql.ResultSet.CLOSE_CURSORS_AT_COMMIT;
    }

    @Override
    public java.sql.Savepoint setSavepoint() throws SQLException {
        throw new SQLFeatureNotSupportedException("savepoints");
    }

    @Override
    public java.sql.Savepoint setSavepoint(String name) throws SQLException {
        throw new SQLFeatureNotSupportedException("savepoints");
    }

    @Override
    public void rollback(java.sql.Savepoint savepoint) throws SQLException {
        throw new SQLFeatureNotSupportedException("savepoints");
    }

    @Override
    public void releaseSavepoint(java.sql.Savepoint savepoint) throws SQLException {
        throw new SQLFeatureNotSupportedException("savepoints");
    }

    @Override
    public Statement createStatement(int t, int c, int h) {
        return new BallistaTpuStatement(this);
    }

    @Override
    public PreparedStatement prepareStatement(String sql, int t, int c, int h)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("prepared statements");
    }

    @Override
    public java.sql.CallableStatement prepareCall(String sql, int t, int c, int h)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("callable statements");
    }

    @Override
    public PreparedStatement prepareStatement(String sql, int autoGeneratedKeys)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("prepared statements");
    }

    @Override
    public PreparedStatement prepareStatement(String sql, int[] columnIndexes)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("prepared statements");
    }

    @Override
    public PreparedStatement prepareStatement(String sql, String[] columnNames)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("prepared statements");
    }

    @Override
    public java.sql.Clob createClob() throws SQLException {
        throw new SQLFeatureNotSupportedException("clob");
    }

    @Override
    public java.sql.Blob createBlob() throws SQLException {
        throw new SQLFeatureNotSupportedException("blob");
    }

    @Override
    public java.sql.NClob createNClob() throws SQLException {
        throw new SQLFeatureNotSupportedException("nclob");
    }

    @Override
    public java.sql.SQLXML createSQLXML() throws SQLException {
        throw new SQLFeatureNotSupportedException("sqlxml");
    }

    @Override
    public void setClientInfo(String name, String value) {
    }

    @Override
    public void setClientInfo(java.util.Properties properties) {
    }

    @Override
    public String getClientInfo(String name) {
        return null;
    }

    @Override
    public java.util.Properties getClientInfo() {
        return new java.util.Properties();
    }

    @Override
    public java.sql.Array createArrayOf(String typeName, Object[] elements)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("arrays");
    }

    @Override
    public java.sql.Struct createStruct(String typeName, Object[] attributes)
            throws SQLException {
        throw new SQLFeatureNotSupportedException("structs");
    }

    @Override
    public void setSchema(String schema) {
    }

    @Override
    public String getSchema() {
        return "";
    }

    @Override
    public void abort(java.util.concurrent.Executor executor) throws SQLException {
        close();
    }

    @Override
    public void setNetworkTimeout(java.util.concurrent.Executor executor, int ms) {
    }

    @Override
    public int getNetworkTimeout() {
        return 0;
    }

    @Override
    public <T> T unwrap(Class<T> iface) throws SQLException {
        if (iface.isInstance(this)) {
            return iface.cast(this);
        }
        throw new SQLException("not a wrapper for " + iface);
    }

    @Override
    public boolean isWrapperFor(Class<?> iface) {
        return iface.isInstance(this);
    }
}
