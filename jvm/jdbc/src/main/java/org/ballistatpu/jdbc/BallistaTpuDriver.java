/*
 * Type-4 JDBC driver for ballista-tpu.
 *
 * URL format: jdbc:ballista-tpu://HOST:PORT
 *
 * The wire contract is Arrow Flight: executeQuery sends the raw SQL
 * bytes as the Ticket of a DoGet and reads the schema-first record-batch
 * stream back (server side: ballista_tpu/distributed/flight.py; the
 * byte exchange is pinned by tests/test_flight.py with a stock pyarrow
 * Flight client, so this driver and that test speak the same protocol).
 */
package org.ballistatpu.jdbc;

import java.sql.Connection;
import java.sql.Driver;
import java.sql.DriverManager;
import java.sql.DriverPropertyInfo;
import java.sql.SQLException;
import java.util.Properties;
import java.util.logging.Logger;

public final class BallistaTpuDriver implements Driver {
    static final String URL_PREFIX = "jdbc:ballista-tpu://";

    static {
        try {
            DriverManager.registerDriver(new BallistaTpuDriver());
        } catch (SQLException e) {
            throw new ExceptionInInitializerError(e);
        }
    }

    @Override
    public Connection connect(String url, Properties info) throws SQLException {
        if (!acceptsURL(url)) {
            return null; // per JDBC spec: not ours
        }
        String hostPort = url.substring(URL_PREFIX.length());
        int slash = hostPort.indexOf('/');
        if (slash >= 0) {
            hostPort = hostPort.substring(0, slash);
        }
        int colon = hostPort.lastIndexOf(':');
        if (colon <= 0) {
            throw new SQLException("URL must be " + URL_PREFIX + "HOST:PORT");
        }
        String host = hostPort.substring(0, colon);
        int port;
        try {
            port = Integer.parseInt(hostPort.substring(colon + 1));
        } catch (NumberFormatException e) {
            throw new SQLException("bad port in URL: " + url, e);
        }
        return new BallistaTpuConnection(host, port);
    }

    @Override
    public boolean acceptsURL(String url) {
        return url != null && url.startsWith(URL_PREFIX);
    }

    @Override
    public DriverPropertyInfo[] getPropertyInfo(String url, Properties info) {
        return new DriverPropertyInfo[0];
    }

    @Override
    public int getMajorVersion() {
        return 0;
    }

    @Override
    public int getMinorVersion() {
        return 2;
    }

    @Override
    public boolean jdbcCompliant() {
        return false;
    }

    @Override
    public Logger getParentLogger() {
        return Logger.getLogger("org.ballistatpu.jdbc");
    }
}
