/*
 * Forward-only cursor over a FlightStream: each Arrow record batch is a
 * window of rows; next() walks rows then advances the stream. Column
 * access covers the engine's result types (int64, float64, utf8, date32,
 * timestamp, bool) via Arrow's FieldReader, so no per-type vector
 * casting is needed here.
 */
package org.ballistatpu.jdbc;

import org.apache.arrow.flight.FlightStream;
import org.apache.arrow.vector.VectorSchemaRoot;
import org.apache.arrow.vector.complex.reader.FieldReader;

import java.math.BigDecimal;
import java.sql.Date;
import java.sql.ResultSet;
import java.sql.ResultSetMetaData;
import java.sql.SQLException;
import java.sql.SQLFeatureNotSupportedException;
import java.sql.Statement;
import java.sql.Timestamp;

public final class BallistaTpuResultSet implements ResultSet {
    private final BallistaTpuStatement statement;
    private final FlightStream stream;
    private VectorSchemaRoot root;
    private int rowInBatch = -1;
    private boolean closed;
    private boolean lastWasNull;

    BallistaTpuResultSet(BallistaTpuStatement statement, FlightStream stream) {
        this.statement = statement;
        this.stream = stream;
    }

    @Override
    public boolean next() throws SQLException {
        checkOpen();
        while (true) {
            if (root != null && rowInBatch + 1 < root.getRowCount()) {
                rowInBatch++;
                return true;
            }
            if (!stream.next()) {
                return false;
            }
            root = stream.getRoot();
            rowInBatch = -1;
        }
    }

    private FieldReader reader(int columnIndex) throws SQLException {
        checkOpen();
        if (root == null) {
            throw new SQLException("call next() first");
        }
        if (columnIndex < 1 || columnIndex > root.getFieldVectors().size()) {
            throw new SQLException("bad column index " + columnIndex);
        }
        FieldReader r = root.getVector(columnIndex - 1).getReader();
        r.setPosition(rowInBatch);
        lastWasNull = !r.isSet();
        return r;
    }

    @Override
    public int findColumn(String columnLabel) throws SQLException {
        checkOpen();
        if (root == null) {
            throw new SQLException("call next() first");
        }
        var fields = root.getSchema().getFields();
        for (int i = 0; i < fields.size(); i++) {
            if (fields.get(i).getName().equalsIgnoreCase(columnLabel)) {
                return i + 1;
            }
        }
        throw new SQLException("no such column: " + columnLabel);
    }

    @Override
    public boolean wasNull() {
        return lastWasNull;
    }

    @Override
    public String getString(int columnIndex) throws SQLException {
        Object v = getObject(columnIndex);
        return v == null ? null : v.toString();
    }

    @Override
    public long getLong(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        if (v == null) {
            return 0;
        }
        return ((Number) v).longValue();
    }

    @Override
    public int getInt(int columnIndex) throws SQLException {
        return (int) getLong(columnIndex);
    }

    @Override
    public double getDouble(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        if (v == null) {
            return 0.0;
        }
        return ((Number) v).doubleValue();
    }

    @Override
    public float getFloat(int columnIndex) throws SQLException {
        return (float) getDouble(columnIndex);
    }

    @Override
    public boolean getBoolean(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        return v != null && (v instanceof Boolean ? (Boolean) v
                : ((Number) v).longValue() != 0);
    }

    @Override
    public BigDecimal getBigDecimal(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        if (v == null) {
            return null;
        }
        if (v instanceof BigDecimal) {
            return (BigDecimal) v;
        }
        return new BigDecimal(v.toString());
    }

    @Override
    public Date getDate(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        if (v == null) {
            return null;
        }
        if (v instanceof java.time.LocalDate) {
            return Date.valueOf((java.time.LocalDate) v);
        }
        if (v instanceof Number) { // date32: days since epoch
            return new Date(((Number) v).longValue() * 86_400_000L);
        }
        return Date.valueOf(v.toString());
    }

    @Override
    public Timestamp getTimestamp(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        if (v == null) {
            return null;
        }
        if (v instanceof java.time.LocalDateTime) {
            return Timestamp.valueOf((java.time.LocalDateTime) v);
        }
        return Timestamp.valueOf(v.toString());
    }

    @Override
    public Object getObject(int columnIndex) throws SQLException {
        Object v = reader(columnIndex).readObject();
        return v == null ? null : (v instanceof org.apache.arrow.vector.util.Text
                ? v.toString() : v);
    }

    @Override
    public String getString(String columnLabel) throws SQLException {
        return getString(findColumn(columnLabel));
    }

    @Override
    public long getLong(String columnLabel) throws SQLException {
        return getLong(findColumn(columnLabel));
    }

    @Override
    public int getInt(String columnLabel) throws SQLException {
        return getInt(findColumn(columnLabel));
    }

    @Override
    public double getDouble(String columnLabel) throws SQLException {
        return getDouble(findColumn(columnLabel));
    }

    @Override
    public float getFloat(String columnLabel) throws SQLException {
        return getFloat(findColumn(columnLabel));
    }

    @Override
    public boolean getBoolean(String columnLabel) throws SQLException {
        return getBoolean(findColumn(columnLabel));
    }

    @Override
    public BigDecimal getBigDecimal(String columnLabel) throws SQLException {
        return getBigDecimal(findColumn(columnLabel));
    }

    @Override
    public Date getDate(String columnLabel) throws SQLException {
        return getDate(findColumn(columnLabel));
    }

    @Override
    public Timestamp getTimestamp(String columnLabel) throws SQLException {
        return getTimestamp(findColumn(columnLabel));
    }

    @Override
    public Object getObject(String columnLabel) throws SQLException {
        return getObject(findColumn(columnLabel));
    }

    @Override
    public void close() throws SQLException {
        if (closed) {
            return;
        }
        closed = true;
        try {
            stream.close();
        } catch (Exception e) {
            throw new SQLException("closing flight stream", e);
        }
    }

    @Override
    public boolean isClosed() {
        return closed;
    }

    @Override
    public Statement getStatement() {
        return statement;
    }

    @Override
    public ResultSetMetaData getMetaData() throws SQLException {
        throw new SQLFeatureNotSupportedException("metadata");
    }

    private void checkOpen() throws SQLException {
        if (closed) {
            throw new SQLException("result set is closed");
        }
    }

    // -- unsupported JDBC surface ------------------------------------------

    private static SQLException unsupported(String what) {
        return new SQLFeatureNotSupportedException(what);
    }

    @Override
    public byte getByte(int i) throws SQLException {
        return (byte) getLong(i);
    }

    @Override
    public short getShort(int i) throws SQLException {
        return (short) getLong(i);
    }

    @Override
    public byte[] getBytes(int i) throws SQLException {
        throw unsupported("bytes");
    }

    @Override
    public java.sql.Time getTime(int i) throws SQLException {
        throw unsupported("time");
    }

    @Override
    public java.io.InputStream getAsciiStream(int i) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    @Deprecated
    public java.io.InputStream getUnicodeStream(int i) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public java.io.InputStream getBinaryStream(int i) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public byte getByte(String l) throws SQLException {
        return getByte(findColumn(l));
    }

    @Override
    public short getShort(String l) throws SQLException {
        return getShort(findColumn(l));
    }

    @Override
    public byte[] getBytes(String l) throws SQLException {
        throw unsupported("bytes");
    }

    @Override
    public java.sql.Time getTime(String l) throws SQLException {
        throw unsupported("time");
    }

    @Override
    public java.io.InputStream getAsciiStream(String l) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    @Deprecated
    public java.io.InputStream getUnicodeStream(String l) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public java.io.InputStream getBinaryStream(String l) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public java.sql.SQLWarning getWarnings() {
        return null;
    }

    @Override
    public void clearWarnings() {
    }

    @Override
    public String getCursorName() throws SQLException {
        throw unsupported("cursor name");
    }

    @Override
    @Deprecated
    public BigDecimal getBigDecimal(int i, int scale) throws SQLException {
        return getBigDecimal(i);
    }

    @Override
    @Deprecated
    public BigDecimal getBigDecimal(String l, int scale) throws SQLException {
        return getBigDecimal(l);
    }

    @Override
    public boolean isBeforeFirst() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean isAfterLast() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean isFirst() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean isLast() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public void beforeFirst() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public void afterLast() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean first() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean last() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public int getRow() {
        return 0;
    }

    @Override
    public boolean absolute(int row) throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean relative(int rows) throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public boolean previous() throws SQLException {
        throw unsupported("scrolling");
    }

    @Override
    public void setFetchDirection(int direction) {
    }

    @Override
    public int getFetchDirection() {
        return FETCH_FORWARD;
    }

    @Override
    public void setFetchSize(int rows) {
    }

    @Override
    public int getFetchSize() {
        return 0;
    }

    @Override
    public int getType() {
        return TYPE_FORWARD_ONLY;
    }

    @Override
    public int getConcurrency() {
        return CONCUR_READ_ONLY;
    }

    @Override
    public boolean rowUpdated() {
        return false;
    }

    @Override
    public boolean rowInserted() {
        return false;
    }

    @Override
    public boolean rowDeleted() {
        return false;
    }

    // update surface: single consolidated refusal (read-only engine)
    @Override
    public void updateNull(int i) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBoolean(int i, boolean x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateByte(int i, byte x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateShort(int i, short x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateInt(int i, int x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateLong(int i, long x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateFloat(int i, float x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateDouble(int i, double x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBigDecimal(int i, BigDecimal x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateString(int i, String x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBytes(int i, byte[] x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateDate(int i, Date x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateTime(int i, java.sql.Time x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateTimestamp(int i, Timestamp x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateAsciiStream(int i, java.io.InputStream x, int l) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBinaryStream(int i, java.io.InputStream x, int l) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateCharacterStream(int i, java.io.Reader x, int l) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateObject(int i, Object x, int s) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateObject(int i, Object x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNull(String l) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBoolean(String l, boolean x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateByte(String l, byte x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateShort(String l, short x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateInt(String l, int x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateLong(String l, long x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateFloat(String l, float x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateDouble(String l, double x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBigDecimal(String l, BigDecimal x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateString(String l, String x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBytes(String l, byte[] x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateDate(String l, Date x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateTime(String l, java.sql.Time x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateTimestamp(String l, Timestamp x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateAsciiStream(String l, java.io.InputStream x, int n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBinaryStream(String l, java.io.InputStream x, int n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateCharacterStream(String l, java.io.Reader r, int n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateObject(String l, Object x, int s) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateObject(String l, Object x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void insertRow() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateRow() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void deleteRow() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void refreshRow() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void cancelRowUpdates() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void moveToInsertRow() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void moveToCurrentRow() throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public Object getObject(int i, java.util.Map<String, Class<?>> map) throws SQLException {
        return getObject(i);
    }

    @Override
    public java.sql.Ref getRef(int i) throws SQLException {
        throw unsupported("ref");
    }

    @Override
    public java.sql.Blob getBlob(int i) throws SQLException {
        throw unsupported("blob");
    }

    @Override
    public java.sql.Clob getClob(int i) throws SQLException {
        throw unsupported("clob");
    }

    @Override
    public java.sql.Array getArray(int i) throws SQLException {
        throw unsupported("array");
    }

    @Override
    public Object getObject(String l, java.util.Map<String, Class<?>> map) throws SQLException {
        return getObject(l);
    }

    @Override
    public java.sql.Ref getRef(String l) throws SQLException {
        throw unsupported("ref");
    }

    @Override
    public java.sql.Blob getBlob(String l) throws SQLException {
        throw unsupported("blob");
    }

    @Override
    public java.sql.Clob getClob(String l) throws SQLException {
        throw unsupported("clob");
    }

    @Override
    public java.sql.Array getArray(String l) throws SQLException {
        throw unsupported("array");
    }

    @Override
    public Date getDate(int i, java.util.Calendar cal) throws SQLException {
        return getDate(i);
    }

    @Override
    public Date getDate(String l, java.util.Calendar cal) throws SQLException {
        return getDate(l);
    }

    @Override
    public java.sql.Time getTime(int i, java.util.Calendar cal) throws SQLException {
        throw unsupported("time");
    }

    @Override
    public java.sql.Time getTime(String l, java.util.Calendar cal) throws SQLException {
        throw unsupported("time");
    }

    @Override
    public Timestamp getTimestamp(int i, java.util.Calendar cal) throws SQLException {
        return getTimestamp(i);
    }

    @Override
    public Timestamp getTimestamp(String l, java.util.Calendar cal) throws SQLException {
        return getTimestamp(l);
    }

    @Override
    public java.net.URL getURL(int i) throws SQLException {
        throw unsupported("url");
    }

    @Override
    public java.net.URL getURL(String l) throws SQLException {
        throw unsupported("url");
    }

    @Override
    public void updateRef(int i, java.sql.Ref x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateRef(String l, java.sql.Ref x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBlob(int i, java.sql.Blob x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBlob(String l, java.sql.Blob x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateClob(int i, java.sql.Clob x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateClob(String l, java.sql.Clob x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateArray(int i, java.sql.Array x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateArray(String l, java.sql.Array x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public java.sql.RowId getRowId(int i) throws SQLException {
        throw unsupported("rowid");
    }

    @Override
    public java.sql.RowId getRowId(String l) throws SQLException {
        throw unsupported("rowid");
    }

    @Override
    public void updateRowId(int i, java.sql.RowId x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateRowId(String l, java.sql.RowId x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public int getHoldability() {
        return CLOSE_CURSORS_AT_COMMIT;
    }

    @Override
    public void updateNString(int i, String s) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNString(String l, String s) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNClob(int i, java.sql.NClob c) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNClob(String l, java.sql.NClob c) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public java.sql.NClob getNClob(int i) throws SQLException {
        throw unsupported("nclob");
    }

    @Override
    public java.sql.NClob getNClob(String l) throws SQLException {
        throw unsupported("nclob");
    }

    @Override
    public java.sql.SQLXML getSQLXML(int i) throws SQLException {
        throw unsupported("sqlxml");
    }

    @Override
    public java.sql.SQLXML getSQLXML(String l) throws SQLException {
        throw unsupported("sqlxml");
    }

    @Override
    public void updateSQLXML(int i, java.sql.SQLXML x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateSQLXML(String l, java.sql.SQLXML x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public String getNString(int i) throws SQLException {
        return getString(i);
    }

    @Override
    public String getNString(String l) throws SQLException {
        return getString(l);
    }

    @Override
    public java.io.Reader getNCharacterStream(int i) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public java.io.Reader getNCharacterStream(String l) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public java.io.Reader getCharacterStream(int i) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public java.io.Reader getCharacterStream(String l) throws SQLException {
        throw unsupported("streams");
    }

    @Override
    public void updateNCharacterStream(int i, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNCharacterStream(String l, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateAsciiStream(int i, java.io.InputStream x, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBinaryStream(int i, java.io.InputStream x, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateCharacterStream(int i, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateAsciiStream(String l, java.io.InputStream x, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBinaryStream(String l, java.io.InputStream x, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateCharacterStream(String l, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBlob(int i, java.io.InputStream s, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBlob(String l, java.io.InputStream s, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateClob(int i, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateClob(String l, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNClob(int i, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNClob(String l, java.io.Reader r, long n) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNCharacterStream(int i, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNCharacterStream(String l, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateAsciiStream(int i, java.io.InputStream x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBinaryStream(int i, java.io.InputStream x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateCharacterStream(int i, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateAsciiStream(String l, java.io.InputStream x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBinaryStream(String l, java.io.InputStream x) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateCharacterStream(String l, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBlob(int i, java.io.InputStream s) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateBlob(String l, java.io.InputStream s) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateClob(int i, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateClob(String l, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNClob(int i, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public void updateNClob(String l, java.io.Reader r) throws SQLException {
        throw unsupported("updates");
    }

    @Override
    public <T> T getObject(int i, Class<T> type) throws SQLException {
        return type.cast(getObject(i));
    }

    @Override
    public <T> T getObject(String l, Class<T> type) throws SQLException {
        return type.cast(getObject(l));
    }

    @Override
    public <T> T unwrap(Class<T> iface) throws SQLException {
        if (iface.isInstance(this)) {
            return iface.cast(this);
        }
        throw new SQLException("not a wrapper for " + iface);
    }

    @Override
    public boolean isWrapperFor(Class<?> iface) {
        return iface.isInstance(this);
    }
}
