"""Distributed query profiler + flight recorder (ISSUE 7).

Covers: the always-on flight-recorder ring (bounds, disable knob,
survival across trace reconfiguration), per-task profile window capture
(flow-matched, identity-tagged, bounded), the retroactive slow-query
dump, the scheduler-merged per-job artifact on a real LocalCluster q5
run (scheduler + >=2 executor process tracks, task flow arrows, Gantt
lane, cluster-aggregated named lanes), the ``/debug/profile/<job_id>``
endpoint + enriched ``/debug/queries`` slow entries (plan digest +
artifact path), remote ``df.profile()``, the bench-regression checker's
self-test, and the flight-recorder <5% warm-q1 overhead gate
(drift-cancelling scheme, same as PRs 1/5)."""

import json
import os
import subprocess
import sys
import time

import pytest

from ballista_tpu.client import BallistaContext
from ballista_tpu.datatypes import Int64, Utf8, schema
from ballista_tpu.observability import tracing as obs_tracing
from ballista_tpu.observability.export import LANE_NAMES

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture
def clean_env():
    keys = ("BALLISTA_TRACE", "BALLISTA_TRACE_FILE", "BALLISTA_TRACE_DIR",
            "BALLISTA_TRACE_TRUNCATE", "BALLISTA_TRACE_MAX_MB",
            "BALLISTA_PROFILE", "BALLISTA_SLOW_QUERY_SECS",
            "BALLISTA_SLOW_QUERY_DIR", "BALLISTA_METRICS_PORT",
            "BALLISTA_FLIGHT_RECORDER", "BALLISTA_FLIGHT_RECORDER_SPANS",
            "BALLISTA_TASK_PROFILE")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    obs_tracing.reconfigure()


def _proc_tracks(art: dict) -> list:
    return [e["args"]["name"] for e in art["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"]


# ---------------------------------------------------------------------------
# (a) flight recorder: ring bounds, disable, reconfigure survival
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_bounds(clean_env):
    os.environ["BALLISTA_FLIGHT_RECORDER_SPANS"] = "32"
    os.environ.pop("BALLISTA_TRACE", None)
    obs_tracing.reconfigure()
    from ballista_tpu.observability import trace_span

    assert obs_tracing.flight_recorder_enabled()
    for i in range(100):
        with trace_span("ring.spam", i=i):
            pass
    recs = obs_tracing.ring_records()
    # bounded at the configured capacity, keeping the MOST RECENT spans
    assert len(recs) == 32
    assert [r["i"] for r in recs] == list(range(68, 100))
    # filters: task/job narrow the scan
    with obs_tracing.flow(job="jz", task="jz/0/0"):
        with trace_span("ring.flowed"):
            pass
    assert [r["name"] for r in obs_tracing.ring_records(job="jz")] == \
        ["ring.flowed"]
    assert obs_tracing.ring_records(task="jz/0/0")[0]["job"] == "jz"


def test_flight_recorder_disable_and_survival(clean_env):
    os.environ["BALLISTA_FLIGHT_RECORDER"] = "0"
    obs_tracing.reconfigure()
    from ballista_tpu.observability import trace_span

    assert not obs_tracing.flight_recorder_enabled()
    with trace_span("ring.off"):
        pass
    assert obs_tracing.ring_records() == []
    # back on: the ring survives a trace-FILE reconfiguration (the
    # profiler reconfigures at window start/stop; the retroactive dump
    # depends on history surviving that)
    os.environ.pop("BALLISTA_FLIGHT_RECORDER", None)
    obs_tracing.reconfigure()
    with trace_span("ring.kept"):
        pass
    os.environ["BALLISTA_TRACE_TRUNCATE"] = "1"
    obs_tracing.reconfigure()
    names = [r["name"] for r in obs_tracing.ring_records()]
    assert "ring.kept" in names


def test_capture_task_profile_window(clean_env, monkeypatch):
    obs_tracing.reconfigure()
    from ballista_tpu.observability import distributed as obs_dist
    from ballista_tpu.observability import flow, trace_span

    t0 = time.time()
    with flow(job="jx", stage=3, task="jx/3/1"):
        with trace_span("executor.task", task="jx/3/1"):
            with trace_span("device.block", what="test"):
                pass
        # the scheduler's dispatch span carries the same task attr but
        # belongs to the scheduler's window, not the task's
        with trace_span("scheduler.task_dispatch", task="jx/3/1"):
            pass
    with flow(job="jx", task="jx/3/0"):
        with trace_span("executor.task", task="jx/3/0"):
            pass
    prof = obs_dist.capture_task_profile(
        "jx/3/1", t0, 0.5, "deadbeefcafe", phases0={}, compile0={})
    names = sorted(r["name"] for r in prof["records"])
    assert names == ["device.block", "executor.task"]
    # identity FORCE-tagged (in-process clusters share one ring whose
    # process-level identity may belong to another component)
    assert all(r["exec"] == "deadbeef" and r["role"] == "executor"
               for r in prof["records"])
    assert prof["executor_id"] == "deadbeef"
    assert prof["wall_seconds"] == 0.5
    assert "memory" in prof and "rss_bytes" in prof["memory"]
    # bounded: past the record cap the payload truncates, never grows
    monkeypatch.setattr(obs_dist, "TASK_PROFILE_MAX_RECORDS", 3)
    t1 = time.time()
    with flow(task="jx/9/9"):
        for i in range(10):
            with trace_span("device.block", i=i):
                pass
    prof = obs_dist.capture_task_profile("jx/9/9", t1, 0.1, "aa")
    assert len(prof["records"]) == 3
    assert prof["records_truncated"] == 7


def test_retroactive_slow_query_dump(clean_env, tmp_path):
    out_dir = tmp_path / "slow"
    os.environ["BALLISTA_SLOW_QUERY_SECS"] = "0.0"
    os.environ["BALLISTA_SLOW_QUERY_DIR"] = str(out_dir)
    os.environ.pop("BALLISTA_PROFILE", None)
    obs_tracing.reconfigure()
    ctx = BallistaContext.standalone()
    ctx.register_memtable(
        "t", schema(("k", Utf8), ("a", Int64)),
        {"k": ["x", "y"] * 10, "a": list(range(20))})
    out = ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k ORDER BY k"
                  ).collect()
    assert list(out["s"]) == [90, 100]
    files = list(out_dir.glob("ballista-profile-*.json"))
    # the query ran UNPROFILED; the artifact is retroactive, from the
    # flight recorder
    assert len(files) == 1
    art = json.load(open(files[0]))
    assert art["label"].startswith("slow-query-")
    assert art.get("flight_recorder") is True
    assert set(art["lanes"]) == set(LANE_NAMES)
    assert art["traceEvents"]


# ---------------------------------------------------------------------------
# (b) cluster path: merged per-job artifact (the acceptance gate)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tpch_small(tmp_path_factory):
    from benchmarks.tpch import datagen

    data_dir = str(tmp_path_factory.mktemp("tpch_dprof"))
    datagen.generate(data_dir, scale=0.01, num_parts=2)
    return data_dir


def test_cluster_q5_merged_artifact(clean_env, tpch_small, tmp_path):
    """A LocalCluster q5 run under BALLISTA_PROFILE yields exactly ONE
    merged artifact: valid Chrome-trace JSON with the scheduler track,
    >=2 executor process tracks, task flow arrows from
    scheduler.task_dispatch into executor.task, a stage/task Gantt
    lane, and the cluster-aggregated named lanes."""
    from benchmarks.tpch.schema_def import register_tpch
    from ballista_tpu.distributed.executor import LocalCluster

    out_dir = tmp_path / "profiles"
    os.environ["BALLISTA_PROFILE"] = str(out_dir)
    obs_tracing.reconfigure()
    cluster = LocalCluster(num_executors=2, concurrent_tasks=1,
                           metrics_port=0)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        register_tpch(ctx, tpch_small, "tbl")
        sql = open(os.path.join(REPO, "benchmarks", "tpch", "queries",
                                "q5.sql")).read()
        out = ctx.sql(sql).collect()
        assert len(out) > 0
        # completion is published to the client before the terminal
        # hook writes the artifact — poll briefly (before shutdown, so
        # the scheduler is still alive to finish the write)
        deadline = time.time() + 30
        files = []
        while time.time() < deadline and not files:
            files = list(out_dir.glob("ballista-profile-job-*.json"))
            if not files:
                time.sleep(0.2)
    finally:
        cluster.shutdown()
    assert len(files) == 1, files  # exactly one merged artifact per job
    art = json.load(open(files[0]))
    from tests.test_profiler_health import _validate_chrome_trace

    _validate_chrome_trace(art)
    tracks = _proc_tracks(art)
    assert any(t.startswith("scheduler") for t in tracks), tracks
    exec_tracks = [t for t in tracks if t.startswith("executor ")]
    assert len(exec_tracks) >= 2, tracks
    assert "job timeline (stage/task gantt)" in tracks
    # flow arrows pair dispatch -> task
    flows = [e for e in art["traceEvents"] if e.get("ph") in ("s", "f")]
    assert flows and len(flows) % 2 == 0
    starts = {e["id"] for e in flows if e["ph"] == "s"}
    finishes = {e["id"] for e in flows if e["ph"] == "f"}
    assert starts == finishes
    # gantt slices exist per executed task
    gantt = [e for e in art["traceEvents"] if e.get("cat") == "gantt"]
    tasks = [e for e in art["traceEvents"]
             if e.get("ph") == "X" and e["name"] == "executor.task"]
    assert len(gantt) == len(tasks) >= 2
    # cluster-aggregated lanes: q5 joins dictionary-encoded strings and
    # compiles kernels cold — the measured lanes must hold real time
    assert set(art["lanes"]) == set(LANE_NAMES)
    assert art["lanes"]["compile_trace_lower"] > 0
    assert 0.0 < art["attributed_fraction"] <= 1.0
    dist = art["distributed"]
    assert dist["num_task_profiles"] >= 2
    assert len(dist["executors"]) >= 2
    assert dist.get("plan_digest")


def test_debug_profile_endpoint_and_slow_entries(clean_env, tmp_path):
    """Cluster slow-query flight recorder: with only
    BALLISTA_SLOW_QUERY_SECS set (no ambient profiling), a slow job
    dumps its merged artifact, /debug/queries carries the plan digest +
    artifact path, and /debug/profile/<job_id> serves the artifact."""
    from ballista_tpu.distributed.executor import LocalCluster
    from tests.procutil import http_get

    os.environ["BALLISTA_SLOW_QUERY_SECS"] = "0.0"
    os.environ["BALLISTA_SLOW_QUERY_DIR"] = str(tmp_path / "slow")
    os.environ.pop("BALLISTA_PROFILE", None)
    obs_tracing.reconfigure()
    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("k,a\n")
        for i in range(30):
            f.write(f"{'xy'[i % 2]},{i}\n")
    cluster = LocalCluster(num_executors=2, metrics_port=0)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_csv("t", str(csv), schema(("k", Utf8), ("a", Int64)))
        ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k").collect()
        sport = cluster.scheduler_health_port
        # the slow entry lands at the terminal transition but its
        # artifact path is attached by the background build worker —
        # wait for the entry to carry it
        deadline = time.time() + 30
        jobs = []
        while time.time() < deadline and not jobs:
            dbg = json.loads(http_get(sport, "/debug/queries"))
            jobs = [q for q in dbg["slow_queries"]
                    if "job_id" in q and q.get("profile_artifact")]
            if not jobs:
                time.sleep(0.2)
        assert jobs, dbg["slow_queries"]
        entry = jobs[-1]
        # the bugfix: slow entries are diagnosable after the fact —
        # WHAT ran (plan digest) and the evidence (artifact path)
        assert entry.get("plan_digest")
        assert entry.get("profile_artifact")
        assert os.path.exists(entry["profile_artifact"])
        art = json.load(open(entry["profile_artifact"]))
        assert art["distributed"]["job_id"] == entry["job_id"]
        # the endpoint serves the same job's artifact
        served = json.loads(http_get(
            sport, f"/debug/profile/{entry['job_id']}"))
        assert served["distributed"]["job_id"] == entry["job_id"]
        assert set(served["lanes"]) == set(LANE_NAMES)
        # unknown job -> 404, not a crash
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            http_get(sport, "/debug/profile/nope123")
        # lane + stage histograms exported through the registry gate
        mtext = http_get(sport, "/metrics")
        assert "ballista_query_lane_seconds_bucket{" in mtext
        assert 'ballista_stage_seconds_bucket{le=' in mtext
        assert "ballista_query_lane_seconds_count{" in mtext
    finally:
        cluster.shutdown()


def test_remote_df_profile(clean_env, tmp_path):
    """df.profile() works identically on the cluster path: the query
    runs through the cluster and the scheduler-merged artifact is
    fetched over GetJobProfile and written locally."""
    from ballista_tpu.distributed.executor import LocalCluster

    obs_tracing.reconfigure()
    csv = tmp_path / "t.csv"
    with open(csv, "w") as f:
        f.write("k,a\n")
        for i in range(24):
            f.write(f"{'pq'[i % 2]},{i}\n")
    cluster = LocalCluster(num_executors=2, metrics_port=-1)
    try:
        ctx = BallistaContext.remote("localhost", cluster.port)
        ctx.register_csv("t", str(csv), schema(("k", Utf8), ("a", Int64)))
        df = ctx.sql("SELECT k, sum(a) AS s FROM t GROUP BY k")
        path = df.profile(path=str(tmp_path / "remote-art.json"),
                          label="remote-q")
        art = json.load(open(path))
        assert art["label"] == "remote-q"
        assert art["distributed"]["num_task_profiles"] >= 1
        tracks = _proc_tracks(art)
        assert any(t.startswith("scheduler") for t in tracks)
        assert any(t.startswith("executor ") for t in tracks)
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# (c) bench regression checker + overhead gate
# ---------------------------------------------------------------------------


def test_check_bench_regress_self_test():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "dev",
                                      "check_bench_regress.py"),
         "--self-test"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_check_bench_regress_detects(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({"warm_seconds": 1.0, "value": 1000.0})
                   + "\n")
    new.write_text(json.dumps({"warm_seconds": 3.0, "value": 1000.0})
                   + "\n")
    script = os.path.join(REPO, "dev", "check_bench_regress.py")
    r = subprocess.run([sys.executable, script, str(old), str(new)],
                       capture_output=True, text=True)
    assert r.returncode == 1 and "REGRESSED" in r.stdout, r.stdout
    r = subprocess.run([sys.executable, script, str(new), str(old)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_flight_recorder_overhead_q1_under_5pct(tmp_path_factory,
                                                clean_env):
    """Warm q1 with the always-on flight recorder (ring appends on
    every span, no trace file) stays within 5% of recorder-off — the
    drift-cancelling scheme from the PR 1/5 gates (alternating
    interleaved samples, medians, retries)."""
    from benchmarks.tpch import datagen
    from benchmarks.tpch.schema_def import register_tpch

    data_dir = str(tmp_path_factory.mktemp("tpch_fr"))
    datagen.generate(data_dir, scale=0.01, num_parts=1)
    ctx = BallistaContext.standalone()
    register_tpch(ctx, data_dir, "tbl")
    qdir = os.path.join(REPO, "benchmarks", "tpch", "queries")
    df = ctx.sql(open(os.path.join(qdir, "q1.sql")).read())
    df.collect()  # warm: jit compile + table caches

    def set_enabled(on: bool):
        if on:
            os.environ.pop("BALLISTA_FLIGHT_RECORDER", None)
        else:
            os.environ["BALLISTA_FLIGHT_RECORDER"] = "0"
        os.environ.pop("BALLISTA_TRACE", None)
        obs_tracing.reconfigure()

    def sample(on: bool):
        set_enabled(on)
        t0 = time.perf_counter()
        for _ in range(3):
            df.collect()
        return time.perf_counter() - t0

    try:
        sample(True)
        sample(False)

        def measure():
            offs, ons = [], []
            for i in range(9):
                if i % 2 == 0:
                    offs.append(sample(False))
                    ons.append(sample(True))
                else:
                    ons.append(sample(True))
                    offs.append(sample(False))
            return sorted(offs)[4], sorted(ons)[4]

        for _attempt in range(3):
            t_off, t_on = measure()
            if t_on <= t_off * 1.05 + 2e-3:
                return
        overhead = (t_on - t_off) / t_off
        raise AssertionError(
            f"flight recorder overhead {overhead:.1%} "
            f"(on={t_on:.4f}s off={t_off:.4f}s)")
    finally:
        set_enabled(True)
