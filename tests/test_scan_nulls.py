"""SQL NULLs in delimited scans: empty non-string fields must surface as
validity=False (not silently 0 / 1970-01-01), identically through the
native C++ scanner and the pandas fallback (round-3 advisor finding,
ballista_tpu/native/tblscan.cpp tbl_fill_valid). The parquet source must
follow the same convention (round-4 finding: its chunk loop never passed
``validity=`` to ``ColumnBatch.from_numpy``, so parquet NULLs decoded as
garbage values with no mask)."""

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Decimal, Date32, Utf8
from ballista_tpu.io import TblSource
from ballista_tpu.io import native


SCHEMA = schema(
    ("k", Utf8), ("a", Int64), ("d", Decimal(2)), ("dt", Date32),
)

ROWS = [
    "x|1|1.50|1994-01-01|",
    "y||2.25|1994-01-02|",       # a NULL
    "|3||1994-01-03|",           # k empty (utf8 VALUE, not null), d NULL
    "z|4|4.00||",                # dt NULL
]


def _write(tmp_path):
    f = tmp_path / "t.tbl"
    f.write_text("\n".join(ROWS) + "\n")
    return str(f)


def _scan(path, use_native, monkeypatch):
    src = TblSource(path, SCHEMA)
    if not use_native:
        monkeypatch.setattr(
            type(src), "_use_native", lambda self: False)
    elif not native.available():
        pytest.skip("native scanner not built")
    batches = list(src.scan(0))
    assert len(batches) == 1
    return batches[0]


@pytest.mark.parametrize("use_native", [True, False])
def test_empty_fields_scan_as_nulls(tmp_path, use_native, monkeypatch):
    b = _scan(_write(tmp_path), use_native, monkeypatch)
    assert int(b.num_rows) == 4

    a = b.column("a")
    assert a.validity is not None
    np.testing.assert_array_equal(
        np.asarray(a.validity)[:4], [True, False, True, True])

    d = b.column("d")
    assert d.validity is not None
    np.testing.assert_array_equal(
        np.asarray(d.validity)[:4], [True, True, False, True])

    dt = b.column("dt")
    assert dt.validity is not None
    np.testing.assert_array_equal(
        np.asarray(dt.validity)[:4], [True, True, True, False])

    # utf8: "" is a value, never NULL
    k = b.column("k")
    assert k.validity is None
    decoded = k.to_numpy_logical(np.asarray(b.selection))
    np.testing.assert_array_equal(decoded, ["x", "y", "", "z"])

    # all-valid columns skip the bitmap entirely (wire/memory economy)
    valid_vals = a.to_numpy_logical(np.asarray(b.selection))
    np.testing.assert_array_equal(valid_vals[[0, 2, 3]], [1, 3, 4])


@pytest.mark.parametrize("use_native", [True, False])
def test_big_int64_survives_null_column(tmp_path, use_native, monkeypatch):
    """An int64 above 2^53 must round-trip exactly even when the column
    also contains NULLs (the pandas fallback must not detour through
    float64)."""
    big = 9007199254740993  # 2^53 + 1
    f = tmp_path / "t.tbl"
    f.write_text(f"x|{big}|1.00|1994-01-01|\ny||1.00|1994-01-01|\n")
    b = _scan(str(f), use_native, monkeypatch)
    a = b.column("a")
    vals = np.asarray(a.values)[:2]
    assert int(vals[0]) == big
    np.testing.assert_array_equal(
        np.asarray(a.validity)[:2], [True, False])


def _write_parquet(tmp_path):
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq
    from decimal import Decimal as DEC

    t = pa.table({
        "k": pa.array(["x", "y", None, "z"], pa.string()),
        "a": pa.array([1, None, 3, 4], pa.int64()),
        "d": pa.array([DEC("1.50"), DEC("2.25"), None, DEC("4.00")],
                      pa.decimal128(12, 2)),
        "dt": pa.array([8766, 8767, 8768, None], pa.int32()).cast(
            pa.date32()),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)
    return path


def test_parquet_nulls_surface_validity(tmp_path):
    """Parquet NULLs: non-string columns carry validity=False (the
    physical fill value is masked), utf8 NULLs store "" — byte-for-byte
    the text scanners' convention."""
    from ballista_tpu.io import ParquetSource

    src = ParquetSource(_write_parquet(tmp_path))
    batches = list(src.scan(0))
    assert len(batches) == 1
    b = batches[0]
    assert int(b.num_rows) == 4

    a = b.column("a")
    assert a.validity is not None
    np.testing.assert_array_equal(
        np.asarray(a.validity)[:4], [True, False, True, True])

    d = b.column("d")
    assert d.validity is not None
    np.testing.assert_array_equal(
        np.asarray(d.validity)[:4], [True, True, False, True])
    # valid decimals decode exactly (the NULL's fill never leaks out)
    decoded = d.to_numpy_logical(np.asarray(b.selection))
    np.testing.assert_allclose(decoded[[0, 1, 3]], [1.50, 2.25, 4.00])
    assert np.isnan(decoded[2])

    dt = b.column("dt")
    assert dt.validity is not None
    np.testing.assert_array_equal(
        np.asarray(dt.validity)[:4], [True, True, True, False])

    k = b.column("k")
    assert k.validity is None  # utf8: "" is a value, never NULL
    np.testing.assert_array_equal(
        k.to_numpy_logical(np.asarray(b.selection)), ["x", "y", "", "z"])


def test_parquet_big_int64_survives_null_column(tmp_path):
    """Same invariant as the text path's test above: an int64 above 2^53
    must round-trip exactly even when the column also has NULLs (the
    arrow->numpy conversion must not detour through float64)."""
    pa = pytest.importorskip("pyarrow")
    import pyarrow.parquet as pq

    from ballista_tpu.io import ParquetSource

    big = 9007199254740993  # 2^53 + 1
    path = str(tmp_path / "big.parquet")
    pq.write_table(pa.table({"a": pa.array([big, None], pa.int64())}), path)
    b = list(ParquetSource(path).scan(0))[0]
    a = b.column("a")
    assert int(np.asarray(a.values)[0]) == big
    np.testing.assert_array_equal(np.asarray(a.validity)[:2], [True, False])


def test_parquet_null_aware_aggregation(tmp_path):
    """count(a) skips the parquet NULL row, sum ignores it — identical
    to the delimited end-to-end case below."""
    from ballista_tpu import col, sum_, count
    from ballista_tpu.execution import collect
    from ballista_tpu.io import ParquetSource
    from ballista_tpu.logical import LogicalPlanBuilder

    src = ParquetSource(_write_parquet(tmp_path))
    plan = LogicalPlanBuilder.scan("t", src).aggregate(
        [], [sum_(col("a")).alias("s"), count(col("a")).alias("n"),
             count().alias("all")]
    ).build()
    out = collect(plan)
    assert int(out["s"][0]) == 8  # 1+3+4
    assert int(out["n"][0]) == 3
    assert int(out["all"][0]) == 4


@pytest.mark.parametrize("use_native", [True, False])
def test_null_aware_aggregation_over_scan(tmp_path, use_native, monkeypatch):
    """count(a) skips the NULL row; sum ignores it (end-to-end)."""
    if use_native and not native.available():
        pytest.skip("native scanner not built")
    from ballista_tpu import col, sum_, count
    from ballista_tpu.logical import LogicalPlanBuilder
    from ballista_tpu.execution import collect

    src = TblSource(_write(tmp_path), SCHEMA)
    if not use_native:
        monkeypatch.setattr(type(src), "_use_native", lambda self: False)
    plan = LogicalPlanBuilder.scan("t", src).aggregate(
        [], [sum_(col("a")).alias("s"), count(col("a")).alias("n"),
             count().alias("all")]
    ).build()
    out = collect(plan)
    assert int(out["s"][0]) == 8  # 1+3+4
    assert int(out["n"][0]) == 3
    assert int(out["all"][0]) == 4
