"""Process-level integration: the real scheduler and executor BINARIES
(separate processes, real gRPC control plane, real socket data plane)
serve a SQL query end to end — the role docker-compose integration
plays for the reference (dev/integration-tests.sh), without docker."""

import os
import re
import signal
import subprocess

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from tests.procutil import (http_get, spawn_module as _spawn,
                            wait_healthz)


def _health_port(proc) -> int:
    line = proc.wait_for(lambda ln: "health plane on" in ln)
    m = re.search(r"health plane on [^:]+:(\d+)", line)
    assert m, f"no health port in output: {line!r}"
    return int(m.group(1))


def test_binaries_end_to_end(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = []
    try:
        sched = _spawn(["ballista_tpu.distributed.scheduler_main",
                        "--bind-host", "localhost", "--port", "0"], env)
        procs.append(sched)
        line = sched.wait_for(lambda ln: "listening on" in ln)
        m = re.search(r"listening on [^:]+:(\d+)", line)
        assert m, f"no port in scheduler output: {line!r}"
        port = int(m.group(1))
        # readiness via the health plane, not sleeps/log scraping
        sched_health = _health_port(sched)
        assert wait_healthz(sched_health)["role"] == "scheduler"

        exec_health = []
        for i in range(2):
            e = _spawn(["ballista_tpu.distributed.executor_main",
                        "--scheduler-host", "localhost",
                        "--scheduler-port", str(port),
                        "--work-dir", str(tmp_path / f"w{i}"),
                        "--num-devices", "1"], env)
            procs.append(e)
            exec_health.append(_health_port(e))
        for hp in exec_health:
            assert wait_healthz(hp)["role"] == "executor"

        data = tmp_path / "t.tbl"
        data.write_text("".join(f"{i}|k{i % 3}|\n" for i in range(90)))

        from ballista_tpu.client import BallistaContext
        from ballista_tpu.io import TblSource

        ctx = BallistaContext.remote("localhost", port)
        ctx.register_source(
            "t", TblSource(str(data), schema(("a", Int64), ("c", Utf8)))
        )
        got = ctx.sql(
            "select c, sum(a) as s, count(*) as n from t group by c order by c"
        ).collect()
        a = np.arange(90)
        for i in range(3):
            m_ = a % 3 == i
            assert got["c"][i] == f"k{i}"
            assert int(got["s"][i]) == int(a[m_].sum())
            assert int(got["n"][i]) == int(m_.sum())

        # the REAL binaries serve the health plane: executor heartbeat
        # gauges aggregated on the scheduler, job counters advanced
        text = http_get(sched_health, "/metrics")
        assert "ballista_executors_live 2" in text
        assert "ballista_jobs_completed_total 1" in text
        assert "ballista_executor_rss_bytes{" in text
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_flight_frontend_against_real_cluster(tmp_path):
    """A FOREIGN Arrow Flight client (stock pyarrow, no ballista code)
    runs DDL + a query against the scheduler binary's --flight-port:
    the reference JDBC driver's jdbc:arrow://host:port flow, end to end
    through the real cluster (scheduler + executor processes)."""
    paflight = pytest.importorskip("pyarrow.flight")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = []
    try:
        sched = _spawn(["ballista_tpu.distributed.scheduler_main",
                        "--bind-host", "localhost", "--port", "0",
                        "--flight-port", "0"], env)
        procs.append(sched)
        line = sched.wait_for(lambda ln: "listening on" in ln)
        m = re.search(r"listening on [^:]+:(\d+)", line)
        assert m, f"no port in scheduler output: {line!r}"
        fline = sched.wait_for(lambda ln: "Flight SQL endpoint on" in ln)
        fm = re.search(r"Flight SQL endpoint on [^:]+:(\d+)", fline)
        assert fm, f"no flight port in scheduler output: {fline!r}"
        fport = int(fm.group(1))

        e = _spawn(["ballista_tpu.distributed.executor_main",
                    "--scheduler-host", "localhost",
                    "--scheduler-port", m.group(1),
                    "--work-dir", str(tmp_path / "w0"),
                    "--num-devices", "1"], env)
        procs.append(e)
        wait_healthz(_health_port(e))

        data = tmp_path / "t.tbl"
        data.write_text("".join(f"{i}|k{i % 3}|\n" for i in range(60)))

        client = paflight.connect(f"grpc://localhost:{fport}")
        ddl = (f"CREATE EXTERNAL TABLE t (a BIGINT, c VARCHAR) "
               f"STORED AS TBL LOCATION '{data}'")
        status = client.do_get(paflight.Ticket(ddl.encode())).read_all()
        assert status["status"][0].as_py() == "OK"
        got = client.do_get(paflight.Ticket(
            b"select c, sum(a) as s from t group by c order by c"
        )).read_all().to_pandas()
        a = np.arange(60)
        assert list(got["c"]) == ["k0", "k1", "k2"]
        for i in range(3):
            assert int(got["s"][i]) == int(a[a % 3 == i].sum())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
