"""Process-level integration: the real scheduler and executor BINARIES
(separate processes, real gRPC control plane, real socket data plane)
serve a SQL query end to end — the role docker-compose integration
plays for the reference (dev/integration-tests.sh), without docker.
With ``BALLISTA_PROFILE`` on the scheduler the run also gates the
distributed profiler: executors ship per-task profile windows over the
wire and the scheduler merges them into one Chrome-trace artifact with
a REAL process track per executor pid."""

import json
import os
import re
import signal
import subprocess
import time

import numpy as np
import pytest

from ballista_tpu import schema, Int64, Utf8
from tests.procutil import (http_get, spawn_module as _spawn,
                            wait_healthz)


def _health_port(proc) -> int:
    line = proc.wait_for(lambda ln: "health plane on" in ln)
    m = re.search(r"health plane on [^:]+:(\d+)", line)
    assert m, f"no health port in output: {line!r}"
    return int(m.group(1))


def test_binaries_end_to_end(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    # distributed profiler: the scheduler merges its own spans with the
    # task profile windows the executor binaries ship over the wire
    profile_dir = tmp_path / "profiles"
    sched_env = dict(env)
    sched_env["BALLISTA_PROFILE"] = str(profile_dir)

    procs = []
    try:
        sched = _spawn(["ballista_tpu.distributed.scheduler_main",
                        "--bind-host", "localhost", "--port", "0"],
                       sched_env)
        procs.append(sched)
        line = sched.wait_for(lambda ln: "listening on" in ln)
        m = re.search(r"listening on [^:]+:(\d+)", line)
        assert m, f"no port in scheduler output: {line!r}"
        port = int(m.group(1))
        # readiness via the health plane, not sleeps/log scraping
        sched_health = _health_port(sched)
        assert wait_healthz(sched_health)["role"] == "scheduler"

        exec_health = []
        for i in range(2):
            e = _spawn(["ballista_tpu.distributed.executor_main",
                        "--scheduler-host", "localhost",
                        "--scheduler-port", str(port),
                        "--work-dir", str(tmp_path / f"w{i}"),
                        "--concurrent-tasks", "1",
                        "--num-devices", "1"], env)
            procs.append(e)
            exec_health.append(_health_port(e))
        for hp in exec_health:
            assert wait_healthz(hp)["role"] == "executor"

        # a DIRECTORY of part files -> multi-partition scan stage, so
        # with one task slot per executor both executors serve tasks
        data = tmp_path / "t"
        data.mkdir()
        for p in range(6):
            (data / f"part-{p}.tbl").write_text(
                "".join(f"{i}|k{i % 3}|\n"
                        for i in range(p * 15, (p + 1) * 15)))

        from ballista_tpu.client import BallistaContext
        from ballista_tpu.io import TblSource

        ctx = BallistaContext.remote("localhost", port)
        ctx.register_source(
            "t", TblSource(str(data), schema(("a", Int64), ("c", Utf8)))
        )
        got = ctx.sql(
            "select c, sum(a) as s, count(*) as n from t group by c order by c"
        ).collect()
        a = np.arange(90)
        for i in range(3):
            m_ = a % 3 == i
            assert got["c"][i] == f"k{i}"
            assert int(got["s"][i]) == int(a[m_].sum())
            assert int(got["n"][i]) == int(m_.sum())

        # the REAL binaries serve the health plane: executor heartbeat
        # gauges aggregated on the scheduler, job counters advanced
        text = http_get(sched_health, "/metrics")
        assert "ballista_executors_live 2" in text
        assert "ballista_jobs_completed_total 1" in text
        assert "ballista_executor_rss_bytes{" in text

        # merged per-job artifact: one file, valid Chrome-trace JSON,
        # with the scheduler track and BOTH executor processes (real
        # distinct pids) as their own tracks, task flow arrows included.
        # Job completion is published to the client BEFORE the
        # scheduler's terminal hook writes the artifact — poll briefly.
        deadline = time.time() + 30
        files = []
        while time.time() < deadline and not files:
            files = list(profile_dir.glob("ballista-profile-job-*.json"))
            if not files:
                time.sleep(0.2)
        assert len(files) == 1, files
        art = json.load(open(files[0]))
        assert art["traceEvents"] and art.get("displayTimeUnit") == "ms"
        tracks = [ev["args"]["name"] for ev in art["traceEvents"]
                  if ev.get("ph") == "M" and ev["name"] == "process_name"]
        assert any(t.startswith("scheduler") for t in tracks), tracks
        exec_tracks = [t for t in tracks if t.startswith("executor ")]
        assert len(exec_tracks) >= 2, tracks
        # distinct OS pids on the executor tracks (real processes)
        exec_pids = {re.search(r"pid (\d+)", t).group(1)
                     for t in exec_tracks}
        assert len(exec_pids) >= 2, tracks
        assert any(ev.get("ph") == "s" for ev in art["traceEvents"])
        assert set(art["lanes"]) and art["wall_seconds"] > 0
        # /debug/profile/<job_id> serves the same artifact from the
        # scheduler binary's health plane
        dbg = json.loads(http_get(sched_health, "/debug/queries"))
        job_entries = [q for q in dbg["queries"] if "job_id" in q]
        assert job_entries and job_entries[-1].get("plan_digest")
        served = json.loads(http_get(
            sched_health, f"/debug/profile/{job_entries[-1]['job_id']}"))
        assert served["distributed"]["job_id"] == \
            job_entries[-1]["job_id"]
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_flight_frontend_against_real_cluster(tmp_path):
    """A FOREIGN Arrow Flight client (stock pyarrow, no ballista code)
    runs DDL + a query against the scheduler binary's --flight-port:
    the reference JDBC driver's jdbc:arrow://host:port flow, end to end
    through the real cluster (scheduler + executor processes)."""
    paflight = pytest.importorskip("pyarrow.flight")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    procs = []
    try:
        sched = _spawn(["ballista_tpu.distributed.scheduler_main",
                        "--bind-host", "localhost", "--port", "0",
                        "--flight-port", "0"], env)
        procs.append(sched)
        line = sched.wait_for(lambda ln: "listening on" in ln)
        m = re.search(r"listening on [^:]+:(\d+)", line)
        assert m, f"no port in scheduler output: {line!r}"
        fline = sched.wait_for(lambda ln: "Flight SQL endpoint on" in ln)
        fm = re.search(r"Flight SQL endpoint on [^:]+:(\d+)", fline)
        assert fm, f"no flight port in scheduler output: {fline!r}"
        fport = int(fm.group(1))

        e = _spawn(["ballista_tpu.distributed.executor_main",
                    "--scheduler-host", "localhost",
                    "--scheduler-port", m.group(1),
                    "--work-dir", str(tmp_path / "w0"),
                    "--num-devices", "1"], env)
        procs.append(e)
        wait_healthz(_health_port(e))

        data = tmp_path / "t.tbl"
        data.write_text("".join(f"{i}|k{i % 3}|\n" for i in range(60)))

        client = paflight.connect(f"grpc://localhost:{fport}")
        ddl = (f"CREATE EXTERNAL TABLE t (a BIGINT, c VARCHAR) "
               f"STORED AS TBL LOCATION '{data}'")
        status = client.do_get(paflight.Ticket(ddl.encode())).read_all()
        assert status["status"][0].as_py() == "OK"
        got = client.do_get(paflight.Ticket(
            b"select c, sum(a) as s from t group by c order by c"
        )).read_all().to_pandas()
        a = np.arange(60)
        assert list(got["c"]) == ["k0", "k1", "k2"]
        for i in range(3):
            assert int(got["s"][i]) == int(a[a % 3 == i].sum())
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
